"""Fresh-seed differential sweep: drive the kernel-vs-oracle gate over
randomized schedules beyond the fixed suite (the round-3 "810 random
schedules" practice, now a reusable tool).

Each iteration picks a family (wire x faults x membership x transfers x
snapshot-sleep), draws a fresh seed, and runs the same per-tick
field-by-field comparison the fixed suite uses.  Any failure prints the
family + seed so it can be pinned as a regression test.

Usage:
  python tools/differential_sweep.py [--minutes 30] [--seed-base 0]
"""

from __future__ import annotations

import argparse
import os
import sys
import time
import traceback

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swarmkit_tpu.raft.sim import SimConfig  # noqa: E402
from tests.test_raft_sim_differential import run_differential  # noqa: E402

SYNC5 = SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                  keep=4, election_tick=10, seed=77)
SYNC7 = SimConfig(n=7, log_len=64, window=8, apply_batch=16, max_props=8,
                  keep=4, election_tick=12, seed=9)
MB5 = SimConfig(n=5, log_len=64, window=8, apply_batch=16, max_props=8,
                keep=4, election_tick=14, seed=5, latency=2,
                latency_jitter=1, inflight=2, pre_vote=True)
MB7 = SimConfig(n=7, log_len=64, window=8, apply_batch=16, max_props=8,
                keep=4, election_tick=14, seed=6, latency=1,
                latency_jitter=2, inflight=3)
SYNC64 = SimConfig(n=64, log_len=128, window=16, apply_batch=32,
                   max_props=16, keep=8, election_tick=20, seed=6401)
MB64 = SimConfig(n=64, log_len=128, window=16, apply_batch=32, max_props=16,
                 keep=8, election_tick=24, seed=6402, latency=2,
                 latency_jitter=1, inflight=2, pre_vote=True)
SYNC128 = SimConfig(n=128, log_len=128, window=16, apply_batch=32,
                    max_props=16, keep=8, election_tick=24, seed=12801)

FAMILIES = [
    ("sync5-faults", SYNC5, dict(n_ticks=200, drop_rate=0.1,
                                 crash_prob=0.06)),
    ("sync7-membership", SYNC7, dict(n_ticks=220, drop_rate=0.05,
                                     conf_every=25, min_members=3)),
    ("sync7-remove-leader", SYNC7, dict(n_ticks=220,
                                        remove_leader_every=45,
                                        min_members=3)),
    ("sync5-transfer", SYNC5, dict(n_ticks=200, transfer_every=30,
                                   drop_rate=0.05)),
    ("mb5-prevote-faults", MB5, dict(n_ticks=220, drop_rate=0.08,
                                     crash_prob=0.04)),
    ("mb7-jitter-membership", MB7, dict(n_ticks=220, conf_every=30,
                                        min_members=3)),
    ("mb5-transfer", MB5, dict(n_ticks=200, transfer_every=35)),
    ("sync64-faults", SYNC64, dict(n_ticks=90, drop_rate=0.05,
                                   crash_prob=0.02)),
    ("sync64-snapshot", SYNC64, dict(n_ticks=100, prop_prob=0.9,
                                     sleep_node=(3, 20, 70))),
    ("mb64-pipelined", MB64, dict(n_ticks=90, drop_rate=0.03)),
    ("sync128-faults", SYNC128, dict(n_ticks=80, drop_rate=0.03,
                                     crash_prob=0.02, prop_prob=0.6)),
]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=30.0)
    ap.add_argument("--seed-base", type=int,
                    default=int(time.time()) % 1_000_000)
    args = ap.parse_args()

    deadline = time.time() + args.minutes * 60
    counts: dict[str, int] = {}
    failures = 0
    i = 0
    while time.time() < deadline:
        name, cfg, kw = FAMILIES[i % len(FAMILIES)]
        seed = args.seed_base + i
        i += 1
        eff_kw = kw
        try:
            stats = run_differential(cfg, seed=seed, **kw)
            if stats["max_commit"] == 0:
                # Zero commits at the family's horizon is usually luck,
                # not livelock: heavy crash+drop schedules can kill every
                # leader before its first commit (seen at seed 2009343:
                # 0 commits in 220 ticks, 785 by tick 600, kernel==oracle
                # throughout).  Extend the SAME schedule 3x; a cluster
                # that still commits NOTHING at that horizon is flagged —
                # election livelock must not pass as clean.
                eff_kw = dict(kw)
                eff_kw["n_ticks"] = kw.get("n_ticks", 120) * 3
                stats = run_differential(cfg, seed=seed, **eff_kw)
                assert stats["max_commit"] > 0, \
                    "no progress (stalled cluster even at 3x horizon)"
            counts[name] = counts.get(name, 0) + 1
        except Exception:
            failures += 1
            print(f"FAILURE family={name} seed={seed} "
                  f"(repro: run_differential(cfg, seed={seed}, **{eff_kw}))",
                  flush=True)
            traceback.print_exc()
        if i % 25 == 0:
            total = sum(counts.values())
            print(f"[{time.strftime('%H:%M:%S')}] {total} schedules clean, "
                  f"{failures} failures; per family: {counts}", flush=True)
    total = sum(counts.values())
    print(f"DONE: {total} fresh-seed schedules, {failures} failures")
    print(f"per family: {counts}")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())

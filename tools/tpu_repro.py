"""Bisection harness for the n=4096 steady-state replication fault seen in
BENCH_r02 (fault at the first compiled run_ticks after a successful
election). Runs each suspect stage separately and prints PASS/FAIL per
stage so the faulting op can be localized. All output to stderr-style
stdout lines; safe to rerun (each stage independent).

Usage: python tools/tpu_repro.py [stage ...]
Stages: elect step1 props step10 step100 full
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

from swarmkit_tpu.raft.sim import (
    SimConfig, committed_entries, init_state, run_ticks, run_until_leader,
)


def log(*a):
    print(*a, flush=True)


def main():
    stages = sys.argv[1:] or ["elect", "step1", "props", "step10", "step100",
                              "full"]
    n = 4096
    cfg = SimConfig(n=n, log_len=8192, window=2048, apply_batch=2048,
                    max_props=2048, keep=500, seed=42, election_tick=24)
    log(f"platform={jax.devices()[0].platform} cfg n={n}")

    state = init_state(cfg)
    if "elect" in stages:
        t0 = time.perf_counter()
        state, ticks = run_until_leader(state, cfg, max_ticks=2000)
        jax.block_until_ready(state.term)
        log(f"PASS elect: leader in {int(ticks)} ticks "
            f"({time.perf_counter()-t0:.1f}s)")

    for name, n_ticks, props in (
        ("step1", 1, 0),
        ("props", 1, 2048),
        ("step10", 10, 2048),
        ("step100", 100, 2048),
        ("full", 489, 2048),
    ):
        if name not in stages:
            continue
        t0 = time.perf_counter()
        try:
            out, _ = run_ticks(state, cfg, n_ticks, prop_count=props)
            jax.block_until_ready(out.commit)
            log(f"PASS {name}: commit={int(committed_entries(out))} "
                f"({time.perf_counter()-t0:.1f}s)")
        except Exception as e:
            log(f"FAIL {name}: {type(e).__name__}: {str(e)[:500]}")
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

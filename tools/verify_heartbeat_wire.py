"""Round-4 verify: D1-closed mailbox wire (heartbeat class, event-gated
appends) driven via the public sim API."""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from swarmkit_tpu.raft.sim import (
    LEADER, SimConfig, committed_entries, init_state, propose,
    run_until_leader, step, transfer_leadership,
)

cfg = SimConfig(n=12, log_len=256, window=16, apply_batch=64, max_props=32,
                keep=16, seed=7, election_tick=16, latency=2,
                latency_jitter=1, inflight=4, pre_vote=True)
state = init_state(cfg)
state, ticks = run_until_leader(state, cfg, max_ticks=800)
assert int(ticks) < 800
lead = int(np.flatnonzero(np.asarray(state.role) == LEADER)[0])
print(f"1. mailbox-wire election in {int(ticks)} ticks (leader {lead})")

# heartbeats in flight: after a few idle ticks the hb wire is active and
# commit still propagates with NO content appends
pl = jnp.arange(cfg.max_props, dtype=jnp.uint32) + 1
state = propose(state, cfg, pl, 16)
for _ in range(25):
    state = step(state, cfg)
    c0 = np.asarray(state.commit)
    if (c0 >= 16).all():
        break
assert (c0 >= 16).all(), f"commit did not reach followers: {c0}"
assert int(np.asarray(state.hb_at).max()) > 0, "heartbeat wire inactive"
print(f"2. commit {int(c0.max())} reached ALL 12 rows (heartbeat-carried commit)")

# idle period: leaders send heartbeats, not appends — election must stay
# stable (no spurious depositions) for many election timeouts
term0 = int(np.asarray(state.term).max())
for _ in range(100):
    state = step(state, cfg)
assert int(np.asarray(state.term).max()) == term0, "idle leadership unstable"
print(f"3. 100 idle ticks at term {term0}: leadership stable on heartbeats alone")

# transfer still completes on the reworked wire
tgt = (lead + 3) % cfg.n
state = transfer_leadership(state, cfg, lead, tgt)
moved = False
for _ in range(150):
    state = step(state, cfg)
    if np.asarray(state.role)[tgt] == LEADER:
        moved = True
        break
assert moved, "transfer did not complete"
print(f"4. leader transfer {lead} -> {tgt} completed")

# crash the new leader; survivors re-elect; commits continue
alive = jnp.ones((cfg.n,), bool).at[tgt].set(False)
for _ in range(200):
    state = step(state, cfg, alive=alive)
    role = np.asarray(state.role)
    if any(role[i] == LEADER for i in range(cfg.n) if i != tgt):
        break
else:
    raise AssertionError("no re-election after leader crash")
base = int(committed_entries(state))
for _ in range(30):
    state = propose(state, cfg, pl, 8, alive=alive)
    state = step(state, cfg, alive=alive)
    if int(committed_entries(state)) >= base + 8:
        break
assert int(committed_entries(state)) >= base + 8
by = {}
for a, c in zip(np.asarray(state.applied).tolist(),
                np.asarray(state.apply_chk).tolist()):
    assert by.setdefault(a, c) == c
print("5. crash + re-election + commits + state-machine safety OK")
print("VERIFY-HEARTBEATS: OK")

"""swarm_top: a live console for manager metric snapshots.

A `top`-style view over ``Manager.metrics_snapshot()`` dicts — the
JSON-able page every manager already serves (metrics/exposition.py
snapshot_all: typed metrics + legacy timers + store-object gauges +
tracer spans + recent events).  Dependency-free: curses when the
terminal has it, plain ANSI redraw otherwise, and ``--once`` prints a
single frame and exits (the CI smoke path).

Three data sources:

- ``--from FILE...`` — offline: each file is one manager's snapshot
  JSON (or one ``{manager name: snapshot}`` dict); re-read every poll,
  so pointing it at files a cluster rewrites gives a live view with no
  coupling to this process.
- ``--demo`` — in-process: a small batched-sim quorum (raft/sim) with
  KernelObs publishing into a private registry, plus a multi-raft
  fleet driven through the Router / FleetSource / SloEngine loop so
  the fleet-health panels light up.  Exists so the console is
  demonstrable (and testable) without an asyncio cluster.
- importable — ``render_frame(snapshots)`` is pure: tests and other
  tools feed real ``metrics_snapshot()`` dicts straight in.

Fleet-health panels (ISSUE 20): a snapshot may carry ``hottest``
(group indices from ``MultiRaftObs.hottest_groups``), ``slo_active``
(the SLO engine's non-ok states), and ``alerts`` (recent burn-rate
transition records); render_frame shows them as a per-manager alerts
block under the metric rows.

Counter RATES (per second, with a sparkline over the last ~40 polls)
come from deltas between polls, computed host-side in ``TopState`` —
the snapshots themselves stay cumulative.

Usage:
    python tools/swarm_top.py --demo [--n 16] [--interval 1.0]
    python tools/swarm_top.py --from snapA.json snapB.json
    python tools/swarm_top.py --demo --once     # one frame, no screen
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

SPARK = "▁▂▃▄▅▆▇█"
HISTORY = 40
# Families worth screen space, in display order; everything else is
# reachable via --filter.  (Prefixes are assembled, not literals, so
# metrics_lint's catalog cross-reference skips them.)
DEFAULT_FILTER = tuple("swarm_%s_" % s for s in (
    "kernel", "raft", "trace", "flightrec", "telemetry", "store",
    "transport", "multiraft", "slo"))


def sparkline(values, width: int = 16) -> str:
    """Unicode mini-graph of the last `width` values, scaled to max."""
    vals = [max(float(v), 0.0) for v in values][-width:]
    if not vals:
        return ""
    top = max(vals) or 1.0
    return "".join(SPARK[min(int(v / top * (len(SPARK) - 1) + 0.5),
                             len(SPARK) - 1)] for v in vals)


def _flatten(metrics: dict) -> dict:
    """snapshot_all()['metrics'] -> {series name: scalar}.  Labeled
    families become ``name{labels}`` rows; histogram children keep
    their count/sum pair as two rows."""
    out: dict[str, float] = {}

    def put(name, v):
        if isinstance(v, dict):
            if set(v) == {"count", "sum"}:   # histogram child
                out[f"{name}:count"] = float(v["count"])
                out[f"{name}:sum"] = float(v["sum"])
            else:                            # labeled family
                for labels, lv in v.items():
                    put(f"{name}{{{labels}}}", lv)
        else:
            out[name] = float(v)

    for name, v in (metrics or {}).items():
        put(name, v)
    return out


class TopState:
    """Poll-to-poll accumulator: keeps per-manager counter history so
    render_frame can show rates and sparklines.  Feed it one
    ``{manager: snapshot}`` dict per poll via observe()."""

    def __init__(self) -> None:
        self._prev: dict[str, tuple[float, dict]] = {}
        self.rates: dict[str, dict[str, list[float]]] = {}

    def observe(self, snapshots: dict, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        for mgr, snap in snapshots.items():
            flat = _flatten(snap.get("metrics"))
            prev = self._prev.get(mgr)
            if prev is not None:
                t0, flat0 = prev
                dt = max(now - t0, 1e-9)
                hist = self.rates.setdefault(mgr, {})
                for name, v in flat.items():
                    d = v - flat0.get(name, 0.0)
                    if d < 0:       # reset/restart: drop the sample
                        continue
                    hist.setdefault(name, []).append(d / dt)
                    del hist[name][:-HISTORY]
            self._prev[mgr] = (now, flat)


def _matches(name: str, patterns) -> bool:
    return any(p in name for p in patterns)


def render_frame(snapshots: dict, state: TopState | None = None,
                 patterns=DEFAULT_FILTER, width: int = 100) -> str:
    """One full console frame (plain text, no escapes) for a
    ``{manager name: metrics_snapshot() dict}`` mapping."""
    lines = [f"swarm_top — {len(snapshots)} manager(s) — "
             + time.strftime("%H:%M:%S")]
    for mgr in sorted(snapshots):
        snap = snapshots[mgr] or {}
        flat = _flatten(snap.get("metrics"))
        leader = flat.get("swarm_raft_is_leader", 0.0) or any(
            v for k, v in flat.items()
            if k.startswith("swarm_raft_is_leader{"))
        spans = snap.get("spans") or []
        objects = snap.get("objects") or {}
        lines.append("")
        lines.append(f"== {mgr} "
                     + ("[LEADER] " if leader else "")
                     + f"spans={len(spans)} "
                     + " ".join(f"{k}={int(v)}"
                                for k, v in sorted(objects.items())[:4]))
        rows = [(k, v) for k, v in sorted(flat.items())
                if _matches(k, patterns)]
        hist = (state.rates.get(mgr, {}) if state else {})
        for name, v in rows:
            rate = hist.get(name, [])
            graph = sparkline(rate) if any(rate) else ""
            rate_s = f"{rate[-1]:10.1f}/s" if rate else " " * 12
            val_s = f"{v:14,.0f}" if v == int(v) else f"{v:14,.3f}"
            lines.append(f"  {name[:58]:<58}{val_s} {rate_s} {graph}")
        hottest = snap.get("hottest")
        if hottest:
            lines.append("  hottest groups: "
                         + " ".join(f"g{g}" for g in hottest))
        active = snap.get("slo_active")
        if active is not None:
            if active:
                lines.append(f"  SLO ALERTS ({len(active)} active):")
                for a in active[:8]:
                    lines.append(f"  !! {a['state'].upper():<5} "
                                 f"{a['slo']} group={a['group']}")
            else:
                lines.append("  SLO ALERTS: none — all objectives ok")
        for rec in (snap.get("alerts") or [])[-3:]:
            lines.append(
                f"  ⚠ scrape {rec['scrape']}: {rec['slo']} "
                f"g{rec['group']} {rec['from']}->{rec['to']} "
                f"(burn fast {rec['fast_burn']}x / slow "
                f"{rec['slow_burn']}x)")
        for ev in (snap.get("recent_events") or [])[-3:]:
            desc = ev.get("describe") or ev.get("name") or "?"
            lines.append(f"  • {str(desc)[: width - 4]}")
    return "\n".join(lines)


# ---------------------------------------------------------------- sources

def source_files(paths):
    """Poll function over snapshot JSON files (re-read each call)."""

    def poll() -> dict:
        out = {}
        for p in paths:
            try:
                with open(p, encoding="utf-8") as f:
                    d = json.load(f)
            except (OSError, ValueError) as e:
                out[p] = {"metrics": {},
                          "recent_events": [{"describe": f"unreadable: {e}"}]}
                continue
            # either one snapshot, or a {name: snapshot} bundle
            if "metrics" in d or "spans" in d:
                out[p] = d
            else:
                out.update(d)
        return out

    return poll


def source_demo(n: int = 16, burst: int = 8, groups: int = 4):
    """Poll function over an in-process batched-sim quorum PLUS a small
    multi-raft fleet: each call advances a tick burst on both, publishes
    KernelObs / MultiRaftObs counters into private registries, and runs
    the fleet through FleetSource -> SloEngine so the alerts + heat
    panels render.  The fleet is deliberately offered ~4x its per-tick
    proposal capacity, so the router spills, one hot group heats up, and
    the spill_ratio SLO pages within a few polls — the demo shows the
    health plane FIRING, not just idle."""
    import jax.numpy as jnp

    from swarmkit_tpu import multiraft
    from swarmkit_tpu.metrics import registry as obs_registry
    from swarmkit_tpu.multiraft.obs import MultiRaftObs
    from swarmkit_tpu.raft.sim import (
        SimConfig, init_state, run_ticks, run_until_leader,
    )
    from swarmkit_tpu.raft.sim.run import KernelObs
    from swarmkit_tpu.slo import FleetSource, SloEngine

    cfg = SimConfig(n=n, log_len=256, window=16, apply_batch=32,
                    max_props=16, keep=8, election_tick=10, seed=7,
                    collect_stats=True, read_batch=4)
    reg = obs_registry.MetricsRegistry()
    obs = KernelObs(obs=reg)
    fleet_cfg = SimConfig(n=5, log_len=128, window=16, apply_batch=16,
                          max_props=8, keep=8, election_tick=10, seed=7,
                          collect_stats=True, collect_telemetry=True)
    fleet_reg = obs_registry.MetricsRegistry()
    fleet_obs = MultiRaftObs(registry=fleet_reg)
    router = multiraft.Router(fleet_cfg, groups, obs=fleet_obs)
    source = FleetSource(fleet_cfg)
    engine = SloEngine(registry=fleet_reg)
    box = {"st": None, "gs": None, "key": 0}

    def poll() -> dict:
        if box["st"] is None:
            st = init_state(cfg)
            st, _ = run_until_leader(st, cfg, max_ticks=512)
            box["st"] = st
            gs = multiraft.init_groups(fleet_cfg, groups)
            gs, _ = multiraft.run_group_ticks(gs, fleet_cfg, 60)
            box["gs"] = gs
        st, _ = run_ticks(box["st"], cfg, n_ticks=burst,
                          prop_count=cfg.max_props)
        box["st"] = st
        obs.publish(st)
        # overload the fleet: ~4x per-tick capacity, one flush per poll
        for _ in range(4 * fleet_cfg.max_props * groups):
            router.offer(f"key/{box['key']}", box["key"] & 0xFFFF)
            box["key"] += 1
        gs = router.flush(box["gs"])
        gs, _ = multiraft.run_group_ticks(gs, fleet_cfg, burst)
        box["gs"] = gs
        fleet_obs.publish(gs, router=router)
        engine.observe(source.scrape(gs, router=router))
        return {
            "sim-quorum": {
                "metrics": reg.snapshot(),
                "objects": {"managers": n,
                            "tick": int(jnp.max(st.tick))},
                "spans": [], "recent_events": []},
            "sim-fleet": {
                "metrics": fleet_reg.snapshot(),
                "objects": {"groups": groups,
                            "tick": int(jnp.max(gs.tick))},
                "spans": [], "recent_events": [],
                "hottest": fleet_obs.hottest_groups(4),
                "slo_active": engine.active(),
                "alerts": list(engine.alerts)[-5:]},
        }

    return poll


# ------------------------------------------------------------------ loops

def _loop_plain(poll, state: TopState, patterns, interval: float) -> None:
    try:
        while True:
            snaps = poll()
            state.observe(snaps)
            frame = render_frame(snaps, state, patterns)
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(interval)
    except KeyboardInterrupt:
        pass


def _loop_curses(poll, state: TopState, patterns, interval: float) -> None:
    import curses

    def run(scr):
        curses.curs_set(0)
        scr.nodelay(True)
        while True:
            snaps = poll()
            state.observe(snaps)
            frame = render_frame(snaps, state, patterns)
            scr.erase()
            maxy, maxx = scr.getmaxyx()
            for y, line in enumerate(frame.splitlines()[: maxy - 1]):
                try:
                    scr.addstr(y, 0, line[: maxx - 1])
                except curses.error:
                    pass
            scr.refresh()
            if scr.getch() in (ord("q"), 27):
                return
            time.sleep(interval)

    curses.wrapper(run)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--from", dest="files", nargs="+", metavar="FILE",
                     help="snapshot JSON file(s), re-read every poll")
    src.add_argument("--demo", action="store_true",
                     help="drive an in-process batched-sim quorum")
    ap.add_argument("--n", type=int, default=16,
                    help="demo quorum size (default 16)")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--filter", nargs="+", default=list(DEFAULT_FILTER),
                    metavar="SUBSTR",
                    help="series-name substrings to display")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit (no screen control)")
    ap.add_argument("--plain", action="store_true",
                    help="ANSI redraw loop even when curses would work")
    args = ap.parse_args(argv)

    poll = source_demo(args.n) if args.demo else source_files(args.files)
    state = TopState()
    patterns = tuple(args.filter)

    if args.once:
        snaps = poll()
        state.observe(snaps)
        if args.demo:        # a second poll so rates/sparklines exist
            snaps = poll()
            state.observe(snaps)
        print(render_frame(snaps, state, patterns), flush=True)
        return 0

    use_curses = not args.plain and sys.stdout.isatty()
    if use_curses:
        try:
            _loop_curses(poll, state, patterns, args.interval)
            return 0
        except Exception:
            pass  # no terminal/curses: fall through to plain
    _loop_plain(poll, state, patterns, args.interval)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Multi-raft G-sweep: aggregate serving throughput vs group count.

Runs ``bench.measure_multiraft`` across a list of group counts (default
G in {64, 256, 1024}, N=3 voters each) plus the single-group headline
shape (G=1, n=4096) as the contrast row, and prints the PERF.md
"Multi-raft serving" table: aggregate committed entries/s and
lease-served reads/s summed over groups, with election settle time and
compile cost per point.  The contrast is the paper's serving-plane
story: many small quorums vs one giant one on the SAME tick kernel.

Every point also emits one JSON line on stdout (``--json``) so sweeps
are machine-diffable like bench.py rounds; the human table goes last.

Usage:
    python tools/multiraft_sweep.py                  # full sweep
    python tools/multiraft_sweep.py --groups 64,256 --entries 500000
    python tools/multiraft_sweep.py --no-single      # skip the G=1 row
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import _cli_common  # noqa: E402

_cli_common.bootstrap()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--groups", default="64,256,1024",
                    help="comma-separated group counts (default 64,256,1024)")
    ap.add_argument("--n", type=int, default=3,
                    help="voters per group (default 3)")
    ap.add_argument("--entries", type=int, default=2_000_000,
                    help="aggregate entries to commit per point")
    ap.add_argument("--seed", type=int, default=2024)
    ap.add_argument("--single-n", type=int, default=4096,
                    help="row count for the single-group contrast row")
    ap.add_argument("--no-single", action="store_true",
                    help="skip the G=1 single-group contrast row")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON line per point (before the table)")
    args = ap.parse_args(argv)

    import jax

    import bench

    rows = []
    for g in [int(x) for x in args.groups.split(",") if x]:
        print(f"measuring G={g} n={args.n} ...", file=sys.stderr, flush=True)
        r = bench.measure_multiraft(jax, g, args.n, args.entries, args.seed)
        rows.append((f"{g} x n={args.n}", r))
        if args.json:
            print(json.dumps({"groups": g, "n": args.n, **{
                k: round(v, 1) if isinstance(v, float) else v
                for k, v in r.items()}}), flush=True)

    if not args.no_single:
        print(f"measuring single group n={args.single_n} ...",
              file=sys.stderr, flush=True)
        # the contrast row reports a RATE, so a few hundred ticks of
        # steady state suffice — don't scale its entry count with the
        # aggregate target (n=4096 single-group ticks are ~3 orders
        # costlier than a G x n=3 tick)
        s = bench.measure(
            jax, args.single_n, entries=min(args.entries, 200_000),
            seed=args.seed,
            election_tick=bench.election_tick_for(args.single_n))
        rows.append((f"1 x n={args.single_n}",
                     {"rate": s["rate"], "read_rate": float("nan"),
                      "groups_with_leader": 1, "groups": 1,
                      "elect_ticks": s["election_ticks"],
                      "t_compile": s.get("t_compile", 0.0)}))
        if args.json:
            print(json.dumps({"groups": 1, "n": args.single_n,
                              "rate": round(s["rate"], 1)}), flush=True)

    print("\n| groups | agg entries/s | agg reads/s | led | elect ticks "
          "| compile s |")
    print("|---|---|---|---|---|---|")
    for label, r in rows:
        reads = ("-" if r["read_rate"] != r["read_rate"]
                 else f"{r['read_rate']:,.0f}")
        print(f"| {label} | {r['rate']:,.0f} | {reads} "
              f"| {r['groups_with_leader']}/{r['groups']} "
              f"| {r['elect_ticks']} | {r['t_compile']:.1f} |")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Flight-record viewer: summarize / export / diff saved records.

Records come from three producers (all JSON via
``swarmkit_tpu.flightrec.record.save_record``):

- DST post-mortems — ``tools/dst_sweep.py --mutate`` re-runs a shrunk
  violating schedule with the recorder on and attaches the window to the
  repro artifact; ``swarmkit_tpu.dst.capture_flight`` gives the full
  record programmatically.
- ``tools/fault_sweep.py --flight-dir DIR`` — host-span records dumped
  for every failing scenario.
- Any recorded run — ``flightrec.record.capture(state)`` on a SimState
  built with ``SimConfig(record_events=True)``.

Usage:
    python tools/flight_view.py summarize rec.json [--last 20]
    python tools/flight_view.py export rec.json -o trace.json [--check]
    python tools/flight_view.py diff a.json b.json

``export`` writes Chrome-trace JSON: open it at https://ui.perfetto.dev
or chrome://tracing.  Device events appear as instants on one track per
simulated manager; host tracer spans as complete events on one track per
subsystem.  ``--check`` schema-validates the result before writing.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swarmkit_tpu.flightrec import export as flight_export          # noqa: E402
from swarmkit_tpu.flightrec import record as flight_record          # noqa: E402


def cmd_summarize(args) -> int:
    rec = flight_record.load_record(args.record)
    print(flight_record.summarize(rec, last=args.last), flush=True)
    return 0


def cmd_export(args) -> int:
    rec = flight_record.load_record(args.record)
    trace = flight_export.to_chrome_trace(
        rec.events, rec.spans, tick_us=args.tick_us,
        counters=getattr(rec, "counters", ()))
    if args.check:
        problems = flight_export.validate_chrome_trace(trace)
        if problems:
            for p in problems:
                print(f"INVALID: {p}", file=sys.stderr, flush=True)
            return 1
    out = args.out or os.path.splitext(args.record)[0] + ".trace.json"
    flight_export.export_record(rec, out, tick_us=args.tick_us)
    print(f"wrote {len(trace['traceEvents'])} trace events to {out} "
          f"(open at https://ui.perfetto.dev)", flush=True)
    return 0


def cmd_diff(args) -> int:
    a = flight_record.load_record(args.a)
    b = flight_record.load_record(args.b)
    report = flight_record.diff_records(a, b)
    print(report, flush=True)
    return 0 if "streams are identical" in report else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("summarize", help="per-code counts + tail window")
    p.add_argument("record")
    p.add_argument("--last", type=int, default=20,
                   help="tail-window length (default 20)")
    p.set_defaults(fn=cmd_summarize)

    p = sub.add_parser("export", help="write Chrome/Perfetto trace JSON")
    p.add_argument("record")
    p.add_argument("-o", "--out", default=None,
                   help="output path (default: <record>.trace.json)")
    p.add_argument("--tick-us", type=float, default=1.0,
                   help="microseconds per simulated tick on the timeline")
    p.add_argument("--check", action="store_true",
                   help="schema-validate the trace; nonzero exit if invalid")
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser("diff", help="first divergence between two records")
    p.add_argument("a")
    p.add_argument("b")
    p.set_defaults(fn=cmd_diff)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())

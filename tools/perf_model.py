"""Per-phase performance model of the raft tick kernel (PERF.md generator).

Measures, on the CPU backend (pin first — the image's sitecustomize
registers the axon TPU platform and ignores JAX_PLATFORMS):

1. End-to-end steady-state per-tick cost, dynamic-membership vs
   static_members, at several N — the A/B that localizes the round-4
   regression (the dynamic path is bit-identical to round 4's kernel; the
   static path elides every membership-view op).
2. Standalone micro-kernels for each membership-related phase component,
   timed in isolation over realistic array shapes, attributing the delta.

Usage: python tools/perf_model.py [--quick] [--tiled {on,off,both}]
                                  [--peer-tiled {on,off,both}]
                                  [--active-rows {on,off,both}] [--reads]
Prints a markdown report to stdout (paste into PERF.md).  --tiled runs the
chunked-log-axis A/B instead (ms/tick per variant plus the analytic
swarm_kernel_bytes_touched{phase=...,variant=...} gauges).  --peer-tiled
runs the peer-axis A/B: hierarchical banded quorum reductions
(SimConfig.peer_chunk) vs dense [N, N] tallies on the [N, N]-dominated
shape, with phase="votes"|"commit" bytes rows.  --active-rows runs the
role-sparse progress A/B: [A, N] slab per-peer state writes
(SimConfig.active_rows) vs the dense elementwise kernel, with
phase="progress" bytes rows.  --reads runs
the linearizable-read A/B instead: tick-clock leases on (lease-valid
leaders serve with zero extra collectives) vs off (every batch waits for
a ReadIndex quorum confirmation), reads/s + ms/tick per wire, plus the
analytic swarm_kernel_bytes_touched{phase="read",...} rows.
"""

from __future__ import annotations

import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swarmkit_tpu.metrics import catalog as obs_catalog  # noqa: E402
from swarmkit_tpu.metrics import registry as obs_registry  # noqa: E402
from swarmkit_tpu.raft.sim import (  # noqa: E402
    SimConfig, committed_entries, has_leader, init_state, reads_served,
    run_ticks, run_until_leader,
)
from swarmkit_tpu.raft.sim.kernel import _idx_at_slots, _is_conf  # noqa: E402
from swarmkit_tpu.raft.sim.run import KernelObs  # noqa: E402

I32 = jnp.int32
U32 = jnp.uint32

OBS = KernelObs()  # feeds swarm_kernel_tick_seconds on the default registry


def _phase_gauge(phase: str, ms: float) -> None:
    """Publish one micro-kernel row as swarm_kernel_phase_ms{phase=...} so
    PERF.md's attribution table is also a live gauge family."""
    obs_catalog.get(OBS.obs, "swarm_kernel_phase_ms").labels(
        phase=phase).set(ms)


def _steady_harness(cfg: SimConfig, ticks: int):
    """The A/B steady-state scaffold every report variant shares: elect a
    leader, warm the jit cache with one full run, then take the best-of-3
    wall time of a `ticks`-tick steady-state scan.  Returns
    (ms_per_tick, best_wall_seconds, start_state, final_state) so callers
    can derive entries/s / reads/s deltas from the same run."""
    st = init_state(cfg)
    with OBS.timed("run_until_leader"):
        st, _ = run_until_leader(st, cfg, max_ticks=512)
        jax.block_until_ready(st.term)
    assert bool(has_leader(st)), f"no leader at n={cfg.n}"
    warm, _ = run_ticks(st, cfg, ticks, prop_count=cfg.max_props)
    jax.block_until_ready(warm.commit)
    best = float("inf")
    for _ in range(3):
        with OBS.timed("run_ticks"):
            t0 = time.perf_counter()
            fin, _ = run_ticks(st, cfg, ticks, prop_count=cfg.max_props)
            jax.block_until_ready(fin.commit)
        best = min(best, time.perf_counter() - t0)
    return best / ticks * 1e3, best, st, fin


def steady_rate(n: int, ticks: int = 64, static: bool = False, **kw):
    """Per-tick ms + entries/s for the bench steady-state flow."""
    kw.setdefault("log_len", 8192)
    cfg = SimConfig(n=n, window=2048, apply_batch=2048,
                    max_props=2048, keep=500, seed=42, election_tick=16,
                    static_members=static, **kw)
    ms, best, st, fin = _steady_harness(cfg, ticks)
    ents = int(committed_entries(fin)) - int(committed_entries(st))
    rate = ents / best
    g = obs_catalog.get(OBS.obs, "swarm_bench_entries_per_second")
    g.labels(config=f"perf-model-n{n}-"
             f"{'static' if static else 'dynamic'}").set(rate)
    return ms, rate


def _time_jit(fn, *args, reps: int = 20):
    """Best-of wall time of a jitted fn in ms (post-warmup)."""
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def micro_phases(n: int, L: int = 8192):
    """Isolated cost of each membership-related component at [N], [N,N],
    [N,L] shapes (keys match the kernel's phase letters)."""
    cfg = SimConfig(n=n, log_len=L, window=2048, apply_batch=2048,
                    max_props=2048, keep=500)
    member = jnp.ones((n, n), bool)
    match = jnp.arange(n * n, dtype=I32).reshape(n, n) % 1000
    granted = (match % 3) == 0
    log_data = (jnp.arange(n * L, dtype=U32).reshape(n, L) * U32(2654435761))
    last = jnp.full((n,), L // 2, I32)
    applied = last - 100
    commit = last - 50

    rows = {}
    rows["views: n_mem sum + quorum [N,N]->[N]"] = _time_jit(
        lambda m: jnp.sum(m.astype(I32), axis=1) // 2 + 1, member)
    rows["mask: one granted&member reduction [N,N]"] = _time_jit(
        lambda g, m: jnp.sum((g & m).astype(I32), axis=1), granted, member)
    rows["unmasked equivalent [N,N]"] = _time_jit(
        lambda g: jnp.sum(g.astype(I32), axis=1), granted)
    rows["commit bisect mask: where(member,match,-1) [N,N]"] = _time_jit(
        lambda m, mm: jnp.where(mm, m, -1), match, member)

    def conf_scan(log_data, last, applied, commit):
        own_idx = _idx_at_slots(cfg, last)
        icr = _is_conf(log_data)
        big = jnp.iinfo(jnp.int32).max
        first_conf = jnp.min(
            jnp.where((own_idx > applied[:, None])
                      & (own_idx <= commit[:, None]) & icr, own_idx, big),
            axis=1)
        hup = jnp.any((own_idx > applied[:, None])
                      & (own_idx <= commit[:, None]) & icr, axis=1)
        tail = jnp.any((own_idx > commit[:, None])
                       & (own_idx <= last[:, None]) & icr, axis=1)
        return first_conf, hup, tail

    rows["Phase E conf decode + hup/tail scans [N,L]x3"] = _time_jit(
        conf_scan, log_data, last, applied, commit)

    def apply_chk(log_data, last, applied, commit):
        own_idx = _idx_at_slots(cfg, last)
        mask = (own_idx > applied[:, None]) & (own_idx <= commit[:, None])
        from swarmkit_tpu.raft.sim.kernel import _entry_chk
        return jnp.sum(jnp.where(mask, _entry_chk(own_idx, log_data),
                                 U32(0)), axis=1, dtype=U32)

    rows["(context) apply+checksum pass [N,L]"] = _time_jit(
        apply_chk, log_data, last, applied, commit)
    for k, v in rows.items():
        _phase_gauge(f"{_PHASE_SLUGS.get(k, k)}@n{n}", v)
    return rows


def _bytes_touched(n: int, L: int, chunk: int, variant: str) -> None:
    """Publish the analytic per-tick log-buffer traffic of the C/E/F hot
    phases as swarm_kernel_bytes_touched{phase=...,variant=...}.

    full: every phase streams the whole [N, L] s32+u32 pair (append also
    writes it back).  tiled: append touches the band_chunks*log_chunk DUS
    band plus the [N, window] gather side-buffers, apply reads the
    [N, apply_batch] gather window, compaction the [N, keep] ahead span."""
    cfg = SimConfig(n=n, log_len=L, window=2048, apply_batch=2048,
                    max_props=2048, keep=500, log_chunk=chunk)
    g = obs_catalog.get(OBS.obs, "swarm_kernel_bytes_touched")
    if cfg.tiled:
        band = cfg.band_chunks * cfg.log_chunk
        phases = {"C-append": n * (band * 8 * 2 + cfg.window * 12),
                  "E-apply": n * cfg.apply_batch * 8,
                  "F-compact": n * cfg.keep * 8}
    else:
        phases = {"C-append": n * L * 8 * 2,
                  "E-apply": n * L * 8,
                  "F-compact": n * L * 8}
    for ph, b in phases.items():
        g.labels(phase=ph, variant=variant).set(b)


def tiled_report(mode: str, quick: bool) -> None:
    """--tiled {on,off,both}: A/B the chunked log-axis kernel against the
    full-pass kernel on the synchronous wire, static_members."""
    variants = {"on": ("tiled",), "off": ("full",),
                "both": ("full", "tiled")}[mode]
    points = [(256, 8192), (256, 65536)]
    if not quick:
        points.append((1024, 8192))
    print("\n## Tiled log axis A/B (static_members, synchronous wire, "
          "log_chunk=1024)\n")
    print("Best-of-3 wall times; absolute numbers move with machine load, "
          "the tiled/full ratio is the stable signal.\n")
    print("| n | log_len | " + " | ".join(
        f"{v} ms/tick" for v in variants)
        + (" | speedup |" if len(variants) == 2 else " |"))
    print("|---|---|" + "---|" * (len(variants) + (len(variants) == 2)))
    for n, L in points:
        ms = {}
        for v in variants:
            chunk = 1024 if v == "tiled" else 0
            ms[v], _ = steady_rate(n, static=True, log_len=L,
                                   log_chunk=chunk)
            _bytes_touched(n, L, chunk, v)
        row = f"| {n} | {L} | " + " | ".join(
            f"{ms[v]:.2f}" for v in variants)
        if len(variants) == 2:
            row += f" | {ms['full'] / ms['tiled']:.2f}x"
        print(row + " |")


def peer_steady(n: int, chunk: int, ticks: int = 32, static: bool = True):
    """Per-tick ms on the [N, N]-dominated shape: the log axis is tiled
    with small cursor work (window/apply_batch/max_props 256), so the
    vote/commit/heartbeat quorum reductions dominate and the peer_chunk
    A/B isolates the hierarchical lowering (chunk=0 = dense)."""
    cfg = SimConfig(n=n, log_len=4096, window=256, apply_batch=256,
                    max_props=256, keep=500, seed=42, election_tick=16,
                    static_members=static, log_chunk=256, peer_chunk=chunk)
    ms, _, _, _ = _steady_harness(cfg, ticks)
    return ms


def peer_micro(n: int, chunk: int, reps: int = 10):
    """Isolated per-tick cost of the two [N, N] quorum-REDUCTION phase
    groups the peer tiling rewrites, dense vs banded, mirroring
    kernel.py's two code paths (static-membership form):

    votes  = the three Phase A/B tallies (pre-vote, vote, rejection)
    commit = the Phase D commit bisection (ceil(log2 L)+1 count rounds
             over the match matrix)

    This is the [N, N]-dominated measurement the tiling targets.  The
    whole-tick A/B below it is diluted: a tick also spends O(N^2) on
    ELEMENTWISE progress/fan-out state writes that the tiling
    deliberately leaves dense (they are state updates, not reductions),
    so the per-tick ratio approaches 1.0 even while the reduction phases
    themselves speed up severalfold.  Returns {phase: (dense_ms,
    banded_ms)}.
    """
    L = 4096
    rounds = L.bit_length() + 1
    pc, pg = chunk, n // chunk
    idx = jnp.arange(n * n, dtype=I32).reshape(n, n)
    g1, g2, rj = (idx % 3) == 0, (idx % 5) == 0, (idx % 7) == 0
    match = idx % (L // 2)
    commit = jnp.full((n,), L // 4, I32)
    hi0 = jnp.full((n,), L, I32)

    def _band(x, j0):
        return jax.lax.dynamic_slice(x, (0, j0), (n, pc))

    def _pcount(pred):
        def _grp(g, acc):
            c = jnp.sum(pred(g * pc).astype(I32), axis=1)
            return jax.lax.dynamic_update_slice(acc, c[:, None], (0, g))
        parts = jax.lax.fori_loop(0, pg, _grp, jnp.zeros((n, pg), I32))
        return jnp.sum(parts, axis=1)

    def votes_dense(g1, g2, rj):
        return (jnp.sum(g1.astype(I32), axis=1)
                + jnp.sum(g2.astype(I32), axis=1)
                + jnp.sum((rj & ~g2).astype(I32), axis=1))

    def votes_banded(g1, g2, rj):
        return (_pcount(lambda j0: _band(g1, j0))
                + _pcount(lambda j0: _band(g2, j0))
                + _pcount(lambda j0: _band(rj, j0) & ~_band(g2, j0)))

    def _bisect(count):
        def body(_, lohi):
            lo, hi = lohi
            mid = (lo + hi + 1) // 2
            ok = count(mid) * 2 > n
            return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid - 1)
        lo, _ = jax.lax.fori_loop(0, rounds, body, (commit, hi0))
        return lo

    def commit_dense(match):
        return _bisect(lambda mid: jnp.sum(
            (match >= mid[:, None]).astype(I32), axis=1))

    def commit_banded(match):
        return _bisect(lambda mid: _pcount(
            lambda j0: _band(match, j0) >= mid[:, None]))

    out = {
        "votes": (_time_jit(votes_dense, g1, g2, rj, reps=reps),
                  _time_jit(votes_banded, g1, g2, rj, reps=reps)),
        "commit": (_time_jit(commit_dense, match, reps=reps),
                   _time_jit(commit_banded, match, reps=reps)),
    }
    for ph, (d, b) in out.items():
        _phase_gauge(f"peer-{ph}-dense@n{n}", d)
        _phase_gauge(f"peer-{ph}-banded@n{n}", b)
    return out


def _peer_bytes_touched(n: int, chunk: int, variant: str,
                        log_len: int = 4096) -> None:
    """Publish the analytic per-tick intermediate traffic of the peer-axis
    quorum phases as swarm_kernel_bytes_touched{phase="votes"|"commit"}.

    Both lowerings must READ every peer column; what the banded form
    removes is the MATERIALIZED [N, N] intermediates.  votes (dense):
    the three Phase A/B tallies each write an [N, N] masked-bool plus an
    [N, N] i32 cast before reducing.  commit (dense): one [N, N] i32
    match_eff write plus, per bisection round, an [N, N] compare-bool and
    an [N, N] i32 cast.  banded: per-band temporaries stay at
    [N, peer_chunk] (cache-resident working set) and each pass lands an
    [N, num_peer_chunks] i32 partial buffer instead."""
    cfg = SimConfig(n=n, log_len=log_len, window=256, apply_batch=256,
                    max_props=256, keep=500, peer_chunk=chunk)
    g = obs_catalog.get(OBS.obs, "swarm_kernel_bytes_touched")
    rounds = max(1, log_len.bit_length() + 1)
    if cfg.peer_tiled:
        pc, pg = cfg.peer_chunk, cfg.num_peer_chunks
        phases = {"votes": 3 * (n * pc * 5 + n * pg * 4),
                  "commit": rounds * (n * pc * 5 + n * pg * 4)}
    else:
        phases = {"votes": 3 * n * n * 5,
                  "commit": n * n * 4 + rounds * n * n * 5}
    for ph, b in phases.items():
        g.labels(phase=ph, variant=variant).set(b)


def peer_report(mode: str, quick: bool) -> None:
    """--peer-tiled {on,off,both}: A/B the hierarchical (banded) peer-axis
    quorum reductions against the dense [N, N] tallies on the
    [N, N]-dominated shape (log axis tiled, static_members)."""
    variants = {"on": ("banded",), "off": ("dense",),
                "both": ("dense", "banded")}[mode]
    points = [(1024, 256)]
    if not quick:
        points.append((4096, 1024))
    if len(variants) == 2:
        print("\n## Peer-axis quorum reductions, isolated (the "
              "[N, N]-dominated phases the tiling rewrites)\n")
        print("votes = the three Phase A/B tallies; commit = the Phase D "
              "bisection (13 count rounds at L=4096).  Micro-kernels "
              "mirror kernel.py's two lowerings exactly.\n")
        print("| n | peer_chunk | phase | dense ms | banded ms | speedup |")
        print("|---|---|---|---|---|---|")
        for n, chunk in points:
            micro = peer_micro(n, chunk, reps=5 if quick else 10)
            td = tb = 0.0
            for ph, (d, b) in micro.items():
                td, tb = td + d, tb + b
                print(f"| {n} | {chunk} | {ph} | {d:.2f} | {b:.2f} "
                      f"| {d / b:.2f}x |")
            print(f"| {n} | {chunk} | **combined** | {td:.2f} | {tb:.2f} "
                  f"| {td / tb:.2f}x |")
    print("\n## Whole-tick A/B (context: includes the elementwise [N, N] "
          "progress/fan-out state writes the tiling leaves dense, which "
          "dilute the per-tick ratio toward 1.0)\n")
    print("Shape: log_chunk=256, window/apply/props=256, static_members, "
          "synchronous wire.  Best-of-3 wall times; absolute numbers move "
          "with machine load, the banded/dense ratio is the stable "
          "signal.\n")
    print("| n | peer_chunk | " + " | ".join(
        f"{v} ms/tick" for v in variants)
        + (" | speedup |" if len(variants) == 2 else " |"))
    print("|---|---|" + "---|" * (len(variants) + (len(variants) == 2)))
    for n, chunk in points:
        ms = {}
        for v in variants:
            c = chunk if v == "banded" else 0
            ms[v] = peer_steady(n, c)
            _peer_bytes_touched(n, c, v)
        row = f"| {n} | {chunk} | " + " | ".join(
            f"{ms[v]:.2f}" for v in variants)
        if len(variants) == 2:
            row += f" | {ms['dense'] / ms['banded']:.2f}x"
        print(row + " |")


def sparse_steady(n: int, active_rows: int, ticks: int = 32):
    """Per-tick ms + entries/s on the elementwise-progress-dominated
    shape: log axis tiled, peer reductions banded, small cursor work —
    exactly the residual O(N^2) the role-sparse slabs rewrite.  With
    active_rows=0 the tick pays the historical dense per-peer writes;
    with 0 < A < n the steady-state tick runs them on [A, n] slabs."""
    cfg = SimConfig(n=n, log_len=4096, window=256, apply_batch=256,
                    max_props=256, keep=500, seed=42, election_tick=16,
                    static_members=True, log_chunk=256,
                    peer_chunk=min(1024, n), active_rows=active_rows)
    ms, best, st, fin = _steady_harness(cfg, ticks)
    ents = int(committed_entries(fin)) - int(committed_entries(st))
    return ms, ents / best


def _progress_bytes_touched(n: int, active_rows: int, variant: str) -> None:
    """Publish the analytic per-tick elementwise progress traffic as
    swarm_kernel_bytes_touched{phase="progress",variant=...}.

    The per-peer progress state a steady-state tick rewrites is two
    [rows, N] i32 planes (match, next), three [rows, N] bool planes
    (granted, rejection hints, recent_active), and the two [rows, N]
    bool ack-fold intermediates (ok/reject) that feed them: 13 bytes per
    (row, peer) cell.  dense: rows = N, every tick.  sparse: rows = A
    plus the [N] i32 TTL vector and the [A] gather/scatter index
    traffic; the [N, N] planes are only touched on the A-row scatter
    band."""
    g = obs_catalog.get(OBS.obs, "swarm_kernel_bytes_touched")
    cell = 2 * 4 + 3 * 1 + 2 * 1
    if active_rows:
        g.labels(phase="progress", variant=variant).set(
            active_rows * n * cell + n * 4 + active_rows * 4)
    else:
        g.labels(phase="progress", variant=variant).set(n * n * cell)


def sparse_report(mode: str, quick: bool) -> None:
    """--active-rows {on,off,both}: A/B the role-sparse [A, N] progress
    slabs (SimConfig.active_rows) against the dense elementwise per-peer
    writes on the progress-dominated shape (log tiled, peers banded,
    static_members, synchronous wire)."""
    variants = {"on": ("sparse",), "off": ("dense",),
                "both": ("dense", "sparse")}[mode]
    points = [(1024, 16)]
    if not quick:
        points.append((4096, 16))
    print("\n## Role-sparse progress A/B (static_members, synchronous "
          "wire, log_chunk=256, peer_chunk banded)\n")
    print("Steady state has one leader and no candidates, so the sparse "
          "tick gathers the A hot rows, runs every per-peer progress "
          "write at [A, n], and scatters back; active_rows=0 is the "
          "historical dense elementwise kernel.  Best-of-3 wall times; "
          "the sparse/dense ratio is the stable signal.\n")
    print("| n | active_rows | " + " | ".join(
        f"{v} ms/tick" for v in variants)
        + " | " + " | ".join(f"{v} entries/s" for v in variants)
        + (" | speedup |" if len(variants) == 2 else " |"))
    print("|---|---|" + "---|" * (2 * len(variants) + (len(variants) == 2)))
    for n, a in points:
        ms, eps = {}, {}
        for v in variants:
            ar = a if v == "sparse" else 0
            ms[v], eps[v] = sparse_steady(n, ar)
            _progress_bytes_touched(n, ar, v)
        row = (f"| {n} | {a} | "
               + " | ".join(f"{ms[v]:.2f}" for v in variants) + " | "
               + " | ".join(f"{eps[v]:,.0f}" for v in variants))
        if len(variants) == 2:
            row += f" | {ms['dense'] / ms['sparse']:.2f}x"
        print(row + " |")


def read_steady(n: int, ticks: int = 64, leases: bool = True, **kw):
    """Per-tick ms + reads/s + entries/s with the read path compiled in
    (32 reads per row per refill, leases on or off)."""
    kw.setdefault("log_len", 8192)
    cfg = SimConfig(n=n, window=2048, apply_batch=2048, max_props=2048,
                    keep=500, seed=42, election_tick=16, static_members=True,
                    read_batch=32, read_leases=leases, **kw)
    ms, best, st, fin = _steady_harness(cfg, ticks)
    reads = int(reads_served(fin)) - int(reads_served(st))
    ents = int(committed_entries(fin)) - int(committed_entries(st))
    return ms, reads / best, ents / best


def _read_bytes_touched(n: int) -> None:
    """Analytic per-tick read-path traffic as
    swarm_kernel_bytes_touched{phase="read",variant=...}.

    The read registers are eight [N] i32 vectors (read + write every
    tick).  A lease-valid leader serves against the tick clock — one [N]
    compare, zero extra collectives.  The ReadIndex-every-batch variant
    additionally reduces the [N, N] heartbeat-ack matrix per confirmation
    (on a real transport that is the extra quorum round-trip the lease
    elides; on device it is the ack-matrix read)."""
    g = obs_catalog.get(OBS.obs, "swarm_kernel_bytes_touched")
    regs = n * 8 * 4 * 2
    g.labels(phase="read", variant="lease").set(regs + n * 4)
    g.labels(phase="read", variant="readindex").set(regs + n * n + n * 4)


def reads_report(quick: bool) -> None:
    """--reads: lease-serving vs ReadIndex-every-batch A/B at n=256."""
    n = 256
    print(f"\n## Linearizable reads A/B (static_members, n={n}, "
          "read_batch=32/row, 2048 props/tick)\n")
    print("Leases serve from the tick clock once a quorum ack renews them; "
          "`readindex` (read_leases=False) confirms every batch against "
          "the heartbeat ack quorum instead.\n")
    print("| wire | variant | ms/tick | reads/s | entries/s |")
    print("|---|---|---|---|---|")
    wires = [("sync", {})]
    if not quick:
        wires.append(("mailbox lat=2 jit=1",
                      dict(latency=2, latency_jitter=1, inflight=4)))
    g = obs_catalog.get(OBS.obs, "swarm_bench_reads_per_second")
    for wire, kw in wires:
        for leases in (True, False):
            variant = "lease" if leases else "readindex"
            ms, rps, eps = read_steady(n, leases=leases, **kw)
            g.labels(config=f"perf-model-n{n}-{variant}").set(rps)
            print(f"| {wire} | {variant} | {ms:.2f} | {rps:,.0f} | "
                  f"{eps:,.0f} |")
    _read_bytes_touched(n)


_PHASE_SLUGS = {
    "views: n_mem sum + quorum [N,N]->[N]": "views",
    "mask: one granted&member reduction [N,N]": "vote-mask",
    "unmasked equivalent [N,N]": "vote-unmasked",
    "commit bisect mask: where(member,match,-1) [N,N]": "commit-bisect",
    "Phase E conf decode + hup/tail scans [N,L]x3": "E-conf-scan",
    "(context) apply+checksum pass [N,L]": "apply-chk",
}


def main():
    quick = "--quick" in sys.argv
    if "--reads" in sys.argv:
        reads_report(quick)
        print("\n## Live metrics (registry render)\n")
        print("```")
        print(obs_registry.DEFAULT.render().rstrip())
        print("```")
        return
    if "--active-rows" in sys.argv:
        mode = sys.argv[sys.argv.index("--active-rows") + 1]
        if mode not in ("on", "off", "both"):
            raise SystemExit(
                f"--active-rows {mode}: expected on, off, or both")
        sparse_report(mode, quick)
        print("\n## Live metrics (registry render)\n")
        print("```")
        print(obs_registry.DEFAULT.render().rstrip())
        print("```")
        return
    if "--peer-tiled" in sys.argv:
        mode = sys.argv[sys.argv.index("--peer-tiled") + 1]
        if mode not in ("on", "off", "both"):
            raise SystemExit(
                f"--peer-tiled {mode}: expected on, off, or both")
        peer_report(mode, quick)
        print("\n## Live metrics (registry render)\n")
        print("```")
        print(obs_registry.DEFAULT.render().rstrip())
        print("```")
        return
    if "--tiled" in sys.argv:
        mode = sys.argv[sys.argv.index("--tiled") + 1]
        if mode not in ("on", "off", "both"):
            raise SystemExit(f"--tiled {mode}: expected on, off, or both")
        tiled_report(mode, quick)
        print("\n## Live metrics (registry render)\n")
        print("```")
        print(obs_registry.DEFAULT.render().rstrip())
        print("```")
        return
    sizes = (256,) if quick else (64, 256, 1024)
    print("## Steady-state per-tick cost (CPU, synchronous wire, "
          "2048 props/tick)\n")
    print("| n | dynamic ms/tick | static ms/tick | dynamic e/s | "
          "static e/s | static speedup |")
    print("|---|---|---|---|---|---|")
    for n in sizes:
        dm, dr = steady_rate(n, static=False)
        sm, sr = steady_rate(n, static=True)
        print(f"| {n} | {dm:.2f} | {sm:.2f} | {dr:,.0f} | {sr:,.0f} | "
              f"{dm / sm:.2f}x |")

    print("\n## Mailbox wire (lat=2 jitter=1 inflight=4), n=256\n")
    print("| variant | ms/tick | entries/s |")
    print("|---|---|---|")
    for static in (False, True):
        m, r = steady_rate(256, static=static, latency=2, latency_jitter=1,
                           inflight=4)
        print(f"| {'static' if static else 'dynamic'} | {m:.2f} | {r:,.0f} |")

    print("\n## Micro-kernel attribution (isolated jits, best-of-20)\n")
    for n in sizes:
        print(f"\n### n={n}, L=8192\n")
        print("| component | ms |")
        print("|---|---|")
        for k, v in micro_phases(n).items():
            print(f"| {k} | {v:.3f} |")

    # everything above also landed in the typed registry (the same families
    # a live manager scrape serves) — render it so the report doubles as an
    # exposition-format example for README.md's Observability section
    print("\n## Live metrics (registry render)\n")
    print("```")
    print(obs_registry.DEFAULT.render().rstrip())
    print("```")


if __name__ == "__main__":
    main()

"""Per-phase tick profile: where one kernel step spends its time.

The tick kernel's phases are delimited by ``jax.named_scope`` seams
(raft/sim/kernel.py: phase_A_timers ... phase_F_compact, phase_R0..R2),
so a compiled module attributes every HLO op to a phase.  CPU runtimes
expose no per-op timings, so this tool measures each phase with an
isolated micro-kernel mirroring that phase's dominant array ops at the
profiled config's exact shapes, then scales the shares onto the
measured whole-tick time:

- ``raw ms``    — the isolated best-of-k micro-kernel time;
- ``attributed ms`` — raw share x whole-tick time, so the attributed
  column sums to the tick by construction;
- ``coverage``  — sum(raw) / tick_ms, the honesty diagnostic: far from
  1.0 means the micro-kernels and the fused tick have drifted apart
  (XLA fuses across phase seams; 0.8-1.3 is typical on CPU).

Also measured: whole-tick compile seconds (lower + backend compile,
timed separately), device peak memory (``memory_stats()``; None on CPU
backends that don't report it), and — with ``--capture DIR`` — a
``jax.profiler.trace`` capture of the timed loop for offline Perfetto
inspection.

``--bench-json PATH`` appends one JSON line carrying ``compile_seconds``
/ ``peak_bytes`` / per-phase ms in the same shape bench.py emits, so
``tools/bench_gate.py`` gates them as resource series.
``--verify-scopes`` checks the named_scope seams actually survive into
the compiled HLO (the contract the attribution rests on).

Usage: python tools/profile_tick.py [--n 256] [--quick] [--json]
                                    [--capture DIR] [--bench-json PATH]
                                    [--verify-scopes]
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swarmkit_tpu.raft.sim import (  # noqa: E402
    SimConfig, has_leader, init_state, run_until_leader,
)
from swarmkit_tpu.raft.sim.kernel import (  # noqa: E402
    _entry_chk, _idx_at_slots, _is_conf, step,
)

I32 = jnp.int32
U32 = jnp.uint32

# The named_scope seams the kernel wraps its phases in; --verify-scopes
# pins this list against the compiled HLO.
PHASE_SCOPES = ("phase_R0_submit", "phase_A_timers", "phases_ABC_progress",
                "phase_D_progress", "phase_D_commit_fold", "phase_R1_stamp",
                "phase_E_apply", "phase_R2_settle", "phase_F_compact")


def profile_config(n: int) -> SimConfig:
    """The steady-state bench shape (perf_model.steady_rate) plus the
    read path, so R0-R2 exist to be measured."""
    return SimConfig(n=n, log_len=8192, window=2048, apply_batch=2048,
                     max_props=2048, keep=500, seed=42, election_tick=16,
                     read_batch=32)


def _time_call(fn, *args, reps: int = 10):
    """Best-of wall time in ms (post-warmup)."""
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def _steady_state(cfg: SimConfig):
    """Elect a leader and advance into replication steady state."""
    st = init_state(cfg)
    st, _ = run_until_leader(st, cfg, max_ticks=512)
    assert bool(has_leader(st)), f"no leader at n={cfg.n}"
    pc = jnp.asarray(cfg.max_props, I32)

    def _payload(tick, k):
        return tick.astype(U32) * U32(1 << 16) + k.astype(U32) + U32(1)

    stepf = jax.jit(lambda s: step(s, cfg, prop_count=pc,
                                   payload_fn=_payload))
    for _ in range(4):  # fill pipelines so every phase has real work
        st = stepf(st)
    jax.block_until_ready(st.commit)
    return st, stepf


def measure_compile(cfg: SimConfig, state) -> dict:
    """Whole-tick compile cost, lowering and backend compile separately
    (a fresh jit closure so nothing is cached)."""
    pc = jnp.asarray(cfg.max_props, I32)
    f = jax.jit(lambda s: step(s, cfg, prop_count=pc))
    t0 = time.perf_counter()
    lowered = f.lower(state)
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    compiled = lowered.compile()
    t_compile = time.perf_counter() - t0
    return {"lower_seconds": round(t_lower, 3),
            "compile_seconds": round(t_compile, 3),
            "compiled": compiled}


def peak_bytes() -> int | None:
    """Device peak-memory high-water mark, or None when the backend
    doesn't report one (CPU returns None / empty stats — a fabricated 0
    would read as 'no memory used')."""
    try:
        peaks = []
        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats and stats.get("peak_bytes_in_use"):
                peaks.append(int(stats["peak_bytes_in_use"]))
        return max(peaks) if peaks else None
    except Exception:
        return None


def phase_micro(cfg: SimConfig, state, reps: int = 10) -> dict:
    """Isolated per-phase micro-kernels at the profiled state's exact
    shapes, mirroring each phase's dominant ops in kernel.py.  Keys are
    the kernel's named_scope seams (heartbeat fan-out and append-accept
    ride inside phases_ABC_progress like they do in the kernel)."""
    n, L = cfg.n, cfg.log_len
    rounds = L.bit_length() + 1
    member = jnp.asarray(state.member)
    granted = jnp.asarray(state.granted)
    rejected = jnp.asarray(state.rejected)
    match = jnp.asarray(state.match)
    log_term = jnp.asarray(state.log_term)
    log_data = jnp.asarray(state.log_data)
    last = jnp.asarray(state.last)
    commit = jnp.asarray(state.commit)
    applied = jnp.asarray(state.applied)
    elapsed = jnp.asarray(state.elapsed)
    timeout = jnp.asarray(state.timeout)
    rows = {}

    def a_timers(elapsed, timeout, last):
        e2 = elapsed + 1
        fire = e2 >= timeout
        contact = jnp.where(fire, 0, e2)
        hb = jnp.minimum(e2 % 7, last)
        return e2, fire, contact, hb

    rows["phase_A_timers"] = _time_call(jax.jit(a_timers), elapsed, timeout,
                                        last, reps=reps)

    def abc_progress(granted, rejected, member, match, log_term, log_data,
                     last):
        # A/B vote tallies (three masked [N, N] reductions) ...
        votes = (jnp.sum((granted & member).astype(I32), axis=1)
                 + jnp.sum((rejected & member).astype(I32), axis=1)
                 + jnp.sum((granted & ~rejected).astype(I32), axis=1))
        # ... plus Phase C's log traffic: the propose stamp and the
        # append store each rewrite both [N, L] planes under a slot
        # mask, the accept check compares terms over the same slots,
        # and the heartbeat fan-out gathers the leader's send window
        own_idx = _idx_at_slots(cfg, last)
        wmask = (own_idx > (last - cfg.window)[:, None]) \
            & (own_idx <= last[:, None])
        pmask = (own_idx > last[:, None]) \
            & (own_idx <= (last + cfg.max_props)[:, None])
        accept = jnp.sum((log_term == jnp.max(log_term)).astype(I32), axis=1)
        lt = jnp.where(pmask, jnp.max(log_term), log_term)
        ld = jnp.where(pmask, log_data + U32(1), log_data)
        lt = jnp.where(wmask, lt + 1, lt)
        ld = jnp.where(wmask, ld ^ U32(2654435761), ld)
        wnd = jnp.take_along_axis(
            ld, (own_idx % cfg.log_len)[:, : cfg.window], axis=1)
        # per-peer progress planes (match/next elementwise updates)
        m2 = jnp.where(member, jnp.maximum(match, last[None, :]), match)
        return votes, accept, lt, ld, wnd, m2

    rows["phases_ABC_progress"] = _time_call(
        jax.jit(abc_progress), granted, rejected, member, match, log_term,
        log_data, last, reps=reps)

    def d_commit(match, member, commit, last):
        # Phase D: commit bisection — ceil(log2 L)+1 masked count rounds
        # over the match matrix (kernel.py _progress_b)
        meff = jnp.where(member, match, -1)

        def body(_, lohi):
            lo, hi = lohi
            mid = (lo + hi + 1) // 2
            cnt = jnp.sum((meff >= mid[:, None]).astype(I32), axis=1)
            ok = cnt * 2 > n
            return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid - 1)

        lo, _ = jax.lax.fori_loop(0, rounds, body, (commit, last))
        return lo

    rows["phase_D_commit_fold"] = _time_call(jax.jit(d_commit), match,
                                             member, commit, last, reps=reps)

    def e_apply(log_data, last, applied, commit):
        # Phase E: the apply+checksum pass over the apply window, plus
        # the conf-entry decode scans (kernel.py Phase E)
        own_idx = _idx_at_slots(cfg, last)
        mask = (own_idx > applied[:, None]) & (own_idx <= commit[:, None])
        chk = jnp.sum(jnp.where(mask, _entry_chk(own_idx, log_data), U32(0)),
                      axis=1, dtype=U32)
        icr = _is_conf(log_data)
        hup = jnp.any(mask & icr, axis=1)
        return chk, hup

    rows["phase_E_apply"] = _time_call(jax.jit(e_apply), log_data, last,
                                       applied, commit, reps=reps)

    def f_compact(log_term, log_data, last, applied):
        # Phase F: pressure check + wipe of the compacted span (one
        # masked rewrite of both planes behind the new snap_idx)
        own_idx = _idx_at_slots(cfg, last)
        snap = jnp.maximum(applied - cfg.keep, 0)
        wipe = own_idx <= snap[:, None]
        return (jnp.where(wipe, 0, log_term),
                jnp.where(wipe, U32(0), log_data))

    rows["phase_F_compact"] = _time_call(jax.jit(f_compact), log_term,
                                         log_data, last, applied, reps=reps)

    def r_reads(commit, applied, last, member, granted):
        # R0 submit + R1 stamp (one [N, N] ack-quorum reduction) + R2
        # settle: eight [N] register vectors of cursor math around it
        pend = jnp.minimum(last % (cfg.read_batch + 1), cfg.read_batch)
        goal = jnp.maximum(commit, applied)
        acks = jnp.sum((granted & member).astype(I32), axis=1)
        stamped = jnp.where(acks * 2 > n, goal, -1)
        served = jnp.where((stamped >= 0) & (applied >= stamped), pend, 0)
        return pend - served, stamped, served

    rows["phase_R0_R2_reads"] = _time_call(jax.jit(r_reads), commit, applied,
                                           last, member, granted, reps=reps)
    return rows


def verify_scopes(compiled) -> list[str]:
    """Named-scope seams missing from the compiled HLO (empty = all
    present).  R0/R1/R2 seams only exist when cfg.read_batch > 0."""
    txt = compiled.as_text()
    return [s for s in PHASE_SCOPES if s not in txt]


def run_profile(n: int, quick: bool = False, capture_dir: str | None = None
                ) -> dict:
    """Measure everything; returns the result dict the CLI renders."""
    cfg = profile_config(n)
    reps = 3 if quick else 10
    st, stepf = _steady_state(cfg)

    comp = measure_compile(cfg, st)
    compiled = comp.pop("compiled")

    def timed_loop():
        return _time_call(stepf, st, reps=reps)

    if capture_dir:
        with jax.profiler.trace(capture_dir):
            tick_ms = timed_loop()
    else:
        tick_ms = timed_loop()

    micro = phase_micro(cfg, st, reps=reps)
    raw_sum = sum(micro.values())
    phases = {k: {"raw_ms": round(v, 3),
                  "attributed_ms": round(tick_ms * v / raw_sum, 3)}
              for k, v in micro.items()}
    out = {
        "n": n, "platform": jax.devices()[0].platform,
        "tick_ms": round(tick_ms, 3),
        "coverage": round(raw_sum / tick_ms, 3),
        "phases": phases,
        "lower_seconds": comp["lower_seconds"],
        "compile_seconds": comp["compile_seconds"],
        "peak_bytes": peak_bytes(),
        "missing_scopes": verify_scopes(compiled),
    }
    if capture_dir:
        out["capture_dir"] = capture_dir
    return out


def render(out: dict) -> str:
    lines = [f"## Tick profile: n={out['n']} ({out['platform']}), "
             f"whole tick {out['tick_ms']:.2f} ms",
             "",
             f"compile {out['compile_seconds']:.2f}s "
             f"(+{out['lower_seconds']:.2f}s lowering), peak memory "
             + (f"{out['peak_bytes']:,} bytes" if out["peak_bytes"]
                else "n/a (backend reports none)"),
             "",
             "| phase | raw ms | attributed ms | share |",
             "|---|---|---|---|"]
    total = sum(p["attributed_ms"] for p in out["phases"].values())
    for name, p in out["phases"].items():
        lines.append(f"| {name} | {p['raw_ms']:.3f} | "
                     f"{p['attributed_ms']:.3f} | "
                     f"{p['attributed_ms'] / total * 100:.0f}% |")
    lines.append("")
    lines.append(f"micro-kernel coverage: {out['coverage']:.2f}x of the "
                 "fused tick (1.0 = isolated phases account for the whole "
                 "tick; drift means the micro-kernels need re-syncing with "
                 "kernel.py)")
    if out["missing_scopes"]:
        lines.append(f"WARNING: named_scope seams missing from compiled "
                     f"HLO: {out['missing_scopes']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--quick", action="store_true",
                    help="3 reps instead of 10")
    ap.add_argument("--json", action="store_true",
                    help="print one JSON line instead of markdown")
    ap.add_argument("--capture", metavar="DIR", default=None,
                    help="wrap the timed loop in jax.profiler.trace(DIR)")
    ap.add_argument("--bench-json", metavar="PATH", default=None,
                    help="append a bench-shaped JSON line (compile_seconds"
                         "/peak_bytes/phases) to PATH for bench_gate")
    ap.add_argument("--verify-scopes", action="store_true",
                    help="exit nonzero if any named_scope seam is missing "
                         "from the compiled HLO")
    args = ap.parse_args(argv)

    out = run_profile(args.n, quick=args.quick, capture_dir=args.capture)
    if args.json:
        print(json.dumps(out), flush=True)
    else:
        print(render(out), flush=True)
    if args.bench_json:
        line = {"profile_n": out["n"],
                "compile_seconds": out["compile_seconds"],
                "tick_ms": out["tick_ms"],
                "phases_ms": {k: v["attributed_ms"]
                              for k, v in out["phases"].items()}}
        if out["peak_bytes"]:
            line["peak_bytes"] = out["peak_bytes"]
        with open(args.bench_json, "a", encoding="utf-8") as f:
            f.write(json.dumps(line) + "\n")
    if args.verify_scopes and out["missing_scopes"]:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

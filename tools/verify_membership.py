"""Round-4 verify: log-driven membership on the device kernel, driven
through the PUBLIC sim API only (init_state/propose_conf/step/run_*)."""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from swarmkit_tpu.raft.sim import (
    LEADER, SimConfig, committed_entries, init_state, propose, propose_conf,
    run_until_leader, step,
)

cfg = SimConfig(n=16, log_len=256, window=16, apply_batch=64, max_props=32,
                keep=16, seed=42, pre_vote=True)
# 1. bootstrap a 9-voter subset of 16 rows
state = init_state(cfg, voters=range(9))
state, ticks = run_until_leader(state, cfg, max_ticks=500)
self_mem = np.asarray(state.member).diagonal()
lead = int(np.flatnonzero(np.asarray(state.role == LEADER) & self_mem)[0])
assert lead < 9, "leader outside bootstrap config"
print(f"1. elected leader {lead} in {int(ticks)} ticks (9-voter bootstrap)")

# 2. commit traffic, then grow the cluster one row at a time via CONF entries
pl = jnp.arange(cfg.max_props, dtype=jnp.uint32) + 1
for joiner in range(9, 16):
    state = propose_conf(state, cfg, joiner, False)
    for _ in range(6):
        state = propose(state, cfg, pl, 8)
        state = step(state, cfg)
member = np.asarray(state.member)
assert member[:9, 9:].all(), "adds did not reach bootstrap rows"
assert member.diagonal()[9:].all(), "joiners never learned membership"
print(f"2. grew 9 -> 16 via committed CONF entries; commit={int(committed_entries(state))}")

# 3. now quorum is 9 of 16: crash 7 rows — survivors are EXACTLY quorum.
# This regime livelocks under etcd-3.1's campaign-reset lease; the
# contact-based lease (core.contact_elapsed / kernel `contact`) recovers.
alive = jnp.ones((cfg.n,), bool).at[jnp.arange(7)].set(False)  # kill 0..6
for _ in range(120):
    state = step(state, cfg, alive=alive)
    if (np.asarray(state.role)[7:] == LEADER).any():
        break
role = np.asarray(state.role)
live_leader = [i for i in range(7, 16) if role[i] == LEADER]
assert live_leader, "no leader among 9 survivors (quorum 9/16 should hold)"
base = int(committed_entries(state))
for _ in range(15):
    state = propose(state, cfg, pl, 8, alive=alive)
    state = step(state, cfg, alive=alive)
    if int(committed_entries(state)) >= base + 8:
        break
assert int(committed_entries(state)) >= base + 8
print(f"3. exact-quorum survivorship (7 crashed) elects leader {live_leader[0]}; commits advance")

# 4. shrink back: remove a crashed row via the log — quorum drops to 8/15
state = propose_conf(state, cfg, 0, True, alive=alive)
for _ in range(10):
    state = step(state, cfg, alive=alive)
m = np.asarray(state.member)
live = [i for i in range(7, 16)]
assert not m[live, 0].any(), "removal did not apply on live rows"
print("4. removed crashed row 0 through the replicated log")

# 5. state-machine safety: equal applied => equal checksum
applied = np.asarray(state.applied); chk = np.asarray(state.apply_chk)
by = {}
for a, c in zip(applied.tolist(), chk.tolist()):
    assert by.setdefault(a, c) == c, "checksum divergence"
print("5. state-machine safety holds across membership churn")
print("VERIFY-MEMBERSHIP: OK")

"""Round-4 verify: drive the new control-plane surfaces end to end on a
real swarmd over its control socket — service-logs (follow/tail),
service-update with update-config flags, service-rollback, host+ingress
ports, templated secret payloads."""
import asyncio, io, json, os, sys, tempfile
sys.path.insert(0, "/root/repo")
import tests.conftest
from swarmkit_tpu.cmd import swarmctl as ctl_cmd
from swarmkit_tpu.cmd import swarmd


async def main():
    tmp = tempfile.TemporaryDirectory(prefix="verify-cp-")
    sock = os.path.join(tmp.name, "swarmd.sock")
    args = swarmd.build_parser().parse_args([
        "--state-dir", os.path.join(tmp.name, "state"),
        "--listen-control-api", sock,
        "--node-id", "m1", "--manager", "--election-tick", "4",
        "--backend", "inproc", "--executor", "test"])
    node = await swarmd.run(args)
    try:
        while not node.is_leader():
            await asyncio.sleep(0.05)

        async def ctl(*argv):
            out = io.StringIO()
            rc = await ctl_cmd.run(ctl_cmd.build_parser().parse_args(
                ["--socket", sock, *argv]), out=out)
            return rc, out.getvalue()

        rc, out = await ctl("service-create", "--name", "app",
                            "--image", "v1", "--replicas", "2",
                            "--publish", "8080:80")
        assert rc == 0
        svc = json.loads(out)["id"]
        for _ in range(200):
            rc, out = await ctl("task-ls", "--service", svc)
            if out.count("RUNNING") == 2:
                break
            await asyncio.sleep(0.05)
        assert out.count("RUNNING") == 2
        rc, out = await ctl("service-inspect", svc)
        ep = json.loads(out)["endpoint"]
        assert ep["ports"][0]["published_port"] == 8080
        print("1. service with published port running (8080->80 ingress)")

        for c in node.config.executor.controllers.values():
            if c.task.service_id == svc:
                c.write_log("app line")
        rc, out = await ctl("service-logs", svc, "--tail", "10")
        assert rc == 0 and "app line" in out and "started" in out
        print("2. swarmctl service-logs tails task output:")
        print("   " + out.strip().splitlines()[0])

        rc, out = await ctl("service-update", svc, "--image", "v2",
                            "--update-parallelism", "1",
                            "--update-order", "start-first",
                            "--update-monitor", "0.2")
        assert rc == 0
        for _ in range(300):
            rc, out = await ctl("service-inspect", svc)
            st = json.loads(out).get("update_status") or {}
            if st.get("state") == "completed":
                break
            await asyncio.sleep(0.05)
        assert st.get("state") == "completed"
        print("3. rolling update v1 -> v2 (start-first, parallelism 1) completed")

        rc, out = await ctl("service-rollback", svc)
        assert rc == 0
        assert json.loads(out)["spec"]["task"]["container"]["image"] == "v1"
        print("4. service-rollback restored v1")
        print("VERIFY-CONTROLPLANE: OK")
    finally:
        await node._ctl_server.stop()
        await node.stop()

asyncio.run(main())

"""Metric-name lint: the catalog is the single ground truth.

Checks, in order:

1. Every catalog spec instantiates cleanly into a strict registry —
   catches bad names, empty help text, invalid label names, and
   non-increasing histogram bucket edges through the registry's own
   validation.
2. No two specs render to colliding exposition series (a histogram's
   ``_bucket``/``_sum``/``_count`` suffixes must not shadow another
   family, and vice versa).
3. Every ``swarm_*`` metric-name literal in the source tree (package +
   tools + bench.py, tests excluded) resolves to a catalog entry, so an
   instrumentation site cannot invent a name the scrape page never
   documents.
4. Every catalog entry is referenced somewhere outside the catalog —
   dead specs rot; delete or wire them.
5. The flight-recorder event vocabulary (``flightrec/codes.py``) stays
   publishable AND internally consistent: every code name must fit the
   ``swarm_flightrec_events_total{code=...}`` schema, the capture
   counter must keep its ``trigger`` label, every ``CODE_NAMES`` entry
   must name a module constant carrying that exact value (names unique),
   and every uppercase int event constant — arg-value enums like
   ``EDGE_*``/``BLOCK_*`` and ``EVENT_WIDTH`` excepted — must appear in
   ``CODE_NAMES``, so the device vocabulary and the scrape-side schema
   cannot drift apart.
6. The telemetry plane stays in the same lockstep: every
   ``swarm_telemetry_*`` histogram's bucket edges must equal
   ``telemetry.series.LATENCY_BUCKET_EDGES`` (the catalog duplicates
   them as literals to avoid an import cycle), the series gauge must be
   labeled ``series=`` and publishable for every ``SERIES_NAMES`` entry,
   and the ``SERIES_*`` index enum must mirror ``SERIES_NAMES`` exactly
   (both directions), with ``NUM_BUCKETS``/``NUM_SERIES`` consistent.
7. The model-checker names (``mc/metrics.py METRIC_NAMES``) and the
   ``swarm_mc_*`` catalog entries mirror each other exactly, and every
   declared label publishes with its sample value.
8. The dst attack suite stays wired end to end: every profile in
   ``dst.schedule.ATTACK_PROFILES`` is requestable (EXTRA_PROFILES +
   generator), drives a real FaultSchedule leaf (``ATTACK_LEAVES``),
   owns a flightrec signature code (``ATTACK_SIGNATURE_CODES`` naming a
   ``CODE_NAMES`` entry), and publishes under
   ``swarm_dst_attack_ticks_total{attack=...}`` — an attack verb cannot
   land without scrape-side accounting and a post-mortem signature.
9. The durability boundary (ISSUE 16) stays wired the same way: every
   ``dst.schedule.STORAGE_PROFILES`` entry is requestable, drives a
   FaultSchedule leaf (``STORAGE_LEAVES``), owns a signature code
   (``STORAGE_SIGNATURE_CODES``), and publishes under the attack
   counter; the ``FSYNC_*``/``RECOVER_*``/``SNAP_CORRUPT`` flightrec
   codes exist in ``CODE_NAMES``; the ``swarm_kernel_fsync_lag`` gauge
   and ``swarm_kernel_durable_commit_advance_total`` counter are in the
   catalog; and the DURABILITY / RECOVERY_MONOTONIC / SLO_FSYNC_LAG
   invariant bits are named in the DST artifact schema
   (``invariants.BIT_NAMES``).

10. The causal-trace fusion layer (ISSUE 17) stays wired: the
    ``swarm_trace_*`` clock/flow metrics exist with the right kinds and
    the orphan counter publishes both its ``side`` values; the tagged
    flight-ring row is exactly one lane wider than the base row
    (``EVENT_WIDTH_TAGGED == EVENT_WIDTH + 1``); every
    ``TAGGED_CODES`` member is a ``CODE_NAMES`` code (the decoder keys
    tag semantics off names); and the decoder's ``FlightEvent`` carries
    the ``tag`` field the tagged lane decodes into.

11. The multi-raft serving plane (ISSUE 18) keeps its names honest:
    the ``swarm_multiraft_*`` constants (``multiraft/obs.py
    METRIC_NAMES``) and the catalog mirror each other exactly in both
    directions, every declared label publishes with its sample value,
    and every label has a ``SAMPLE_LABELS`` entry — same lockstep as
    check #7.

12. The vectorized control plane (ISSUE 19) keeps the same lockstep:
    the ``swarm_cpl_*`` names the coalescing proposal pipeline publishes
    (``store/pipeline.py METRIC_NAMES``) and the ``swarm_sched_kernel_*``
    names the jitted scheduler kernel publishes
    (``manager/scheduler/kernel.py METRIC_NAMES``) mirror the catalog in
    both directions, every declared label publishes with its sample
    value, and every label has a ``SAMPLE_LABELS`` entry.

13. The fleet health plane (ISSUE 20) keeps the same lockstep: the
    ``swarm_slo_*`` names the burn-rate engine publishes
    (``slo/engine.py METRIC_NAMES``) mirror the catalog in both
    directions with publishable sample labels, every ``SLO_CATALOG``
    spec name publishes as a valid ``slo=`` label value, and the
    per-group heat gauge the engine's sibling detector feeds
    (``swarm_multiraft_group_heat``) stays wired: a
    ``multiraft/obs.py`` constant (check #11 territory) labeled by
    group, with ``multiraft/heat.py`` exposing ``SPILL_WEIGHT`` and
    ``HeatTracker.hottest_groups``.

Importable (``run_lint`` returns the problem list) so the pytest wrapper
in tests/test_metrics_lint.py runs it in-suite; the CLI exits nonzero on
any finding.

Usage: python tools/metrics_lint.py
"""

from __future__ import annotations

import ast
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_NAME = re.compile(r"^swarm_[a-z0-9_]+$")
_HISTO_SUFFIXES = ("_bucket", "_sum", "_count")


def _source_files(repo_root: str):
    roots = (os.path.join(repo_root, "swarmkit_tpu"),
             os.path.join(repo_root, "tools"))
    for root in roots:
        for dirpath, _dirs, files in os.walk(root):
            for f in files:
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)
    yield os.path.join(repo_root, "bench.py")


def _metric_literals(path: str) -> set[str]:
    """All string constants in `path` shaped like a swarm_ metric name."""
    with open(path, encoding="utf-8") as f:
        try:
            tree = ast.parse(f.read(), filename=path)
        except SyntaxError:
            return set()
    return {node.value for node in ast.walk(tree)
            if isinstance(node, ast.Constant)
            and isinstance(node.value, str) and _NAME.match(node.value)}


def run_lint(repo_root: str | None = None) -> list[str]:
    """Returns a list of human-readable problems (empty = clean)."""
    from swarmkit_tpu.metrics import catalog
    from swarmkit_tpu.metrics.registry import MetricError, MetricsRegistry

    repo_root = repo_root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    problems: list[str] = []

    # 1. every spec instantiates under strict validation
    reg = MetricsRegistry(strict=True)
    for name in catalog.CATALOG:
        try:
            catalog.get(reg, name)
        except (MetricError, ValueError, KeyError) as e:
            problems.append(f"catalog entry {name!r} is invalid: {e}")

    # 2. exposition-series collisions across families
    series: dict[str, str] = {}
    for name, spec in catalog.CATALOG.items():
        rendered = ([name + s for s in _HISTO_SUFFIXES]
                    if spec.kind == "histogram" else [name])
        for r in rendered:
            if r in series:
                problems.append(
                    f"exposition collision: {name!r} renders series {r!r} "
                    f"already produced by {series[r]!r}")
            series[r] = name

    # 3+4. cross-reference source literals with the catalog
    catalog_path = os.path.join(
        repo_root, "swarmkit_tpu", "metrics", "catalog.py")
    used: set[str] = set()
    for path in _source_files(repo_root):
        if os.path.abspath(path) == os.path.abspath(catalog_path):
            continue
        for name in _metric_literals(path):
            used.add(name)
            if name in catalog.LEGACY_SERIES \
                    or name.startswith(catalog.LEGACY_PREFIXES):
                continue
            base = name
            for suf in _HISTO_SUFFIXES:
                if name.endswith(suf) and name[:-len(suf)] in catalog.CATALOG:
                    base = name[:-len(suf)]
                    break
            if base not in catalog.CATALOG:
                problems.append(
                    f"{os.path.relpath(path, repo_root)}: metric name "
                    f"{name!r} is not in the catalog")
    for name in catalog.CATALOG:
        if name not in used:
            problems.append(f"catalog entry {name!r} is never referenced "
                            "outside the catalog (dead spec?)")

    # 5. flight-recorder wiring: every event code in the device vocabulary
    #    must publish under swarm_flightrec_events_total{code=...} — a code
    #    added to flightrec/codes.py without scrape-side room (or a label
    #    schema drift on the counter) breaks post-mortem accounting silently
    from swarmkit_tpu.flightrec import codes as flight_codes

    ev_spec = catalog.CATALOG.get("swarm_flightrec_events_total")
    if ev_spec is None:
        problems.append("flightrec: 'swarm_flightrec_events_total' missing "
                        "from the catalog")
    elif tuple(ev_spec.labels) != ("code",):
        problems.append("flightrec: 'swarm_flightrec_events_total' must be "
                        f"labeled by ('code',), got {tuple(ev_spec.labels)}")
    else:
        fam = catalog.get(MetricsRegistry(strict=True),
                          "swarm_flightrec_events_total")
        for code in sorted(flight_codes.CODE_NAMES):
            try:
                fam.labels(code=flight_codes.CODE_NAMES[code]).inc(0)
            except MetricError as e:
                problems.append(f"flightrec: event code "
                                f"{flight_codes.CODE_NAMES[code]!r} cannot "
                                f"publish: {e}")
    cap_spec = catalog.CATALOG.get("swarm_flightrec_captures_total")
    if cap_spec is None or "trigger" not in tuple(cap_spec.labels):
        problems.append("flightrec: 'swarm_flightrec_captures_total' must "
                        "exist with a 'trigger' label")

    #    ... and the vocabulary itself cannot drift: CODE_NAMES entries
    #    must mirror the module constants exactly, and no event constant
    #    may be missing from CODE_NAMES (the decoder and the events
    #    counter both key on the names)
    code_names = list(flight_codes.CODE_NAMES.values())
    if len(set(code_names)) != len(code_names):
        problems.append("flightrec: duplicate event names in CODE_NAMES")
    for code, cname in flight_codes.CODE_NAMES.items():
        if getattr(flight_codes, cname, None) != code:
            problems.append(
                f"flightrec: CODE_NAMES[{code}] = {cname!r} but the module "
                f"constant {cname} = {getattr(flight_codes, cname, None)!r}")
    non_codes = {"EVENT_WIDTH", "EVENT_WIDTH_TAGGED"}
    arg_prefixes = ("EDGE_", "BLOCK_")
    for attr, val in vars(flight_codes).items():
        if (attr.isupper() and isinstance(val, int)
                and attr not in non_codes
                and not attr.startswith(arg_prefixes)
                and attr not in code_names):
            problems.append(f"flightrec: event constant {attr} = {val} is "
                            "missing from CODE_NAMES")

    # 6. telemetry wiring: the catalog's swarm_telemetry_* schema must stay
    #    in lockstep with the device-side layout (telemetry/series.py) the
    #    same way check #5 pins the flightrec vocabulary — bucket edges are
    #    duplicated as literals in the catalog (import-cycle break), so
    #    equality is enforced here instead of by construction
    from swarmkit_tpu.telemetry import series as tel_series

    want_buckets = tuple(float(e) for e in tel_series.LATENCY_BUCKET_EDGES)
    for hname in ("swarm_telemetry_commit_latency_ticks",
                  "swarm_telemetry_election_ticks",
                  "swarm_telemetry_read_latency_ticks"):
        spec = catalog.CATALOG.get(hname)
        if spec is None or spec.kind != "histogram":
            problems.append(f"telemetry: {hname!r} missing from the catalog "
                            "or not a histogram")
        elif tuple(spec.buckets or ()) != want_buckets:
            problems.append(
                f"telemetry: {hname!r} bucket edges {spec.buckets} diverge "
                f"from telemetry.series.LATENCY_BUCKET_EDGES {want_buckets}")
    sv_spec = catalog.CATALOG.get("swarm_telemetry_series_value")
    if sv_spec is None or tuple(sv_spec.labels) != ("series",):
        problems.append("telemetry: 'swarm_telemetry_series_value' must "
                        "exist labeled by ('series',)")
    else:
        fam = catalog.get(MetricsRegistry(strict=True),
                          "swarm_telemetry_series_value")
        for sname in tel_series.SERIES_NAMES.values():
            try:
                fam.labels(series=sname).set(0)
            except MetricError as e:
                problems.append(f"telemetry: series {sname!r} cannot "
                                f"publish: {e}")

    #    ... and the series index enum cannot drift from SERIES_NAMES
    #    (ring rows and the scrape/decode side both key on it)
    tel_names = list(tel_series.SERIES_NAMES.values())
    if len(set(tel_names)) != len(tel_names):
        problems.append("telemetry: duplicate names in SERIES_NAMES")
    for idx, sname in tel_series.SERIES_NAMES.items():
        const = f"SERIES_{sname.upper()}"
        if getattr(tel_series, const, None) != idx:
            problems.append(
                f"telemetry: SERIES_NAMES[{idx}] = {sname!r} but the module "
                f"constant {const} = {getattr(tel_series, const, None)!r}")
    for attr, val in vars(tel_series).items():
        if (attr.startswith("SERIES_") and isinstance(val, int)
                and attr != "SERIES_NAMES" and val not in tel_series.SERIES_NAMES):
            problems.append(f"telemetry: series constant {attr} = {val} is "
                            "missing from SERIES_NAMES")
    if tel_series.NUM_BUCKETS != len(tel_series.LATENCY_BUCKET_EDGES) + 1:
        problems.append("telemetry: NUM_BUCKETS must be "
                        "len(LATENCY_BUCKET_EDGES) + 1")
    if tel_series.NUM_SERIES != len(tel_series.SERIES_NAMES):
        problems.append("telemetry: NUM_SERIES must equal len(SERIES_NAMES)")

    # 7. model-checker wiring: every swarm_mc_* name the scanner publishes
    #    (mc/metrics.py METRIC_NAMES) must exist in the catalog with exactly
    #    the declared label set, and every swarm_mc_* catalog entry must be
    #    one the scanner knows — same two-way lockstep as checks #5/#6
    from swarmkit_tpu.mc import metrics as mc_metrics

    for name, labels in mc_metrics.METRIC_NAMES.items():
        spec = catalog.CATALOG.get(name)
        if spec is None:
            problems.append(f"mc: {name!r} (mc/metrics.py) missing from "
                            "the catalog")
            continue
        if tuple(spec.labels) != tuple(labels):
            problems.append(
                f"mc: {name!r} labels {tuple(spec.labels)} diverge from "
                f"mc.metrics.METRIC_NAMES {tuple(labels)}")
            continue
        fam = catalog.get(MetricsRegistry(strict=True), name)
        kwargs = {lb: mc_metrics.SAMPLE_LABELS[lb] for lb in labels}
        try:
            if spec.kind == "gauge":
                fam.labels(**kwargs).set(0)
            else:
                fam.labels(**kwargs).inc(0)
        except (MetricError, KeyError) as e:
            problems.append(f"mc: {name!r} cannot publish with sample "
                            f"labels {kwargs}: {e}")
    # built from pieces so check #3's literal scan skips this prefix
    mc_prefix = "_".join(("swarm", "mc", ""))
    for name in catalog.CATALOG:
        if name.startswith(mc_prefix) \
                and name not in mc_metrics.METRIC_NAMES:
            problems.append(f"mc: catalog entry {name!r} has no "
                            "mc/metrics.py constant (scanner can't "
                            "publish it)")
    for lb in {l for ls in mc_metrics.METRIC_NAMES.values() for l in ls}:
        if lb not in mc_metrics.SAMPLE_LABELS:
            problems.append(f"mc: label {lb!r} missing from "
                            "mc.metrics.SAMPLE_LABELS")

    # 8. attack-suite wiring: the dst adversary profiles, their schedule
    #    leaves, their flightrec signature codes, and the attack counter
    #    stay in the same lockstep as #5-#7
    import dataclasses as _dc

    from swarmkit_tpu.dst import schedule as dst_schedule

    att_fam = None
    att_spec = catalog.CATALOG.get("swarm_dst_attack_ticks_total")
    if att_spec is None or tuple(att_spec.labels) != ("attack",):
        problems.append("attacks: 'swarm_dst_attack_ticks_total' must "
                        "exist labeled by ('attack',)")
    else:
        att_fam = catalog.get(MetricsRegistry(strict=True),
                              "swarm_dst_attack_ticks_total")
    sched_fields = {f.name for f in
                    _dc.fields(dst_schedule.FaultSchedule)}
    for prof in dst_schedule.ATTACK_PROFILES:
        if prof not in dst_schedule.EXTRA_PROFILES:
            problems.append(f"attacks: profile {prof!r} missing from "
                            "EXTRA_PROFILES (make_schedule can't name it)")
        if prof not in dst_schedule._GENERATORS:
            problems.append(f"attacks: profile {prof!r} has no "
                            "_GENERATORS entry")
        leaf = dst_schedule.ATTACK_LEAVES.get(prof)
        if leaf is None or leaf not in sched_fields:
            problems.append(f"attacks: profile {prof!r} has no "
                            f"FaultSchedule leaf (ATTACK_LEAVES -> {leaf!r})")
        cname = dst_schedule.ATTACK_SIGNATURE_CODES.get(prof)
        if cname is None \
                or cname not in flight_codes.CODE_NAMES.values():
            problems.append(
                f"attacks: profile {prof!r} signature code {cname!r} is "
                "not a flightrec CODE_NAMES entry")
        if att_fam is not None:
            try:
                att_fam.labels(attack=prof).inc(0)
            except MetricError as e:
                problems.append(f"attacks: profile {prof!r} cannot "
                                f"publish: {e}")
    for extra in sorted((set(dst_schedule.ATTACK_LEAVES)
                         | set(dst_schedule.ATTACK_SIGNATURE_CODES))
                        - set(dst_schedule.ATTACK_PROFILES)):
        problems.append(f"attacks: {extra!r} wired in ATTACK_LEAVES/"
                        "ATTACK_SIGNATURE_CODES but absent from "
                        "ATTACK_PROFILES")

    # 9. durability-boundary wiring (ISSUE 16): storage-fault profiles,
    #    their leaves and signature codes, the fsync/recovery metrics,
    #    and the new invariant bits, pinned like check #8
    from swarmkit_tpu.dst import invariants as dst_invariants

    for prof in dst_schedule.STORAGE_PROFILES:
        if prof not in dst_schedule.EXTRA_PROFILES:
            problems.append(f"storage: profile {prof!r} missing from "
                            "EXTRA_PROFILES (make_schedule can't name it)")
        if prof not in dst_schedule._GENERATORS:
            problems.append(f"storage: profile {prof!r} has no "
                            "_GENERATORS entry")
        leaf = dst_schedule.STORAGE_LEAVES.get(prof)
        if leaf is None or leaf not in sched_fields:
            problems.append(f"storage: profile {prof!r} has no "
                            f"FaultSchedule leaf (STORAGE_LEAVES -> "
                            f"{leaf!r})")
        if leaf is not None and leaf not in dst_schedule._OPTIONAL_LEAVES:
            problems.append(f"storage: leaf {leaf!r} missing from "
                            "_OPTIONAL_LEAVES (artifacts can't carry it)")
        cname = dst_schedule.STORAGE_SIGNATURE_CODES.get(prof)
        if cname is None \
                or cname not in flight_codes.CODE_NAMES.values():
            problems.append(
                f"storage: profile {prof!r} signature code {cname!r} is "
                "not a flightrec CODE_NAMES entry")
        if att_fam is not None:
            try:
                att_fam.labels(attack=prof).inc(0)
            except MetricError as e:
                problems.append(f"storage: profile {prof!r} cannot "
                                f"publish: {e}")
    for extra in sorted((set(dst_schedule.STORAGE_LEAVES)
                         | set(dst_schedule.STORAGE_SIGNATURE_CODES))
                        - set(dst_schedule.STORAGE_PROFILES)):
        problems.append(f"storage: {extra!r} wired in STORAGE_LEAVES/"
                        "STORAGE_SIGNATURE_CODES but absent from "
                        "STORAGE_PROFILES")
    for cname in ("FSYNC_ADVANCE", "RECOVER_TRUNCATE",
                  "RECOVER_REJECT_SNAP", "RECOVER_TORN", "FSYNC_STALL",
                  "SNAP_CORRUPT"):
        if cname not in flight_codes.CODE_NAMES.values():
            problems.append(f"storage: flightrec code {cname} missing "
                            "from CODE_NAMES")
    for mname, kind in (("swarm_kernel_fsync_lag", "gauge"),
                        ("swarm_kernel_durable_commit_advance_total",
                         "counter")):
        spec = catalog.CATALOG.get(mname)
        if spec is None or spec.kind != kind:
            problems.append(f"storage: {mname!r} missing from the catalog "
                            f"or not a {kind}")
    for bname in ("durability", "recovery_monotonic", "slo_fsync_lag"):
        if bname not in dst_invariants.BIT_NAMES.values():
            problems.append(f"storage: invariant bit {bname!r} missing "
                            "from invariants.BIT_NAMES (artifact schema)")

    # 10. causal-trace fusion wiring (ISSUE 17): the trace-tag lane, its
    #     decoder field, and the swarm_trace_* clock/flow metrics stay in
    #     lockstep across codes.py / decoder.py / export.py / catalog
    import dataclasses as _dc10

    from swarmkit_tpu.flightrec import decoder as flight_decoder

    for mname, kind in (("swarm_trace_clock_sync_points_total", "counter"),
                        ("swarm_trace_clock_tick_us", "gauge"),
                        ("swarm_trace_clock_residual_us", "gauge"),
                        ("swarm_trace_flow_events_total", "counter"),
                        ("swarm_trace_flow_orphans_total", "counter")):
        spec = catalog.CATALOG.get(mname)
        if spec is None or spec.kind != kind:
            problems.append(f"trace: {mname!r} missing from the catalog "
                            f"or not a {kind}")
    orph_spec = catalog.CATALOG.get("swarm_trace_flow_orphans_total")
    if orph_spec is None or tuple(orph_spec.labels) != ("side",):
        problems.append("trace: 'swarm_trace_flow_orphans_total' must be "
                        "labeled by ('side',)")
    else:
        fam = catalog.get(MetricsRegistry(strict=True),
                          "swarm_trace_flow_orphans_total")
        for side in ("host_only", "device_only"):
            try:
                fam.labels(side=side).inc(0)
            except MetricError as e:
                problems.append(f"trace: orphan side {side!r} cannot "
                                f"publish: {e}")
    if flight_codes.EVENT_WIDTH_TAGGED != flight_codes.EVENT_WIDTH + 1:
        problems.append(
            f"trace: EVENT_WIDTH_TAGGED = {flight_codes.EVENT_WIDTH_TAGGED} "
            f"must be EVENT_WIDTH + 1 = {flight_codes.EVENT_WIDTH + 1} "
            "(one trace-tag lane on top of the base row)")
    for code in sorted(flight_codes.TAGGED_CODES):
        if code not in flight_codes.CODE_NAMES:
            problems.append(f"trace: TAGGED_CODES member {code} is not a "
                            "CODE_NAMES code")
    ev_fields = {f.name for f in _dc10.fields(flight_decoder.FlightEvent)}
    if "tag" not in ev_fields:
        problems.append("trace: decoder.FlightEvent lacks the 'tag' field "
                        "the tagged lane decodes into")

    # 11. multi-raft serving-plane wiring (ISSUE 18): the swarm_multiraft_*
    #     names the plane publishes (multiraft/obs.py METRIC_NAMES) and the
    #     catalog stay in the same two-way lockstep as checks #5-#7
    from swarmkit_tpu.multiraft import obs as mr_obs

    for name, labels in mr_obs.METRIC_NAMES.items():
        spec = catalog.CATALOG.get(name)
        if spec is None:
            problems.append(f"multiraft: {name!r} (multiraft/obs.py) "
                            "missing from the catalog")
            continue
        if tuple(spec.labels) != tuple(labels):
            problems.append(
                f"multiraft: {name!r} labels {tuple(spec.labels)} diverge "
                f"from multiraft.obs.METRIC_NAMES {tuple(labels)}")
            continue
        fam = catalog.get(MetricsRegistry(strict=True), name)
        kwargs = {lb: mr_obs.SAMPLE_LABELS[lb] for lb in labels}
        try:
            if spec.kind == "gauge":
                fam.labels(**kwargs).set(0)
            else:
                fam.labels(**kwargs).inc(0)
        except (MetricError, KeyError) as e:
            problems.append(f"multiraft: {name!r} cannot publish with "
                            f"sample labels {kwargs}: {e}")
    # built from pieces so check #3's literal scan skips this prefix
    mr_prefix = "_".join(("swarm", "multiraft", ""))
    for name in catalog.CATALOG:
        if name.startswith(mr_prefix) \
                and name not in mr_obs.METRIC_NAMES:
            problems.append(f"multiraft: catalog entry {name!r} has no "
                            "multiraft/obs.py constant (the serving plane "
                            "can't publish it)")
    for lb in {l for ls in mr_obs.METRIC_NAMES.values() for l in ls}:
        if lb not in mr_obs.SAMPLE_LABELS:
            problems.append(f"multiraft: label {lb!r} missing from "
                            "multiraft.obs.SAMPLE_LABELS")

    # 12. vectorized-control-plane wiring (ISSUE 19): the coalescing
    #     proposal pipeline (store/pipeline.py, swarm_cpl_*) and the
    #     jitted scheduler kernel (manager/scheduler/kernel.py,
    #     swarm_sched_kernel_*) keep the same two-way catalog lockstep
    #     as checks #7/#11
    from swarmkit_tpu.manager.scheduler import kernel as sched_kernel
    from swarmkit_tpu.store import pipeline as cpl_pipeline

    for tag, mod, prefix_parts in (
            ("cpl", cpl_pipeline, ("swarm", "cpl", "")),
            ("sched-kernel", sched_kernel, ("swarm", "sched", "kernel",
                                            ""))):
        for name, labels in mod.METRIC_NAMES.items():
            spec = catalog.CATALOG.get(name)
            if spec is None:
                problems.append(f"{tag}: {name!r} ({mod.__name__}) "
                                "missing from the catalog")
                continue
            if tuple(spec.labels) != tuple(labels):
                problems.append(
                    f"{tag}: {name!r} labels {tuple(spec.labels)} diverge "
                    f"from {mod.__name__}.METRIC_NAMES {tuple(labels)}")
                continue
            fam = catalog.get(MetricsRegistry(strict=True), name)
            kwargs = {lb: mod.SAMPLE_LABELS[lb] for lb in labels}
            try:
                if spec.kind == "gauge":
                    fam.labels(**kwargs).set(0)
                elif spec.kind == "histogram":
                    fam.labels(**kwargs).observe(0)
                else:
                    fam.labels(**kwargs).inc(0)
            except (MetricError, KeyError) as e:
                problems.append(f"{tag}: {name!r} cannot publish with "
                                f"sample labels {kwargs}: {e}")
        # built from pieces so check #3's literal scan skips the prefix
        prefix = "_".join(prefix_parts)
        for name in catalog.CATALOG:
            if name.startswith(prefix) and name not in mod.METRIC_NAMES:
                problems.append(f"{tag}: catalog entry {name!r} has no "
                                f"{mod.__name__} constant (the plane "
                                "can't publish it)")
        for lb in {l for ls in mod.METRIC_NAMES.values() for l in ls}:
            if lb not in mod.SAMPLE_LABELS:
                problems.append(f"{tag}: label {lb!r} missing from "
                                f"{mod.__name__}.SAMPLE_LABELS")

    # 13. fleet health plane (ISSUE 20): the swarm_slo_* names the
    #     burn-rate engine publishes (slo/engine.py METRIC_NAMES) keep
    #     the two-way catalog lockstep of checks #11/#12, every
    #     SLO_CATALOG spec publishes as a slo= label value, and the heat
    #     detector's gauge + ranking API stay wired
    from swarmkit_tpu.multiraft import heat as mr_heat
    from swarmkit_tpu.slo import engine as slo_engine
    from swarmkit_tpu.slo import spec as slo_spec

    for name, labels in slo_engine.METRIC_NAMES.items():
        spec = catalog.CATALOG.get(name)
        if spec is None:
            problems.append(f"slo: {name!r} (slo/engine.py) "
                            "missing from the catalog")
            continue
        if tuple(spec.labels) != tuple(labels):
            problems.append(
                f"slo: {name!r} labels {tuple(spec.labels)} diverge "
                f"from slo.engine.METRIC_NAMES {tuple(labels)}")
            continue
        fam = catalog.get(MetricsRegistry(strict=True), name)
        kwargs = {lb: slo_engine.SAMPLE_LABELS[lb] for lb in labels}
        try:
            if spec.kind == "gauge":
                fam.labels(**kwargs).set(0)
            else:
                fam.labels(**kwargs).inc(0)
        except (MetricError, KeyError) as e:
            problems.append(f"slo: {name!r} cannot publish with "
                            f"sample labels {kwargs}: {e}")
    # built from pieces so check #3's literal scan skips this prefix
    slo_prefix = "_".join(("swarm", "slo", ""))
    for name in catalog.CATALOG:
        if name.startswith(slo_prefix) \
                and name not in slo_engine.METRIC_NAMES:
            problems.append(f"slo: catalog entry {name!r} has no "
                            "slo/engine.py constant (the burn-rate "
                            "engine can't publish it)")
    for lb in {l for ls in slo_engine.METRIC_NAMES.values() for l in ls}:
        if lb not in slo_engine.SAMPLE_LABELS:
            problems.append(f"slo: label {lb!r} missing from "
                            "slo.engine.SAMPLE_LABELS")
    state_fam = catalog.get(MetricsRegistry(strict=True),
                            slo_engine.METRIC_STATE)
    for sspec in slo_spec.SLO_CATALOG:
        try:
            state_fam.labels(slo=sspec.name, group="0").set(0)
        except MetricError as e:
            problems.append(f"slo: SLO_CATALOG entry {sspec.name!r} "
                            f"can't publish as a slo= label: {e}")
    heat_name = "_".join(("swarm", "multiraft", "group", "heat"))
    heat_spec = catalog.CATALOG.get(heat_name)
    if heat_spec is None or heat_spec.kind != "gauge" \
            or tuple(heat_spec.labels) != ("group",):
        problems.append(f"slo: {heat_name!r} must be a catalog gauge "
                        "labeled by ('group',) — the heat detector's "
                        "scrape-side output")
    if not getattr(mr_heat, "SPILL_WEIGHT", 0) > 0:
        problems.append("slo: multiraft.heat.SPILL_WEIGHT must be a "
                        "positive spill-vs-commit fusion weight")
    if not callable(getattr(mr_heat.HeatTracker, "hottest_groups", None)):
        problems.append("slo: multiraft.heat.HeatTracker lacks the "
                        "hottest_groups ranking API the rebalance layer "
                        "keys off")
    return problems


def main() -> int:
    from swarmkit_tpu.metrics import catalog
    problems = run_lint()
    for p in problems:
        print(f"LINT: {p}")
    print(f"{len(problems)} problem(s) across {len(catalog.CATALOG)} "
          "catalog entries")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())

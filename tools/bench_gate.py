"""Bench regression gate: fail when the newest BENCH round collapses.

Reads the BENCH_r*.json trajectory the driver leaves in the repo root
(one file per round: ``{"n", "cmd", "rc", "tail", "parsed"}`` where
``parsed`` is bench.py's one JSON line) and compares the NEWEST round
against the best prior round, per series:

- ``headline`` — ``parsed["value"]`` (committed entries/s);
- one series per numeric entry of ``parsed["configs_entries_per_s"]``
  ("skipped (cpu)"-style strings and 0.0 placeholders are not rates and
  carry no signal);
- one ``<config>:ratio`` series per A/B dict entry (the densepeer and
  sparseprog tripwires): the dict's ``*_over_dense`` value is gated like
  a rate, so the banded/dense and sparse/dense lowering ratios are
  standing regression tripwires, not just logged numbers;
- the resource series ``compile_seconds`` / ``peak_bytes`` (when the
  JSON line carries them), gated in the GROWTH direction — the gate
  fails when the last point exceeds ``1/(1 - tol) x`` the best (lowest)
  prior point, catching compile-time and memory blow-ups the rate
  series can't see.

Rounds with ``rc != 0`` or no parsed line are skipped whole (r01/r02
in this repo's own history: tunnel faults, not regressions).  A series
needs at least two points — one historical, one current — to be gated;
the gate FAILS iff the last point of any gated rate series falls below
``(1 - tol) x`` the best previous point.  The default tolerance is wide
(50%) because rounds run on whatever hardware the driver had that day —
this is a collapse detector, not a benchmark diff.

``check_provenance`` is the green-but-empty detector: a round file whose
``rc`` is 0 and whose ``ok``/``skipped`` flags claim success, but whose
recorded ``tail`` is empty, proves nothing ran and nothing was recorded
(MULTICHIP_r05.json is the motivating specimen — the dry-run used to
print nothing on success).  Findings print as ``PROV`` lines and fail
the CLI under ``--strict-provenance``; genuinely skipped rounds must say
``skipped: true`` with a reason instead.

Usage:
    python tools/bench_gate.py [--tol 0.5] [--strict-provenance] [files...]

Importable: ``run_gate(paths=None, tol=0.5) -> report dict`` and
``check_provenance(paths=None) -> list[str]`` (the slow pytest wrapper
asserts on the report and on an injected regression).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _is_rate(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0


# Series where GROWTH (not collapse) is the regression: gated against the
# lowest prior point instead of the highest.
RESOURCE_SERIES = ("compile_seconds", "peak_bytes")


def _series_points(rounds: list[tuple[str, dict]]) -> dict[str, list]:
    """{series name: [(round name, rate), ...]} in round order."""
    series: dict[str, list] = {}
    for rname, parsed in rounds:
        if _is_rate(parsed.get("value")):
            series.setdefault("headline", []).append(
                (rname, float(parsed["value"])))
        for rs in RESOURCE_SERIES:
            if _is_rate(parsed.get(rs)):
                series.setdefault(rs, []).append(
                    (rname, float(parsed[rs])))
        cfgs = parsed.get("configs_entries_per_s")
        for cname, cv in (cfgs or {}).items() if isinstance(cfgs, dict) else ():
            if _is_rate(cv):
                series.setdefault(cname, []).append((rname, float(cv)))
            elif isinstance(cv, dict):
                # A/B tripwire entry (densepeer / sparseprog): gate the
                # lowering ratio itself
                for k, rv in cv.items():
                    if k.endswith("_over_dense") and _is_rate(rv):
                        series.setdefault(f"{cname}:ratio", []).append(
                            (rname, float(rv)))
    return series


def run_gate(paths=None, tol: float = 0.5) -> dict:
    """Evaluate the gate; returns the report dict (report["ok"] is the
    verdict).  `paths` defaults to the repo-root BENCH_r*.json trajectory;
    name-sorted so r01 < r02 < ... gives round order."""
    if paths is None:
        paths = glob.glob(os.path.join(_ROOT, "BENCH_r*.json"))
    rounds: list[tuple[str, dict]] = []
    skipped: list[str] = []
    for p in sorted(paths, key=os.path.basename):
        name = os.path.basename(p)
        try:
            with open(p, encoding="utf-8") as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            skipped.append(f"{name}: unreadable ({e})")
            continue
        if d.get("rc") != 0 or not isinstance(d.get("parsed"), dict):
            skipped.append(f"{name}: rc={d.get('rc')}, no usable parsed line")
            continue
        rounds.append((name, d["parsed"]))

    report: dict = {"rounds": [n for n, _ in rounds],
                    "skipped_rounds": skipped, "tol": tol,
                    "series": {}, "failures": []}
    for sname, pts in sorted(_series_points(rounds).items()):
        entry: dict = {"points": pts, "gated": len(pts) >= 2}
        if entry["gated"]:
            resource = sname in RESOURCE_SERIES
            prior = [v for _, v in pts[:-1]]
            baseline = min(prior) if resource else max(prior)
            last_round, last = pts[-1]
            entry["baseline"] = baseline
            entry["last"] = last
            entry["ratio"] = round(last / baseline, 4)
            if resource:
                if last > baseline / (1.0 - tol):
                    report["failures"].append(
                        f"{sname}: {last:,.1f} in {last_round} exceeds "
                        f"{1.0 / (1.0 - tol):.2f}x the best prior round "
                        f"({baseline:,.1f})")
            elif last < baseline * (1.0 - tol):
                unit = "" if sname.endswith(":ratio") else " entries/s"
                report["failures"].append(
                    f"{sname}: {last:,.1f}{unit} in {last_round} is below "
                    f"{1.0 - tol:.2f}x the best prior round ({baseline:,.1f})")
        report["series"][sname] = entry
    report["ok"] = not report["failures"]
    return report


def _recorded_number(v) -> bool:
    """A config entry carries a real number: a positive rate, or an A/B
    dict with at least one (bench.py rounds ratios/rates the same way)."""
    if _is_rate(v):
        return True
    return isinstance(v, dict) and any(_recorded_number(x)
                                       for x in v.values())


def check_provenance(paths=None) -> list[str]:
    """Green-but-empty detector over round artifacts.

    Two findings, both unearned greens:

    - a round claiming success (rc=0, ok not false, not skipped) with an
      EMPTY TAIL recorded nothing — the run either printed no provenance
      or the capture lost it (MULTICHIP r02-r05);
    - a green round whose HEADLINE CONFIG recorded no numbers: an
      only-config round (parsed carries ``only_config``) none of whose
      matching ``configs_entries_per_s`` entries is a rate, or a full
      round whose headline ``value`` is not a positive rate — rc=0 with
      nothing measured proves nothing about the config it claims.

    `paths` defaults to the repo-root MULTICHIP_r*.json + BENCH_r*.json
    trajectories."""
    if paths is None:
        paths = (glob.glob(os.path.join(_ROOT, "MULTICHIP_r*.json"))
                 + glob.glob(os.path.join(_ROOT, "BENCH_r*.json")))
    findings: list[str] = []
    for p in sorted(paths, key=os.path.basename):
        name = os.path.basename(p)
        try:
            with open(p, encoding="utf-8") as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            findings.append(f"{name}: unreadable ({e})")
            continue
        green = d.get("rc") == 0 and d.get("ok") is not False \
            and not d.get("skipped")
        if not green:
            continue
        if not str(d.get("tail") or "").strip():
            findings.append(
                f"{name}: green (rc=0, ok={d.get('ok')!r}) but the recorded "
                "tail is empty — nothing proves the run did anything; "
                "record the run's JSON line or set skipped=true with a "
                "reason")
            continue
        parsed = d.get("parsed")
        if not isinstance(parsed, dict):
            continue
        only = parsed.get("only_config")
        cfgs = parsed.get("configs_entries_per_s")
        if only:
            vals = [v for k, v in cfgs.items() if only in k] \
                if isinstance(cfgs, dict) else []
            if not any(_recorded_number(v) for v in vals):
                findings.append(
                    f"{name}: green but headline config {only!r} recorded "
                    "no numbers in configs_entries_per_s — the round "
                    "measured nothing it set out to measure")
        elif not _is_rate(parsed.get("value")):
            findings.append(
                f"{name}: green but the headline recorded no numbers "
                f"(value={parsed.get('value')!r})")
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("files", nargs="*",
                    help="BENCH round JSONs (default: repo-root BENCH_r*.json)")
    ap.add_argument("--tol", type=float, default=0.5,
                    help="allowed fractional drop vs best prior round "
                         "(default 0.5)")
    ap.add_argument("--strict-provenance", action="store_true",
                    help="fail on green-but-empty rounds instead of just "
                         "flagging them")
    args = ap.parse_args(argv)

    prov = check_provenance(paths=args.files or None)
    for p in prov:
        print(f"PROV  {p}", flush=True)

    report = run_gate(paths=args.files or None, tol=args.tol)
    for s in report["skipped_rounds"]:
        print(f"skip  {s}", flush=True)
    for sname, e in report["series"].items():
        if e["gated"]:
            print(f"gate  {sname}: last {e['last']:,.1f} vs baseline "
                  f"{e['baseline']:,.1f} ({e['ratio']:.2f}x)", flush=True)
        else:
            print(f"info  {sname}: {len(e['points'])} point(s), not gated",
                  flush=True)
    for f in report["failures"]:
        print(f"FAIL  {f}", flush=True)
    if not report["series"]:
        print("FAIL  no usable bench rounds found", flush=True)
        return 1
    if prov and args.strict_provenance:
        print(f"FAIL  {len(prov)} green-but-empty round(s)", flush=True)
        return 1
    print("PASS" if report["ok"] else
          f"FAIL  {len(report['failures'])} regressed series", flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

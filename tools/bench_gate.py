"""Bench regression gate: fail when the newest BENCH round collapses.

Reads the BENCH_r*.json trajectory the driver leaves in the repo root
(one file per round: ``{"n", "cmd", "rc", "tail", "parsed"}`` where
``parsed`` is bench.py's one JSON line) and compares the NEWEST round
against the best prior round, per series:

- ``headline`` — ``parsed["value"]`` (committed entries/s);
- one series per numeric entry of ``parsed["configs_entries_per_s"]``
  ("skipped (cpu)"-style strings and 0.0 placeholders are not rates and
  carry no signal);
- one ``<config>:ratio`` series per A/B dict entry (the densepeer and
  sparseprog tripwires): the dict's ``*_over_dense`` value is gated like
  a rate, so the banded/dense and sparse/dense lowering ratios are
  standing regression tripwires, not just logged numbers.

Rounds with ``rc != 0`` or no parsed line are skipped whole (r01/r02
in this repo's own history: tunnel faults, not regressions).  A series
needs at least two points — one historical, one current — to be gated;
the gate FAILS iff the last point of any gated series falls below
``(1 - tol) x`` the best previous point.  The default tolerance is wide
(50%) because rounds run on whatever hardware the driver had that day —
this is a collapse detector, not a benchmark diff.

Usage:
    python tools/bench_gate.py [--tol 0.5] [files...]

Importable: ``run_gate(paths=None, tol=0.5) -> report dict`` (the slow
pytest wrapper asserts on the report and on an injected regression).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _is_rate(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) and v > 0


def _series_points(rounds: list[tuple[str, dict]]) -> dict[str, list]:
    """{series name: [(round name, rate), ...]} in round order."""
    series: dict[str, list] = {}
    for rname, parsed in rounds:
        if _is_rate(parsed.get("value")):
            series.setdefault("headline", []).append(
                (rname, float(parsed["value"])))
        cfgs = parsed.get("configs_entries_per_s")
        for cname, cv in (cfgs or {}).items() if isinstance(cfgs, dict) else ():
            if _is_rate(cv):
                series.setdefault(cname, []).append((rname, float(cv)))
            elif isinstance(cv, dict):
                # A/B tripwire entry (densepeer / sparseprog): gate the
                # lowering ratio itself
                for k, rv in cv.items():
                    if k.endswith("_over_dense") and _is_rate(rv):
                        series.setdefault(f"{cname}:ratio", []).append(
                            (rname, float(rv)))
    return series


def run_gate(paths=None, tol: float = 0.5) -> dict:
    """Evaluate the gate; returns the report dict (report["ok"] is the
    verdict).  `paths` defaults to the repo-root BENCH_r*.json trajectory;
    name-sorted so r01 < r02 < ... gives round order."""
    if paths is None:
        paths = glob.glob(os.path.join(_ROOT, "BENCH_r*.json"))
    rounds: list[tuple[str, dict]] = []
    skipped: list[str] = []
    for p in sorted(paths, key=os.path.basename):
        name = os.path.basename(p)
        try:
            with open(p, encoding="utf-8") as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            skipped.append(f"{name}: unreadable ({e})")
            continue
        if d.get("rc") != 0 or not isinstance(d.get("parsed"), dict):
            skipped.append(f"{name}: rc={d.get('rc')}, no usable parsed line")
            continue
        rounds.append((name, d["parsed"]))

    report: dict = {"rounds": [n for n, _ in rounds],
                    "skipped_rounds": skipped, "tol": tol,
                    "series": {}, "failures": []}
    for sname, pts in sorted(_series_points(rounds).items()):
        entry: dict = {"points": pts, "gated": len(pts) >= 2}
        if entry["gated"]:
            baseline = max(v for _, v in pts[:-1])
            last_round, last = pts[-1]
            entry["baseline"] = baseline
            entry["last"] = last
            entry["ratio"] = round(last / baseline, 4)
            if last < baseline * (1.0 - tol):
                unit = "" if sname.endswith(":ratio") else " entries/s"
                report["failures"].append(
                    f"{sname}: {last:,.1f}{unit} in {last_round} is below "
                    f"{1.0 - tol:.2f}x the best prior round ({baseline:,.1f})")
        report["series"][sname] = entry
    report["ok"] = not report["failures"]
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("files", nargs="*",
                    help="BENCH round JSONs (default: repo-root BENCH_r*.json)")
    ap.add_argument("--tol", type=float, default=0.5,
                    help="allowed fractional drop vs best prior round "
                         "(default 0.5)")
    args = ap.parse_args(argv)

    report = run_gate(paths=args.files or None, tol=args.tol)
    for s in report["skipped_rounds"]:
        print(f"skip  {s}", flush=True)
    for sname, e in report["series"].items():
        if e["gated"]:
            print(f"gate  {sname}: last {e['last']:,.1f} vs baseline "
                  f"{e['baseline']:,.1f} ({e['ratio']:.2f}x)", flush=True)
        else:
            print(f"info  {sname}: {len(e['points'])} point(s), not gated",
                  flush=True)
    for f in report["failures"]:
        print(f"FAIL  {f}", flush=True)
    if not report["series"]:
        print("FAIL  no usable bench rounds found", flush=True)
        return 1
    print("PASS" if report["ok"] else
          f"FAIL  {len(report['failures'])} regressed series", flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""Fault-injection sweep: every fault primitive on every raft wire.

For each (wire, fault plan, seed) triple this tool boots a 3-node raft
cluster, drives one full robustness schedule

    converge -> commit "pre" -> inject fault -> (tolerated) commit under
    fault -> heal (+ process restart for the crash plan) -> converge ->
    commit "post" -> leader transfer -> member removal -> commit "final"

and then runs the cross-member oracle: every surviving member's store must
hold exactly the same object set, including all committed markers.  A
divergence or a liveness stall after ``heal()`` is a failure.

Wires (the three Transport implementations behind one Network seam):
  inproc   in-process asyncio Network, fake clock
  devmesh  DeviceMeshNet mailbox exchange over the 8-device CPU mesh,
           fake clock
  grpc     GrpcNetwork over real sockets, system clock, active health
           probing (tools-level proof that vote-health gating and the
           CanRemoveMember precheck operate across processes)

Plans (swarmkit_tpu.raft.faults.FaultPlan): down, drop, partition, delay,
crash — the crash plan also genuinely stops the victim process and
restarts it from its state dir after ``heal()``.

With ``--peer-chunk`` each selected plan is ALSO lowered to a device
fault schedule (``raft.faults.plan_to_schedule``) and run through the DST
kernel in the requested peer-axis lowering (``SimConfig.peer_chunk``,
banded hierarchical quorum reductions) with a dense cross-check: the
violation bitmasks and first-violation ticks must match bit-for-bit.
This runs the sweep's fault vocabulary in either lowering without code
edits; ``--peer-chunk 0`` pins the dense path only.  ``--active-rows``
does the same for the role-sparse progress lowering
(``SimConfig.active_rows``): a nonzero A runs the [A, N] slab kernel
with a dense-progress cross-check, 0 pins the dense elementwise path.

With ``--attacks`` the sweep instead runs the Byzantine-ish adversary
scenarios (``ATTACK_SCENARIOS``): for each attack a seed-pinned DST
sweep must catch the named invariant bit with the defense off, shrink
the first counterexample to a replay-exact artifact, hold the
differential oracle in lockstep, and come back clean with the defense
on.  These verbs force device kernel state directly, so they run on the
device wire only — each host wire gets an explicit skip row (see
``ATTACK_WIRE_SKIP``) rather than a silent coverage gap.

With ``--storage`` the sweep runs the storage-fault scenarios
(``STORAGE_SCENARIOS``): disk truncation, torn writes, corrupt
snapshots and fsync stalls against the kernel's explicit durability
model (``SimConfig.fsync_lag_ticks`` / ``ack_gating``).  Trip scenarios
follow the attack pipeline — defense-off must catch the named bit,
shrink to a replay-exact artifact, defense-on clean on the SAME
schedules; containment scenarios must stay violation-free while the
recovery signature code proves the fault actually bit.  Like the attack
verbs these force device kernel state, so host wires get explicit skip
rows (the host wire's real storage is covered by the raft/storage.py
truncation-parity tests instead).

Usage:
    python tools/fault_sweep.py                       # full sweep
    python tools/fault_sweep.py --wires grpc --plans crash,partition
    python tools/fault_sweep.py --seeds 2009343,7
    python tools/fault_sweep.py --peer-chunk 8        # + device cross-check
    python tools/fault_sweep.py --active-rows 8       # + sparse cross-check
    python tools/fault_sweep.py --attacks all         # adversary pipeline
    python tools/fault_sweep.py --storage all         # durability pipeline
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import tempfile
import time
from typing import Optional

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import _cli_common  # noqa: E402

from swarmkit_tpu.api import Annotations, Node as ApiNode, NodeSpec  # noqa: E402
from swarmkit_tpu.metrics.registry import MetricsRegistry  # noqa: E402
from swarmkit_tpu.raft.faults import FaultPlan  # noqa: E402
from swarmkit_tpu.raft.node import Node, NodeOpts  # noqa: E402
from swarmkit_tpu.raft.transport import Network  # noqa: E402
from swarmkit_tpu.utils.clock import FakeClock, SystemClock  # noqa: E402

WIRES = ("inproc", "devmesh", "grpc")
PLANS = ("down", "drop", "partition", "delay", "crash")
DEFAULT_SEEDS = (2009343,)

# Byzantine-ish adversary scenarios (--attacks): each row pins the
# defense-off / defense-on SimConfig deltas, the invariant bit that
# witnesses the attack, and the schedule shape validated end-to-end by
# the DST pipeline (catch -> shrink -> artifact -> replay -> oracle).
# These run on the DEVICE wire only — the verbs are FaultSchedule leaves
# that force kernel state arrays (vote registers, election timers,
# transfer requests) between ticks, and the three host wires expose no
# equivalent state-injection seam on a live Node — so the sweep emits an
# explicit per-wire skip row for inproc/devmesh/grpc instead of
# silently narrowing coverage.
ATTACK_SCENARIOS = {
    "disruptive_rejoin": dict(
        off=dict(pre_vote=False, check_quorum=False,
                 collect_telemetry=True, slo_leader_changes=2),
        on=dict(pre_vote=True, check_quorum=True,
                collect_telemetry=True, slo_leader_changes=2),
        ticks=120, prop_count=2, bit="slo_leader_churn",
        defense="PreVote + CheckQuorum"),
    "vote_equivocation": dict(
        # check_quorum off on BOTH sides: the lease refuses the rival's
        # re-requests for the unrelated reason of fresh leader contact,
        # which would mask the vote-guard hole under test
        off=dict(check_quorum=False),
        on=dict(check_quorum=False, vote_guard=True),
        ticks=40, prop_count=2, bit="election_safety",
        defense="persisted-vote guard"),
    "append_flood": dict(
        off=dict(slo_log_occupancy=24),
        on=dict(slo_log_occupancy=24, prop_inflight_cap=8),
        ticks=120, prop_count=0, bit="slo_log_occupancy",
        defense="per-row inflight cap"),
    "transfer_abuse": dict(
        off=dict(collect_telemetry=True, slo_leader_changes=8),
        on=dict(collect_telemetry=True, slo_leader_changes=8,
                transfer_cooldown_ticks=60),
        ticks=120, prop_count=2, bit="slo_leader_churn",
        defense="transfer cooldown"),
}

ATTACK_WIRE_SKIP = (
    "attack verbs force kernel state arrays between ticks; host Node "
    "wires have no state-injection seam (device-only by design)")

# Storage-fault scenarios (--storage): the durability boundary of the
# explicit per-row storage model (SimConfig.fsync_lag_ticks arms the
# sync_mark watermark, SimConfig.ack_gating pins acks/votes/commit to
# it).  Two shapes:
#   mode="trip":    defense-off must CATCH the named invariant bit, the
#                   first counterexample shrinks to a replay-exact
#                   artifact, defense-on is clean on the SAME schedules
#                   (the attack-sweep pipeline).
#   mode="contain": the fault must be ABSORBED — the gated config stays
#                   violation-free while the recovery signature code
#                   (STORAGE_SIGNATURE_CODES) proves the verb actually
#                   fired and was repaired, not silently skipped.
# `oracle` picks the differential-oracle bound for trip artifacts:
#   "violation" — the bit is a SAFETY bit and the verb tick IS the
#                 violation tick, so replay_artifact's SAFETY_BITS
#                 truncation already compares exactly the clean prefix;
#   "verb"      — kernel-side divergence precedes the trip (poisoned
#                 install, stall-refused votes), so the sweep bounds its
#                 own oracle_trace at the first storage-verb tick (the
#                 host oracle models a perfect disk; see dst/repro.py).
STORAGE_SCENARIOS = {
    "lost_tail": dict(
        off=dict(fsync_lag_ticks=6),
        on=dict(fsync_lag_ticks=6, ack_gating=True),
        ticks=120, prop_count=2, bit="durability", mode="trip",
        oracle="violation", defense="durable-watermark ack gating"),
    "torn_write": dict(
        off=None,
        on=dict(fsync_lag_ticks=6, ack_gating=True),
        ticks=120, prop_count=2, bit=None, mode="contain",
        defense="checksummed-scan truncation + quorum re-replication"),
    "snap_corrupt": dict(
        off=dict(fsync_lag_ticks=6),
        on=dict(fsync_lag_ticks=6, ack_gating=True),
        ticks=140, prop_count=2, bit="checksum_agreement", mode="trip",
        oracle="verb", defense="pre-install snapshot checksum verify"),
    "disk_stall": dict(
        off=dict(fsync_lag_ticks=2, slo_fsync_lag=8),
        on=dict(fsync_lag_ticks=2, slo_fsync_lag=8, ack_gating=True,
                prop_inflight_cap=8),
        ticks=120, prop_count=2, bit="slo_fsync_lag", mode="trip",
        oracle="verb",
        defense="ack gating + per-row inflight cap backpressure"),
}

STORAGE_WIRE_SKIP = (
    "storage verbs rewrite kernel log/watermark registers between ticks; "
    "the host wires' real on-disk WAL is covered by the raft/storage.py "
    "truncation-parity tests instead")


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# --------------------------------------------------------------------------
# per-wire cluster harnesses


class _Cluster:
    """3-node raft cluster driven tick-by-tick (fake clock wires)."""

    wire = "inproc"
    TICK = 1.0
    delay_s = 2.0          # injected edge latency (spans >1 raft tick)
    MAX_STEPS = 300

    def __init__(self, seed: int) -> None:
        self.seed = seed
        self.clock = self._make_clock()
        # one typed registry per cluster: scenario assertions read counters
        # that only this cluster's nodes/transports could have moved
        self.obs = MetricsRegistry()
        self.network = self._make_network(seed)
        self.nodes: dict[str, Node] = {}
        self.tmp = tempfile.TemporaryDirectory(
            prefix=f"fault-sweep-{self.wire}-")
        self._n = 0

    def counter_sum(self, name: str) -> float:
        """Total of a counter family across all of its label sets."""
        fam = self.obs.get(name)
        if fam is None:
            return 0.0
        snap = fam.snapshot()
        return (sum(snap.values()) if isinstance(snap, dict)
                else float(snap))

    # wire-specific bits --------------------------------------------------
    def _make_clock(self):
        return FakeClock()

    def _make_network(self, seed: int):
        return Network(seed=seed)

    def _addr(self, node_id: str) -> str:
        return f"{node_id}.sweep:4242"

    def _decorate_opts(self, opts: NodeOpts) -> NodeOpts:
        return opts

    async def settle(self) -> None:
        """One scheduling step: a raft tick plus delivery pumping."""
        await self.clock.advance(self.TICK)
        for _ in range(8):
            await asyncio.sleep(0)

    # cluster lifecycle ---------------------------------------------------
    def _opts(self, node_id: str, addr: str, join_addr: str = "") -> NodeOpts:
        self._n += 1
        return self._decorate_opts(NodeOpts(
            node_id=node_id,
            addr=addr,
            network=self.network,
            state_dir=os.path.join(self.tmp.name, node_id),
            clock=self.clock,
            join_addr=join_addr,
            tick_interval=self.TICK,
            election_tick=4,
            heartbeat_tick=1,
            seed=self.seed + self._n,
            obs_registry=self.obs,
        ))

    async def add_node(self, join_from: Optional[Node] = None) -> Node:
        node_id = f"node-{len(self.nodes) + 1}"
        addr = self._addr(node_id)
        join_addr = join_from.addr if join_from is not None else ""
        node = Node(self._opts(node_id, addr, join_addr=join_addr))
        self.nodes[node_id] = node
        await node.start()
        await asyncio.sleep(0)
        return node

    async def stop_node(self, node: Node) -> None:
        await node.stop()
        self.network.unregister(node.addr)

    async def restart_node(self, node: Node) -> Node:
        """Fresh Node object over the same state dir and address."""
        opts = self._opts(node.node_id, node.addr)
        opts.seed = node.opts.seed
        new = Node(opts)
        self.nodes[node.node_id] = new
        await new.start()
        await asyncio.sleep(0)
        return new

    # waiting -------------------------------------------------------------
    def leader(self) -> Optional[Node]:
        leaders = [n for n in self.nodes.values()
                   if n.running and n.is_leader()]
        return leaders[0] if leaders else None

    async def wait_for(self, pred, what: str, max_steps: int = 0) -> None:
        for _ in range(max_steps or self.MAX_STEPS):
            if pred():
                return
            await self.settle()
        raise TimeoutError(f"[{self.wire}] timed out waiting for {what}")

    async def wait_for_cluster(self) -> Node:
        """One leader; every running member on its term and applied up to
        its commit (tests/node_harness.py wait_for_cluster)."""
        def converged() -> bool:
            lead = self.leader()
            if lead is None:
                return False
            members = [n for n in self.nodes.values() if n.running]
            lt = lead._raw.raft.term
            lc = lead._raw.raft.log.committed
            return all(n._raw is not None
                       and n._raw.raft.term == lt
                       and n._raw.raft.log.applied >= lc
                       for n in members)
        await self.wait_for(converged, "cluster convergence")
        return self.leader()

    async def close(self) -> None:
        for n in list(self.nodes.values()):
            if n.running:
                try:
                    await n.stop()
                except Exception:
                    pass
        closer = getattr(self.network, "close", None)
        if closer is not None:
            r = closer()
            if asyncio.iscoroutine(r):
                await r
        self.tmp.cleanup()


class _DeviceMeshCluster(_Cluster):
    wire = "devmesh"

    def _make_network(self, seed: int):
        from swarmkit_tpu.transport import DeviceMeshNet

        return DeviceMeshNet(seed=seed, rows=8, obs=self.obs)

    def _decorate_opts(self, opts: NodeOpts) -> NodeOpts:
        from swarmkit_tpu.transport import DeviceMeshTransport

        opts.transport_factory = DeviceMeshTransport
        return opts


class _GrpcCluster(_Cluster):
    wire = "grpc"
    TICK = 0.05            # real seconds per settle step
    delay_s = 0.2
    MAX_STEPS = 600        # 30s wall-clock ceiling per wait

    def _make_clock(self):
        return SystemClock()

    def _make_network(self, seed: int):
        from swarmkit_tpu.raft.grpc_transport import GrpcNetwork

        return GrpcNetwork(seed=seed, probe_interval=0.1, probe_timeout=0.5,
                           failure_threshold=2, grace_period=0.2,
                           redial_backoff=0.05, redial_backoff_max=0.4,
                           obs=self.obs)

    def _addr(self, node_id: str) -> str:
        return f"127.0.0.1:{_free_port()}"

    async def settle(self) -> None:
        await asyncio.sleep(self.TICK)

    async def close(self) -> None:
        await super().close()
        # let grpc.aio's poller thread drain its completion queue before
        # asyncio.run() closes the loop, or it logs spurious
        # "Event loop is closed" callbacks during teardown
        await asyncio.sleep(0.2)


_CLUSTERS = {
    "inproc": _Cluster,
    "devmesh": _DeviceMeshCluster,
    "grpc": _GrpcCluster,
}


# --------------------------------------------------------------------------
# schedule pieces


def _marker(i: int, tag: str) -> ApiNode:
    return ApiNode(id=f"mark-{tag}",
                   spec=NodeSpec(annotations=Annotations(name=tag)))


async def _commit(node: Node, tag: str) -> None:
    await node.store.update(lambda tx: tx.create(_marker(0, tag)))


def _has(node: Node, tag: str) -> bool:
    return node.store.get("node", f"mark-{tag}") is not None


async def _commit_while_stepping(h: _Cluster, lead: Node, tag: str,
                                 max_steps: int = 120) -> bool:
    """Propose from the leader while the harness keeps ticking (lost
    messages are only retried on heartbeats, which need clock advancement
    on the fake-clock wires).  Under an injected fault the commit MAY time
    out — the sweep only demands liveness after heal()."""
    task = asyncio.ensure_future(_commit(lead, tag))
    for _ in range(max_steps):
        if task.done():
            break
        await h.settle()
    if not task.done():
        task.cancel()
    try:
        await task
        return True
    except Exception:
        return False


def _build_plan(name: str, h: _Cluster, lead: Node, victim: Node
                ) -> FaultPlan:
    others = [n.addr for n in h.nodes.values()
              if n.running and n.addr != victim.addr]
    if name == "down":
        return FaultPlan.down(victim.addr)
    if name == "drop":
        return FaultPlan.drop(lead.addr, victim.addr, p=0.6)
    if name == "partition":
        return FaultPlan.split([victim.addr], others)
    if name == "delay":
        return FaultPlan.delay(lead.addr, victim.addr, h.delay_s)
    if name == "crash":
        return FaultPlan.crash(victim.addr)
    raise ValueError(f"unknown fault plan {name!r}")


# --------------------------------------------------------------------------
# one scenario


async def _run_scenario(wire: str, plan_name: str, seed: int) -> dict:
    h = _CLUSTERS[wire](seed)
    tag = f"{wire}-{plan_name}-{seed}"
    notes: list[str] = []
    try:
        n1 = await h.add_node()
        await h.wait_for(lambda: h.leader() is not None, "first leader")
        await h.add_node(join_from=n1)
        await h.add_node(join_from=n1)
        lead = await h.wait_for_cluster()

        await _commit_while_stepping(h, lead, f"pre-{tag}")
        await h.wait_for(
            lambda: all(_has(n, f"pre-{tag}")
                        for n in h.nodes.values() if n.running),
            "pre-marker replication")

        # -- inject -------------------------------------------------------
        lead = h.leader()
        victim = next(n for n in sorted(h.nodes.values(),
                                        key=lambda n: n.node_id)
                      if n.running and n.raft_id != lead.raft_id)
        # counter baselines: the fault window must be VISIBLE in the typed
        # registry, not just survivable (see metrics assertion post-heal)
        campaigns_before = h.counter_sum("swarm_raft_elections_started_total")
        flips_before = h.counter_sum(
            "swarm_transport_probe_transitions_total")
        plan = _build_plan(plan_name, h, lead, victim)
        plan.inject(h.network)
        if plan_name == "crash":
            # the crash plan is a real process death, not just wire state
            await h.stop_node(victim)

        committed = await _commit_while_stepping(h, lead, f"mid-{tag}")
        notes.append(f"commit under fault: "
                     f"{'ok' if committed else 'timed out (tolerated)'}")

        # -- metrics oracle: the fault must be VISIBLE, not just survived --
        # Hold the partition open until the isolated victim's election
        # timeout fires (the majority commits fast, so the mid-commit alone
        # may not span a timeout) and, on the probing wire, until the
        # health prober flips the victim's state down.
        if plan_name == "partition":
            await h.wait_for(
                lambda: h.counter_sum("swarm_raft_elections_started_total")
                > campaigns_before,
                "partition to register in the campaign counter")
            notes.append(
                f"campaigns {campaigns_before:.0f} -> "
                f"{h.counter_sum('swarm_raft_elections_started_total'):.0f}")
            if wire == "grpc":
                await h.wait_for(
                    lambda: h.counter_sum(
                        "swarm_transport_probe_transitions_total")
                    > flips_before,
                    "partition to flip a prober state")
                notes.append(
                    f"probe flips {flips_before:.0f} -> "
                    f"{h.counter_sum('swarm_transport_probe_transitions_total'):.0f}")

        # -- heal + liveness ----------------------------------------------
        plan.heal(h.network)
        if plan_name == "crash":
            victim = await h.restart_node(victim)
        lead = await h.wait_for_cluster()

        await _commit_while_stepping(h, lead, f"post-{tag}")
        await h.wait_for(
            lambda: all(_has(n, f"post-{tag}")
                        for n in h.nodes.values() if n.running),
            "post-heal replication (liveness)")

        # -- leader transfer ----------------------------------------------
        old_rid = lead.raft_id
        await lead.transfer_leadership()
        await h.wait_for(
            lambda: h.leader() is not None
            and h.leader().raft_id != old_rid,
            "leadership transfer")
        lead = await h.wait_for_cluster()
        notes.append(f"leader moved {old_rid:x} -> {lead.raft_id:x}")

        # -- member removal: drop the non-victim follower so the final
        # commit can only succeed with the recovered victim's ack ---------
        candidates = [rid for rid, m in lead.cluster.members.items()
                      if rid != lead.raft_id and m.addr != victim.addr]
        if not candidates:   # the victim became leader: remove any follower
            candidates = [rid for rid in lead.cluster.members
                          if rid != lead.raft_id]
        removed_rid = candidates[0]
        removed_addr = lead.cluster.members[removed_rid].addr
        removal = asyncio.ensure_future(lead.remove_member(removed_rid))
        await h.wait_for(lambda: removal.done(), "member removal")
        removal.result()
        gone = next(n for n in h.nodes.values() if n.addr == removed_addr)
        await h.stop_node(gone)
        notes.append(f"removed member {removed_rid:x} ({removed_addr})")

        lead = await h.wait_for_cluster()
        await _commit_while_stepping(h, lead, f"final-{tag}")
        await h.wait_for(
            lambda: all(_has(n, f"final-{tag}")
                        for n in h.nodes.values() if n.running),
            "final replication after removal")

        # -- differential oracle: surviving stores must agree -------------
        survivors = [n for n in h.nodes.values() if n.running]
        contents = {n.node_id: sorted(o.id for o in n.store.find("node"))
                    for n in survivors}
        baseline = next(iter(contents.values()))
        diverged = {nid: ids for nid, ids in contents.items()
                    if ids != baseline}
        if diverged:
            raise AssertionError(
                f"store divergence across members: {contents}")
        for phase in ("pre", "post", "final"):
            if f"mark-{phase}-{tag}" not in baseline:
                raise AssertionError(f"{phase} marker missing: {baseline}")
        return {"wire": wire, "plan": plan_name, "seed": seed, "ok": True,
                "notes": "; ".join(notes)}
    except Exception as e:
        return {"wire": wire, "plan": plan_name, "seed": seed, "ok": False,
                "notes": "; ".join(notes), "error": f"{type(e).__name__}: {e}"}
    finally:
        await h.close()


# --------------------------------------------------------------------------
# device-side precheck: the same fault vocabulary in a chosen peer lowering


def _device_plan(name: str, addrs: list[str]) -> FaultPlan:
    """The host sweep's plan shapes rebuilt over synthetic kernel rows."""
    lead, victim = addrs[0], addrs[1]
    if name == "down":
        return FaultPlan.down(victim)
    if name == "drop":
        return FaultPlan.drop(lead, victim, p=0.6)
    if name == "partition":
        return FaultPlan.split([victim], [a for a in addrs if a != victim])
    if name == "delay":
        return FaultPlan.delay(lead, victim, 2.0)
    if name == "crash":
        return FaultPlan.crash(victim)
    raise ValueError(f"unknown fault plan {name!r}")


def run_device_precheck(plans=PLANS, seeds=DEFAULT_SEEDS, peer_chunk: int = 8,
                        n: int = 16, ticks: int = 60,
                        verbose: bool = True,
                        active_rows=None) -> list[dict]:
    """Lower every (plan, seed) to a device fault schedule and run it
    through the DST kernel with ``SimConfig.peer_chunk=peer_chunk`` and
    ``SimConfig.active_rows=active_rows`` (None = default).

    When the chunk selects the banded lowering the run is cross-checked
    against the dense kernel: violation bitmasks, first-violation ticks,
    and per-tick bit traces must match exactly (the hierarchical quorum
    reductions are integer sums, so any drift is a bug, not noise).
    ``peer_chunk=0`` runs the dense lowering alone.  Likewise, when
    ``active_rows`` selects the role-sparse progress slabs the run is
    cross-checked against the dense elementwise progress kernel
    (``active_rows=0``) under the same peer lowering.
    """
    import jax
    import numpy as np

    from swarmkit_tpu import dst
    from swarmkit_tpu.raft.sim.state import SimConfig, init_state

    def _cfg(chunk: int, seed: int, ar=active_rows) -> SimConfig:
        return SimConfig(n=n, log_len=64, window=8, apply_batch=16,
                         max_props=8, keep=4, election_tick=10, seed=seed,
                         log_chunk=0, peer_chunk=chunk,
                         **_cli_common.active_rows_kw(ar))

    def _run(cfg: SimConfig, sched):
        batched = jax.tree_util.tree_map(lambda a: a[None], sched)
        return dst.explore(init_state(cfg), cfg, batched, shard=False)

    addrs = [f"row-{i}.sweep:4242" for i in range(n)]
    rows = {a: i for i, a in enumerate(addrs)}
    results = []
    for plan_name in plans:
        for seed in seeds:
            t0 = time.monotonic()
            cfg = _cfg(peer_chunk, seed)
            sched = dst.from_fault_plan(
                cfg, _device_plan(plan_name, addrs), rows, ticks=ticks,
                inject_at=10, heal_at=40, seed=seed)
            res = _run(cfg, sched)
            ok, err = True, ""
            notes = (f"viol=0x{int(res.viol[0]):x} "
                     f"lowering={'banded' if cfg.peer_tiled else 'dense'}"
                     + ("+sparse" if cfg.active_rows_on else ""))
            if cfg.peer_tiled:
                ref = _run(_cfg(0, seed), sched)
                same = (np.array_equal(res.viol, ref.viol)
                        and np.array_equal(res.first_tick, ref.first_tick)
                        and np.array_equal(res.bits_by_tick,
                                           ref.bits_by_tick))
                if not same:
                    ok = False
                    err = (f"banded/dense divergence: viol "
                           f"{res.viol.tolist()} vs {ref.viol.tolist()}")
                else:
                    notes += " == dense-peer"
            if ok and cfg.active_rows_on:
                ref = _run(_cfg(peer_chunk, seed, ar=0), sched)
                same = (np.array_equal(res.viol, ref.viol)
                        and np.array_equal(res.first_tick, ref.first_tick)
                        and np.array_equal(res.bits_by_tick,
                                           ref.bits_by_tick))
                if not same:
                    ok = False
                    err = (f"sparse/dense progress divergence: viol "
                           f"{res.viol.tolist()} vs {ref.viol.tolist()}")
                else:
                    notes += " == dense-progress"
            wire = f"device(pc={peer_chunk}" + (
                f",ar={active_rows})" if active_rows is not None else ")")
            results.append({"wire": wire,
                            "plan": plan_name, "seed": seed, "ok": ok,
                            "notes": notes, "error": err,
                            "secs": round(time.monotonic() - t0, 2)})
            if verbose:
                r = results[-1]
                state = "ok  " if ok else "FAIL"
                line = (f"{state} {r['wire']:8s} {plan_name:10s} "
                        f"seed={seed} ({r['secs']}s)  {notes}")
                if not ok:
                    line += f"  {err}"
                print(line, flush=True)
    return results


# --------------------------------------------------------------------------
# adversary attack scenarios (device wire): full counterexample pipeline


def run_attack_sweep(attacks=None, seed: int = 7, schedules: int = 8,
                     n: int = 5, out_dir: Optional[str] = None,
                     wires=WIRES, verbose: bool = True) -> list[dict]:
    """Seed-pinned end-to-end run of each ATTACK_SCENARIOS row.

    For every attack: the defense-off sweep must CATCH it (the named
    invariant bit trips), the first counterexample is shrunk and dumped
    as a replayable artifact (replay must reproduce bits + first tick
    exactly, the differential oracle must stay in lockstep over the
    clean prefix), and the defense-on sweep over the SAME schedules must
    come back violation-free.  Host wires get explicit skip rows — see
    ATTACK_WIRE_SKIP."""
    import dataclasses

    from swarmkit_tpu import dst
    from swarmkit_tpu.raft.sim.state import SimConfig, init_state

    attacks = list(attacks or ATTACK_SCENARIOS)
    base = SimConfig(n=n, log_len=64, window=8, apply_batch=16, max_props=8,
                     keep=4, election_tick=10, seed=seed)
    bit_of = {name: bit for bit, name in dst.BIT_NAMES.items()}
    results = []
    for attack in attacks:
        sc = ATTACK_SCENARIOS[attack]
        t0 = time.monotonic()
        off = dataclasses.replace(base, **sc["off"])
        on = dataclasses.replace(base, **sc["on"])
        bit = bit_of[sc["bit"]]
        ok, err, notes = True, "", ""
        try:
            batch, names = dst.make_batch(off, ticks=sc["ticks"],
                                          schedules=schedules, seed=seed,
                                          profiles=(attack,))
            r_off = dst.explore(init_state(off), off, batch, profiles=names,
                                prop_count=sc["prop_count"])
            caught = [int(s) for s in r_off.violating
                      if int(r_off.viol[s]) & bit]
            if not caught:
                raise AssertionError(
                    f"defense-off sweep never tripped {sc['bit']}")
            r_on = dst.explore(init_state(on), on, batch, profiles=names,
                               prop_count=sc["prop_count"])
            if int((r_on.viol != 0).sum()):
                raise AssertionError(
                    f"defense-on ({sc['defense']}) not clean: "
                    f"{[hex(int(v)) for v in r_on.viol]}")
            s = caught[0]
            one = batch.slice(s)
            before = dst.fault_count(one)
            small, evals = dst.shrink(off, one, bit, sc["prop_count"])
            v2, f2 = dst.replay(off, small, sc["prop_count"])
            art = dst.to_artifact(off, small, seed=seed, profile=attack,
                                  index=s, prop_count=sc["prop_count"],
                                  mutation=None, viol=v2, first_tick=f2)
            path = _cli_common.artifact_path(
                None if out_dir is None else out_dir.rstrip(os.sep) + os.sep,
                f"dst_attack_{attack}.json")
            dst.save_artifact(path, art)
            verdict = dst.replay_artifact(path)
            if not verdict["matches_recorded"]:
                raise AssertionError("artifact replay did not reproduce "
                                     "the recorded violation")
            tr = verdict["oracle"]
            if tr["diverged_at"] != -1:
                raise AssertionError(f"differential oracle diverged at "
                                     f"tick {tr['diverged_at']}")
            notes = (f"caught {len(caught)}/{schedules} ({sc['bit']}), "
                     f"shrunk {before}->{dst.fault_count(small)} "
                     f"fault-events in {evals} replays, replay exact, "
                     f"oracle lockstep, defense-on ({sc['defense']}) "
                     f"clean [{path}]")
        except AssertionError as e:
            ok, err = False, str(e)
        results.append({"wire": "device", "plan": attack, "seed": seed,
                        "ok": ok, "notes": notes, "error": err,
                        "secs": round(time.monotonic() - t0, 2)})
        if verbose:
            r = results[-1]
            state = "ok  " if ok else "FAIL"
            line = (f"{state} {'device':8s} {attack:18s} seed={seed} "
                    f"({r['secs']}s)  {notes}")
            if not ok:
                line += f"  {err}"
            print(line, flush=True)
        for wire in wires:
            results.append({"wire": wire, "plan": attack, "seed": seed,
                            "ok": True, "skipped": ATTACK_WIRE_SKIP,
                            "notes": f"SKIP: {ATTACK_WIRE_SKIP}",
                            "secs": 0.0})
            if verbose:
                print(f"skip {wire:8s} {attack:18s} seed={seed} "
                      f"({ATTACK_WIRE_SKIP})", flush=True)
    return results


# --------------------------------------------------------------------------
# storage-fault scenarios (device wire): the durability boundary


def run_storage_sweep(faults=None, seed: int = 7, schedules: int = 8,
                      n: int = 5, out_dir: Optional[str] = None,
                      wires=WIRES, verbose: bool = True) -> list[dict]:
    """Seed-pinned end-to-end run of each STORAGE_SCENARIOS row (see the
    table above for the trip/contain split and the oracle bounds)."""
    import dataclasses

    import numpy as np

    from swarmkit_tpu import dst
    from swarmkit_tpu.raft.sim.state import SimConfig, init_state

    faults = list(faults or STORAGE_SCENARIOS)
    base = SimConfig(n=n, log_len=64, window=8, apply_batch=16, max_props=8,
                     keep=4, election_tick=10, seed=seed)
    bit_of = {name: bit for bit, name in dst.BIT_NAMES.items()}
    results = []
    for fault in faults:
        sc = STORAGE_SCENARIOS[fault]
        t0 = time.monotonic()
        on = dataclasses.replace(base, **sc["on"])
        ok, err, notes = True, "", ""
        try:
            if sc["mode"] == "contain":
                batch, names = dst.make_batch(on, ticks=sc["ticks"],
                                              schedules=schedules, seed=seed,
                                              profiles=(fault,))
                r_on = dst.explore(init_state(on), on, batch, profiles=names,
                                   prop_count=sc["prop_count"])
                if int((r_on.viol != 0).sum()):
                    raise AssertionError(
                        f"gated config not clean under {fault}: "
                        f"{[hex(int(v)) for v in r_on.viol]}")
                code = dst.STORAGE_SIGNATURE_CODES[fault]
                fl = dst.capture_flight(on, batch.slice(0),
                                        sc["prop_count"], window=400)
                hits = sum(code in e.describe()
                           for e in fl["record"].window(400))
                if not hits:
                    raise AssertionError(
                        f"{code} never fired — the {fault} verb was "
                        f"absorbed without any recovery evidence")
                notes = (f"contained: 0/{schedules} violations with "
                         f"{sc['defense']}, {hits} {code} recovery "
                         f"event(s) on schedule 0")
            else:
                off = dataclasses.replace(base, **sc["off"])
                bit = bit_of[sc["bit"]]
                batch, names = dst.make_batch(off, ticks=sc["ticks"],
                                              schedules=schedules, seed=seed,
                                              profiles=(fault,))
                r_off = dst.explore(init_state(off), off, batch,
                                    profiles=names,
                                    prop_count=sc["prop_count"])
                caught = [int(s) for s in r_off.violating
                          if int(r_off.viol[s]) & bit]
                if not caught:
                    raise AssertionError(
                        f"defense-off sweep never tripped {sc['bit']}")
                r_on = dst.explore(init_state(on), on, batch, profiles=names,
                                   prop_count=sc["prop_count"])
                if int((r_on.viol != 0).sum()):
                    raise AssertionError(
                        f"defense-on ({sc['defense']}) not clean: "
                        f"{[hex(int(v)) for v in r_on.viol]}")
                s = caught[0]
                one = batch.slice(s)
                before = dst.fault_count(one)
                small, evals = dst.shrink(off, one, bit, sc["prop_count"])
                v2, f2 = dst.replay(off, small, sc["prop_count"])
                art = dst.to_artifact(off, small, seed=seed, profile=fault,
                                      index=s, prop_count=sc["prop_count"],
                                      mutation=None, viol=v2, first_tick=f2)
                path = _cli_common.artifact_path(
                    None if out_dir is None
                    else out_dir.rstrip(os.sep) + os.sep,
                    f"dst_storage_{fault}.json")
                dst.save_artifact(path, art)
                want_trace = sc["oracle"] == "violation"
                verdict = dst.replay_artifact(path, with_trace=want_trace)
                if not verdict["matches_recorded"]:
                    raise AssertionError("artifact replay did not reproduce "
                                         "the recorded violation")
                if want_trace:
                    div = verdict["oracle"]["diverged_at"]
                else:
                    leaf = getattr(small, dst.STORAGE_LEAVES[fault])
                    first_verb = int(np.where(
                        np.asarray(leaf).any(axis=1))[0].min())
                    div = dst.oracle_trace(
                        off, small, sc["prop_count"],
                        until=first_verb)["diverged_at"]
                if div != -1:
                    raise AssertionError(f"differential oracle diverged at "
                                         f"tick {div}")
                notes = (f"caught {len(caught)}/{schedules} ({sc['bit']}), "
                         f"shrunk {before}->{dst.fault_count(small)} "
                         f"fault-events in {evals} replays, replay exact, "
                         f"oracle lockstep ({sc['oracle']}-bounded), "
                         f"defense-on ({sc['defense']}) clean [{path}]")
        except AssertionError as e:
            ok, err = False, str(e)
        results.append({"wire": "device", "plan": fault, "seed": seed,
                        "ok": ok, "notes": notes, "error": err,
                        "secs": round(time.monotonic() - t0, 2)})
        if verbose:
            r = results[-1]
            state = "ok  " if ok else "FAIL"
            line = (f"{state} {'device':8s} {fault:18s} seed={seed} "
                    f"({r['secs']}s)  {notes}")
            if not ok:
                line += f"  {err}"
            print(line, flush=True)
        for wire in wires:
            results.append({"wire": wire, "plan": fault, "seed": seed,
                            "ok": True, "skipped": STORAGE_WIRE_SKIP,
                            "notes": f"SKIP: {STORAGE_WIRE_SKIP}",
                            "secs": 0.0})
            if verbose:
                print(f"skip {wire:8s} {fault:18s} seed={seed} "
                      f"({STORAGE_WIRE_SKIP})", flush=True)
    return results


# --------------------------------------------------------------------------
# sweep driver


def _dump_flight(res: dict, flight_dir: str) -> Optional[str]:
    """Write a host-side flight record for one failed scenario: the
    tracer's finished spans (raft.propose, dispatcher.session, probe
    spans...) captured at the moment of failure, trigger-tagged so the
    Manager scrape's recent-events section picks it up too."""
    try:
        from swarmkit_tpu.flightrec import record as flight_record
        from swarmkit_tpu.metrics import trace as obs_trace

        rec = flight_record.FlightRecord(
            events=[], dropped=[], n=0, trigger="scenario_failure",
            meta={k: res.get(k) for k in
                  ("wire", "plan", "seed", "error", "notes")},
            spans=[s.to_dict() for s in obs_trace.DEFAULT.finished()])
        flight_record._RECENT.append(rec)
        os.makedirs(flight_dir, exist_ok=True)
        path = os.path.join(
            flight_dir,
            f"fault_{res['wire']}_{res['plan']}_{res['seed']}.json")
        flight_record.save_record(rec, path)
        return path
    except Exception as e:  # a dump failure must not mask the scenario's
        print(f"  (flight dump failed: {type(e).__name__}: {e})", flush=True)
        return None


def run_sweep(wires=WIRES, plans=PLANS, seeds=DEFAULT_SEEDS,
              verbose: bool = True,
              flight_dir: Optional[str] = None) -> list[dict]:
    """Run each (wire, plan, seed) scenario on a fresh event loop and
    return one result dict per scenario (importable from tests).  With
    `flight_dir`, every failing scenario dumps a flight record there."""
    results = []
    for wire in wires:
        for plan in plans:
            for seed in seeds:
                t0 = time.monotonic()
                res = asyncio.run(_run_scenario(wire, plan, seed))
                res["secs"] = round(time.monotonic() - t0, 2)
                if not res["ok"] and flight_dir:
                    res["flight"] = _dump_flight(res, flight_dir)
                results.append(res)
                if verbose:
                    state = "ok  " if res["ok"] else "FAIL"
                    line = (f"{state} {wire:8s} {plan:10s} seed={seed} "
                            f"({res['secs']}s)")
                    if not res["ok"]:
                        line += f"  {res.get('error', '')}"
                        if res.get("flight"):
                            line += f"  [flight: {res['flight']}]"
                    print(line, flush=True)
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--wires", default=",".join(WIRES),
                    help=f"comma list from {WIRES}")
    ap.add_argument("--plans", default=",".join(PLANS),
                    help=f"comma list from {PLANS}")
    ap.add_argument("--seeds", default=",".join(map(str, DEFAULT_SEEDS)),
                    help="comma list of seeds")
    ap.add_argument("--flight-dir", default=None, metavar="DIR",
                    help="dump a flight record (host spans + failure "
                         "provenance) here for every failing scenario; "
                         "inspect with tools/flight_view.py")
    ap.add_argument("--peer-chunk", type=int, default=None, metavar="PC",
                    help="also run every plan through the DST kernel in "
                         "this peer-axis lowering (SimConfig.peer_chunk; "
                         "0 = dense, else banded + dense cross-check)")
    ap.add_argument("--attacks", default=None, metavar="LIST",
                    help=f"run ONLY the seed-pinned adversary attack "
                    f"scenarios ('all' or a comma list from "
                    f"{tuple(ATTACK_SCENARIOS)}): device-wire "
                    f"counterexample pipeline + explicit per-host-wire "
                    f"skip rows (the verbs have no host seam)")
    ap.add_argument("--storage", default=None, metavar="LIST",
                    help=f"run ONLY the seed-pinned storage-fault "
                    f"scenarios ('all' or a comma list from "
                    f"{tuple(STORAGE_SCENARIOS)}): device-wire durability "
                    f"pipeline (catch -> shrink -> replay-exact -> "
                    f"gating-on clean) + explicit per-host-wire skip rows")
    _cli_common.add_active_rows_arg(ap)
    args = ap.parse_args(argv)

    wires = [w for w in args.wires.split(",") if w]
    plans = [p for p in args.plans.split(",") if p]
    seeds = [int(s) for s in args.seeds.split(",") if s]
    for w in wires:
        if w not in _CLUSTERS:
            ap.error(f"unknown wire {w!r}")
    for p in plans:
        if p not in PLANS:
            ap.error(f"unknown plan {p!r}")

    if args.attacks:
        attacks = (list(ATTACK_SCENARIOS) if args.attacks == "all"
                   else [a for a in args.attacks.split(",") if a])
        for a in attacks:
            if a not in ATTACK_SCENARIOS:
                ap.error(f"unknown attack {a!r}; "
                         f"known: {tuple(ATTACK_SCENARIOS)}")
        results = run_attack_sweep(attacks, seed=seeds[0], wires=wires,
                                   out_dir=args.flight_dir)
        failed = [r for r in results if not r["ok"]]
        ran = [r for r in results if "skipped" not in r]
        print(f"\n{len(ran) - len(failed)}/{len(ran)} attack scenarios "
              f"passed ({len(results) - len(ran)} host-wire skips)")
        return 1 if failed else 0

    if args.storage:
        faults = (list(STORAGE_SCENARIOS) if args.storage == "all"
                  else [f for f in args.storage.split(",") if f])
        for f in faults:
            if f not in STORAGE_SCENARIOS:
                ap.error(f"unknown storage fault {f!r}; "
                         f"known: {tuple(STORAGE_SCENARIOS)}")
        results = run_storage_sweep(faults, seed=seeds[0], wires=wires,
                                    out_dir=args.flight_dir)
        failed = [r for r in results if not r["ok"]]
        ran = [r for r in results if "skipped" not in r]
        print(f"\n{len(ran) - len(failed)}/{len(ran)} storage scenarios "
              f"passed ({len(results) - len(ran)} host-wire skips)")
        return 1 if failed else 0

    results = []
    if args.peer_chunk is not None or args.active_rows is not None:
        results += run_device_precheck(
            plans, seeds,
            peer_chunk=args.peer_chunk if args.peer_chunk is not None else 8,
            active_rows=args.active_rows)
    results += run_sweep(wires, plans, seeds, flight_dir=args.flight_dir)
    failed = [r for r in results if not r["ok"]]
    print(f"\n{len(results) - len(failed)}/{len(results)} scenarios passed")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Exhaustive model-checking sweep (swarmkit_tpu/mc/).

Where ``dst_sweep.py`` SAMPLES fault schedules, this tool ENUMERATES
them: every per-tick fault action from the scope's counted alphabet,
every sequence to the horizon, deduplicating reached states by
fingerprint between levels — and checks all armed raft invariants on
every reached state.  Three jobs, all deterministic (the scan has no
seed at all; ``--seed`` only stamps artifacts):

1. **Scan** (default): exhaustively enumerate a documented scope preset
   against the stock kernel.  Must report ZERO violations, and the JSON
   summary must show the scope's full schedule space covered
   (``exhaustive: true``) with millions of branches per big device pass.

2. **Mutation self-test** (after the scan unless suppressed): re-scan a
   smaller horizon against a deliberately broken kernel knob
   (``commit_no_quorum``, ``stale_lease_read``), assert the enumeration
   CATCHES it, lower the first violating branch to a FaultSchedule,
   shrink it, dump a seed-pinned artifact with a flight-recorder
   post-mortem, and replay the artifact — bits and first tick must
   reproduce exactly (``dst_sweep.py --replay`` works on these too).

3. **Budget-bounded scan** (``--budget`` or the preset's own): cap the
   per-level frontier; truncation is LOGGED per level and the summary
   flips to ``exhaustive: false`` — the tool never silently narrows an
   exhaustiveness claim.

Usage:
    python tools/mc_sweep.py                     # n3h8, full scan + self-tests
    python tools/mc_sweep.py --smoke             # tier-1 wall: seconds
    python tools/mc_sweep.py --scope n3h12 --budget 1048576
    python tools/mc_sweep.py --mutate commit_no_quorum
    python tools/mc_sweep.py --json summary.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import _cli_common  # noqa: E402

_cli_common.bootstrap()

from swarmkit_tpu import mc  # noqa: E402
from swarmkit_tpu.dst import repro  # noqa: E402

MUTATIONS = ("commit_no_quorum", "stale_lease_read")


def run_scan(scope_name: str = "n3h8", budget=None, mutation=None,
             symmetry: bool = False, verbose: bool = True,
             collect_edges: bool = False) -> mc.ScanResult:
    """One exhaustive_scan over a documented preset (importable)."""
    scope = mc.SCOPES[scope_name]
    budget = scope.budget if budget is None else (budget or None)
    res = mc.exhaustive_scan(
        scope.cfg(), scope.alphabet(), scope.horizon,
        prop_count=scope.prop_count, mutation=mutation, budget=budget,
        symmetry=symmetry, collect_edges=collect_edges, scope=scope_name,
        log=print if verbose else None)
    if verbose:
        tag = f" [mutation={mutation}]" if mutation else ""
        print(f"scope {scope_name}{tag}: {res.branches_explored:,} branches "
              f"over {res.states_discovered:,} states in "
              f"{res.elapsed:.1f}s ({res.branches_per_sec:,.0f} branches/s, "
              f"max {res.max_branches_per_pass:,}/pass) — "
              f"{len(res.violations)} violation(s), "
              f"exhaustive={res.exhaustive}", flush=True)
    return res


def run_self_test(scope_name: str, mutation: str, out_path=None,
                  verbose: bool = True) -> dict:
    """Detect -> lower -> shrink -> dump -> replay one mutation repro."""
    scope = mc.SCOPES[scope_name]
    res = run_scan(scope_name, mutation=mutation, verbose=False)
    demo = {"mutation": mutation, "scope": scope_name,
            "caught": bool(res.violations),
            "branches_explored": res.branches_explored}
    if not demo["caught"]:
        if verbose:
            print(f"mutation {mutation!r} NOT caught by exhaustive scan "
                  f"at scope {scope_name}", flush=True)
        return demo

    v = res.violations[0]
    art = mc.violation_artifact(scope.cfg(), scope.alphabet(), v,
                                prop_count=scope.prop_count,
                                mutation=mutation, scope=scope_name)
    out_path = _cli_common.artifact_path(
        out_path, f"mc_repro_{scope_name}_{mutation}.json")
    repro.save_artifact(out_path, art)
    verdict = repro.replay_artifact(out_path, with_trace=False)
    demo.update({
        "level": v["level"], "path": v["path"],
        "actions": art["mc"]["actions"],
        "bits": v["invariants"],
        "artifact": out_path,
        "replay_matches": verdict["matches_recorded"],
    })
    if verbose:
        print(f"mutation {mutation!r} caught at level {v['level']} "
              f"({v['invariants']}) after {res.branches_explored:,} "
              f"branches; minimal branch: {art['mc']['actions']}",
              flush=True)
        print(f"repro artifact: {out_path} — replay "
              f"{'reproduces exactly' if demo['replay_matches'] else 'DIVERGED'}",
              flush=True)
    return demo


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    _cli_common.add_common_args(ap)
    ap.add_argument("--scope", default="n3h8", choices=sorted(mc.SCOPES),
                    help="documented scope preset (default: n3h8, the "
                    "headline exhaustive claim)")
    ap.add_argument("--smoke", action="store_true",
                    help="shorthand for --scope smoke with smoke-sized "
                    "self-tests (tier-1 wall)")
    ap.add_argument("--budget", type=int, default=None,
                    help="per-level frontier cap (0 = force unbounded); "
                    "truncation is logged and flips exhaustive=false")
    ap.add_argument("--symmetry", action="store_true",
                    help="opt-in node-relabeling dedup (heuristic: NOT "
                    "part of the exhaustive claim, see mc/fingerprint.py)")
    ap.add_argument("--mutate", default=None, choices=MUTATIONS,
                    help="run ONLY the mutation self-test for this "
                    "broken-kernel knob")
    ap.add_argument("--no-mutation-demo", action="store_true",
                    help="skip the detection self-tests after the scan")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the scan's JSON summary here")
    args = ap.parse_args(argv)

    if args.replay:
        verdict = repro.replay_artifact(args.replay, with_trace=False)
        print(f"replayed {args.replay}: {verdict['violations']} at tick "
              f"{verdict['first_tick']} — "
              f"{'matches recorded run' if verdict['matches_recorded'] else 'MISMATCH'}",
              flush=True)
        return 0 if verdict["matches_recorded"] else 1

    scope_name = "smoke" if args.smoke else args.scope
    # mutation self-tests need horizon >= 8 at n=3 (stale_lease_read's
    # shortest counterexample is 5 ticks past a commit); any other scope
    # delegates to the documented catch scope n3h8
    sc = mc.SCOPES[scope_name]
    test_scope = scope_name if sc.n == 3 and sc.horizon >= 8 else "n3h8"

    if args.mutate:
        demo = run_self_test(test_scope, args.mutate, out_path=args.out)
        return 0 if demo["caught"] and demo.get("replay_matches") else 1

    res = run_scan(scope_name, budget=args.budget, symmetry=args.symmetry)
    ok = not res.violations
    for v in res.violations:
        print(f"  VIOLATION at level {v['level']}: {v['invariants']} via "
              f"{[mc.SCOPES[scope_name].alphabet().names[a] for a in v['path']]}",
              flush=True)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(res.summary(), f, indent=2)
        print(f"summary: {args.json}", flush=True)

    if not args.no_mutation_demo and not args.smoke:
        for mutation in MUTATIONS:
            demo = run_self_test(test_scope, mutation, out_path=args.out)
            ok = ok and demo["caught"] and demo.get("replay_matches", False)

    print("PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

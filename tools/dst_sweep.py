"""Deterministic-simulation sweep: vmapped fault-schedule search with
on-device raft invariant checking (swarmkit_tpu/dst/).

Two jobs, both seed-pinned and CPU-runnable under tier-1:

1. **Sweep** (default): generate S adversarial fault schedules across the
   named profiles, advance S x N simulated clusters in one jitted scan,
   and check ElectionSafety / LogMatching / LeaderCompleteness / commit
   monotonicity / applied-checksum agreement / read linearizability every
   tick (the sweep config enables the linearizable read path,
   ``--reads``, so all six invariants are armed).  The stock kernel must
   report ZERO violations.

2. **Mutation self-test** (runs after the sweep unless suppressed): repeat
   a smaller sweep against a deliberately broken kernel knob, assert the
   checkers CATCH it, greedily shrink the first counterexample to a
   minimal repro, dump it as a JSON artifact, and replay the artifact —
   bits and first-violation tick must reproduce exactly.  Two knobs run
   by default: ``commit_no_quorum`` (leaders commit without a match
   quorum; the differential oracle additionally localizes the divergence)
   and ``stale_lease_read`` (leases force-disabled, stale leaders serve
   reads; swept under the explicit ``stale_leader_reads`` adversary,
   caught by LINEARIZABLE_READ — the oracle view excludes read registers,
   so no oracle divergence is expected there).

Usage:
    python tools/dst_sweep.py --schedules 256 --ticks 100 --seed 0
    python tools/dst_sweep.py --mutate commit_no_quorum --out repro.json
    python tools/dst_sweep.py --mutate stale_lease_read
    python tools/dst_sweep.py --replay repro.json
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import _cli_common  # noqa: E402

_cli_common.bootstrap()

from swarmkit_tpu import dst  # noqa: E402
from swarmkit_tpu.raft.sim.state import SimConfig, init_state  # noqa: E402

DEFAULT_MUTATION = "commit_no_quorum"

# each mutation is swept under the adversary rotation that realizes the
# scenario it breaks: the stale-read knob needs the pinned-victim
# stale-leader overlap, which lives in EXTRA_PROFILES
MUTATION_PROFILES = {
    "stale_lease_read": dst.EXTRA_PROFILES,
}


def _cfg(n: int, seed: int, reads: int = 2,
         peer_chunk=None, active_rows=None) -> SimConfig:
    """The DST cluster shape: small rows, small ring — schedule diversity,
    not cluster size, is the search dimension (mirrors the differential
    suite's CFG5).  `reads` enables the linearizable read path so the
    LINEARIZABLE_READ checker is armed (0 sweeps the read-free kernel).
    `peer_chunk` picks the peer-axis lowering (None = SimConfig default;
    0 = dense; a divisor of n = hierarchical banded quorum counts) and
    `active_rows` the progress lowering (0 = dense elementwise, a
    multiple of 8 < n = [A, N] slabs), so sweeps can run in any lowering
    without code edits."""
    kw = {} if peer_chunk is None else {"peer_chunk": peer_chunk}
    kw.update(_cli_common.active_rows_kw(active_rows))
    return SimConfig(n=n, log_len=64, window=8, apply_batch=16, max_props=8,
                     keep=4, election_tick=10, seed=seed, read_batch=reads,
                     **kw)


def run_sweep(schedules: int = 256, ticks: int = 100, seed: int = 0,
              n: int = 5, prop_count: int = 2, profiles=dst.PROFILES,
              mutation=None, reads: int = 2, verbose: bool = True,
              peer_chunk=None, active_rows=None) -> dict:
    """One explore() call; returns a result summary dict (importable)."""
    cfg = _cfg(n, seed, reads, peer_chunk, active_rows)
    batch, names = dst.make_batch(cfg, ticks=ticks, schedules=schedules,
                                  seed=seed, profiles=profiles)
    res = dst.explore(init_state(cfg), cfg, batch, profiles=names,
                      prop_count=prop_count, mutation=mutation)
    by_profile: dict[str, int] = {}
    for s in res.violating:
        by_profile[names[s]] = by_profile.get(names[s], 0) + 1
    out = {
        "schedules": schedules, "ticks": ticks, "seed": seed, "n": n,
        "mutation": mutation,
        "violations": int((res.viol != 0).sum()),
        "violating_profiles": by_profile,
        "elapsed": round(res.elapsed, 3),
        "schedules_per_sec": round(res.schedules_per_sec, 1),
    }
    if verbose:
        tag = f" [mutation={mutation}]" if mutation else ""
        print(f"explored {schedules} schedules x {ticks} ticks x {n} rows"
              f"{tag}: {out['violations']} violation(s), "
              f"{out['elapsed']}s ({out['schedules_per_sec']} schedules/s)",
              flush=True)
    out["_result"] = res
    out["_batch"] = batch
    out["_names"] = names
    out["_cfg"] = cfg
    return out


def run_mutation_demo(schedules: int = 24, ticks: int = 100, seed: int = 0,
                      n: int = 5, prop_count: int = 2,
                      mutation: str = DEFAULT_MUTATION,
                      out_path=None, profiles=None,
                      verbose: bool = True, peer_chunk=None,
                      active_rows=None) -> dict:
    """Detect -> shrink -> dump -> replay one seeded mutation repro."""
    if profiles is None:
        profiles = MUTATION_PROFILES.get(mutation, dst.PROFILES)
    sweep = run_sweep(schedules, ticks, seed, n, prop_count, profiles,
                      mutation=mutation, verbose=verbose,
                      peer_chunk=peer_chunk, active_rows=active_rows)
    res, batch, names, cfg = (sweep["_result"], sweep["_batch"],
                              sweep["_names"], sweep["_cfg"])
    demo = {"mutation": mutation, "caught": bool(len(res.violating)),
            "violations": sweep["violations"]}
    if not demo["caught"]:
        if verbose:
            print(f"mutation {mutation!r} NOT caught "
                  f"({schedules}x{ticks}, seed {seed})", flush=True)
        return demo

    s = int(res.violating[0])
    sched = batch.slice(s)
    viol = int(res.viol[s])
    before = dst.fault_count(sched)
    small, evals = dst.shrink(cfg, sched, viol, prop_count, mutation)
    v2, f2 = dst.replay(cfg, small, prop_count, mutation)
    # post-mortem: re-run the shrunk schedule with the flight recorder on
    # so the artifact carries the event window explaining the violation
    flight = dst.capture_flight(cfg, small, prop_count, mutation,
                                first_tick=f2)
    art = dst.to_artifact(cfg, small, seed=seed, profile=names[s], index=s,
                          prop_count=prop_count, mutation=mutation,
                          viol=v2, first_tick=f2, flight=flight)
    out_path = _cli_common.artifact_path(out_path,
                                         f"dst_repro_{mutation}.json")
    dst.save_artifact(out_path, art)
    verdict = dst.replay_artifact(out_path)
    demo.update({
        "profile": names[s], "index": s,
        "bits": dst.bits_to_names(viol),
        "fault_count_before": before,
        "fault_count_after": dst.fault_count(small),
        "shrink_evals": evals,
        "artifact": out_path,
        "replay_matches": verdict["matches_recorded"],
        "oracle_diverged_at": verdict["oracle"]["diverged_at"],
        "flight_events": len(flight["window"]),
    })
    if verbose:
        print(f"mutation {mutation!r} caught ({demo['bits']}, profile "
              f"{demo['profile']}): shrunk {before} -> "
              f"{demo['fault_count_after']} fault-events in {evals} replays",
              flush=True)
        oracle_note = (
            f"oracle trace localizes divergence at tick "
            f"{demo['oracle_diverged_at']}"
            if demo["oracle_diverged_at"] >= 0 else
            "oracle view agrees (mutation corrupts only read registers, "
            "outside the oracle's field view)")
        print(f"repro artifact: {out_path} — replay "
              f"{'reproduces exactly' if demo['replay_matches'] else 'DIVERGED'},"
              f" {oracle_note}", flush=True)
        tail = flight["record"].window(6)
        if tail:
            print(f"flight window (last {len(tail)} device events before "
                  f"the violation):", flush=True)
            for e in tail:
                print("  " + e.describe(), flush=True)
    return demo


def run_term_inflation_demo(schedules: int = 8, ticks: int = 60,
                            seed: int = 7, n: int = 5, prop_count: int = 2,
                            verbose: bool = True) -> dict:
    """Seed-pinned PreVote demo: the `term_inflation` adversary forces one
    victim row's election timer over and over; without PreVote every
    forced campaign bumps the cluster term (the classic rejoin-storm term
    inflation PreVote exists to stop), with PreVote the victim's poll is
    non-binding and lease-holding voters refuse, so terms stay near the
    fault-free baseline.  Safety must hold either way — inflation is a
    liveness/availability tax, not a safety bug."""
    import dataclasses

    out = {"schedules": schedules, "ticks": ticks, "seed": seed, "n": n}
    base = _cfg(n, seed)
    for key, pv in (("no_prevote", False), ("prevote", True)):
        cfg = dataclasses.replace(base, pre_vote=pv)
        batch, names = dst.make_batch(cfg, ticks=ticks, schedules=schedules,
                                      seed=seed,
                                      profiles=("term_inflation",))
        res = dst.explore(init_state(cfg), cfg, batch, profiles=names,
                          prop_count=prop_count)
        import numpy as np
        out[key] = {
            "max_term": int(np.asarray(res.final_state.term).max()),
            "violations": int((res.viol != 0).sum()),
        }
    out["neutralized"] = (
        out["no_prevote"]["max_term"] >= 2 * out["prevote"]["max_term"]
        and out["no_prevote"]["violations"] == 0
        and out["prevote"]["violations"] == 0)
    if verbose:
        print(f"term_inflation x{schedules} schedules x {ticks} ticks: "
              f"max term {out['no_prevote']['max_term']} without PreVote "
              f"vs {out['prevote']['max_term']} with it "
              f"({out['no_prevote']['violations']}/"
              f"{out['prevote']['violations']} safety violations) — "
              f"{'PreVote neutralizes the storm' if out['neutralized'] else 'NOT neutralized'}",
              flush=True)
    return out


def run_disruptive_rejoin_demo(schedules: int = 8, ticks: int = 120,
                               seed: int = 7, n: int = 5,
                               prop_count: int = 2,
                               verbose: bool = True) -> dict:
    """Seed-pinned rejoin-storm demo: the `disruptive_rejoin` adversary
    heals a partitioned victim and has it campaign on every OTHER timeout
    from then on.  Without PreVote + CheckQuorum each barrage deposes the
    standing leader (every re-election lands in the churn histogram);
    with both defenses the rejoiner's poll is non-binding and leaseholding
    voters ignore it, so the cluster keeps its first leader.  The
    SLO_LEADER_CHURN bound witnesses the contrast: defense-off trips it,
    defense-on stays clean."""
    import dataclasses

    import numpy as np

    out = {"schedules": schedules, "ticks": ticks, "seed": seed, "n": n}
    base = dataclasses.replace(_cfg(n, seed, reads=0),
                               collect_telemetry=True, slo_leader_changes=2)
    for key, (pv, cq) in (("defense_off", (False, False)),
                          ("defense_on", (True, True))):
        cfg = dataclasses.replace(base, pre_vote=pv, check_quorum=cq)
        batch, names = dst.make_batch(cfg, ticks=ticks, schedules=schedules,
                                      seed=seed,
                                      profiles=("disruptive_rejoin",))
        res = dst.explore(init_state(cfg), cfg, batch, profiles=names,
                          prop_count=prop_count)
        wins = np.asarray(res.final_state.tel_elect_hist) \
            .reshape(schedules, -1).sum(axis=1)
        out[key] = {
            "max_leader_changes": int(wins.max()),
            "churn_violations":
                int(((res.viol & dst.SLO_LEADER_CHURN) != 0).sum()),
            "violations": int((res.viol != 0).sum()),
        }
    out["neutralized"] = (out["defense_off"]["churn_violations"] > 0
                          and out["defense_on"]["violations"] == 0)
    if verbose:
        print(f"disruptive_rejoin x{schedules} schedules x {ticks} ticks: "
              f"{out['defense_off']['max_leader_changes']} leader changes "
              f"without PreVote+CheckQuorum "
              f"({out['defense_off']['churn_violations']} SLO_LEADER_CHURN "
              f"trips) vs {out['defense_on']['max_leader_changes']} with "
              f"them ({out['defense_on']['violations']} violations) — "
              f"{'defenses neutralize the rejoin storm' if out['neutralized'] else 'NOT neutralized'}",
              flush=True)
    return out


def run_transfer_abuse_demo(schedules: int = 8, ticks: int = 120,
                            seed: int = 7, n: int = 5, prop_count: int = 2,
                            cooldown: int = 60,
                            verbose: bool = True) -> dict:
    """Seed-pinned transfer-thrash demo: the `transfer_abuse` adversary
    keeps requesting leadership transfers toward alternating targets.
    Without a cooldown every accepted TimeoutNow completes an election
    (leadership ping-pongs dozens of times per run); with
    `transfer_cooldown_ticks` a leader grants at most one transfer per
    window, so churn stays near the single initial election.  The
    SLO_LEADER_CHURN bound witnesses the contrast."""
    import dataclasses

    import numpy as np

    out = {"schedules": schedules, "ticks": ticks, "seed": seed, "n": n,
           "cooldown": cooldown}
    base = dataclasses.replace(_cfg(n, seed, reads=0),
                               collect_telemetry=True, slo_leader_changes=8)
    for key, cool in (("defense_off", 0), ("defense_on", cooldown)):
        cfg = dataclasses.replace(base, transfer_cooldown_ticks=cool)
        batch, names = dst.make_batch(cfg, ticks=ticks, schedules=schedules,
                                      seed=seed, profiles=("transfer_abuse",))
        res = dst.explore(init_state(cfg), cfg, batch, profiles=names,
                          prop_count=prop_count)
        wins = np.asarray(res.final_state.tel_elect_hist) \
            .reshape(schedules, -1).sum(axis=1)
        out[key] = {
            "max_leader_changes": int(wins.max()),
            "churn_violations":
                int(((res.viol & dst.SLO_LEADER_CHURN) != 0).sum()),
            "violations": int((res.viol != 0).sum()),
        }
    out["neutralized"] = (out["defense_off"]["churn_violations"] > 0
                          and out["defense_on"]["violations"] == 0)
    if verbose:
        print(f"transfer_abuse x{schedules} schedules x {ticks} ticks: "
              f"{out['defense_off']['max_leader_changes']} leader changes "
              f"without a transfer cooldown "
              f"({out['defense_off']['churn_violations']} SLO_LEADER_CHURN "
              f"trips) vs {out['defense_on']['max_leader_changes']} with "
              f"cooldown={cooldown} ({out['defense_on']['violations']} "
              f"violations) — "
              f"{'cooldown neutralizes the thrash' if out['neutralized'] else 'NOT neutralized'}",
              flush=True)
    return out


def run_lost_tail_demo(schedules: int = 8, ticks: int = 120, seed: int = 7,
                       n: int = 5, prop_count: int = 2, out_path=None,
                       verbose: bool = True) -> dict:
    """Seed-pinned durability demo: the `lost_tail` storage fault crashes
    EVERY row on one tick and truncates each log to its fsynced watermark
    (correlated power loss, the classic fsync-lag data-loss scenario).
    Without ack-gating followers acknowledge appends the disk has not yet
    synced, so the cluster can commit entries no surviving copy holds —
    the DURABILITY witness (an acked commit above every surviving log)
    trips at the crash tick.  With ``ack_gating`` rows only ack what
    their watermark covers, committed implies durable on a quorum, and
    the SAME schedules come back clean.  The first counterexample is
    shrunk and dumped as a replay-exact artifact; the differential
    oracle must hold lockstep over the clean prefix (the crash tick IS
    the violation tick, so the SAFETY_BITS truncation bounds the compare
    right before the host oracle's perfect disk stops being a model)."""
    import dataclasses

    out = {"schedules": schedules, "ticks": ticks, "seed": seed, "n": n}
    off = dataclasses.replace(_cfg(n, seed, reads=0), fsync_lag_ticks=6)
    on = dataclasses.replace(off, ack_gating=True)
    batch, names = dst.make_batch(off, ticks=ticks, schedules=schedules,
                                  seed=seed, profiles=("lost_tail",))
    r_off = dst.explore(init_state(off), off, batch, profiles=names,
                        prop_count=prop_count)
    caught = [int(s) for s in r_off.violating
              if int(r_off.viol[s]) & dst.DURABILITY]
    out["caught"] = len(caught)
    r_on = dst.explore(init_state(on), on, batch, profiles=names,
                       prop_count=prop_count)
    out["gated_violations"] = int((r_on.viol != 0).sum())
    if not caught:
        out["neutralized"] = False
        if verbose:
            print(f"lost_tail NOT caught with gating off "
                  f"({schedules}x{ticks}, seed {seed})", flush=True)
        return out

    s = caught[0]
    sched = batch.slice(s)
    before = dst.fault_count(sched)
    small, evals = dst.shrink(off, sched, dst.DURABILITY, prop_count)
    v2, f2 = dst.replay(off, small, prop_count)
    flight = dst.capture_flight(off, small, prop_count, first_tick=f2)
    art = dst.to_artifact(off, small, seed=seed, profile=names[s], index=s,
                          prop_count=prop_count, mutation=None,
                          viol=v2, first_tick=f2, flight=flight)
    out_path = _cli_common.artifact_path(out_path,
                                         "dst_repro_lost_tail.json")
    dst.save_artifact(out_path, art)
    verdict = dst.replay_artifact(out_path)
    out.update({
        "bits": dst.bits_to_names(v2),
        "first_tick": f2,
        "fault_count_before": before,
        "fault_count_after": dst.fault_count(small),
        "shrink_evals": evals,
        "artifact": out_path,
        "replay_matches": verdict["matches_recorded"],
        "oracle_diverged_at": verdict["oracle"]["diverged_at"],
    })
    out["neutralized"] = (out["gated_violations"] == 0
                          and out["replay_matches"]
                          and out["oracle_diverged_at"] == -1)
    if verbose:
        print(f"lost_tail x{schedules} schedules x {ticks} ticks: "
              f"gating-off caught {out['caught']} DURABILITY trips "
              f"(first at tick {f2}), shrunk {before} -> "
              f"{out['fault_count_after']} fault-events in {evals} replays",
              flush=True)
        print(f"repro artifact: {out_path} — replay "
              f"{'reproduces exactly' if out['replay_matches'] else 'DIVERGED'}, "
              f"oracle {'lockstep over the clean prefix' if out['oracle_diverged_at'] == -1 else 'diverged at tick %d' % out['oracle_diverged_at']}, "
              f"gating-on {out['gated_violations']} violations — "
              f"{'ack-gating makes committed mean durable' if out['neutralized'] else 'NOT neutralized'}",
              flush=True)
        tail = flight["record"].window(6)
        if tail:
            print(f"flight window (last {len(tail)} device events before "
                  f"the crash):", flush=True)
            for e in tail:
                print("  " + e.describe(), flush=True)
    return out


def replay_artifact_file(path: str, verbose: bool = True) -> dict:
    verdict = dst.replay_artifact(path)
    if verbose:
        print(f"replayed {path}: {verdict['violations']} at tick "
              f"{verdict['first_tick']} — "
              f"{'matches recorded run' if verdict['matches_recorded'] else 'MISMATCH'}",
              flush=True)
        tr = verdict["oracle"]
        if tr["trace"]:
            first = tr["trace"][0]
            print(f"oracle divergence at tick {tr['diverged_at']}: "
                  f"fields {first['fields']}", flush=True)
        else:
            print("differential oracle agrees with the kernel on every "
                  "tick (stock-kernel artifact)", flush=True)
    return verdict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    _cli_common.add_common_args(ap)
    ap.add_argument("--schedules", type=int, default=256)
    ap.add_argument("--ticks", type=int, default=100)
    ap.add_argument("--n", type=int, default=5, help="cluster rows")
    ap.add_argument("--profiles", default=",".join(dst.PROFILES),
                    help=f"comma list from "
                    f"{dst.PROFILES + dst.EXTRA_PROFILES}")
    ap.add_argument("--reads", type=int, default=2,
                    help="per-row linearizable read batch size; arms the "
                    "LINEARIZABLE_READ checker (0 = read-free kernel)")
    ap.add_argument("--peer-chunk", type=int, default=None,
                    help="peer-axis lowering: 0 = dense [N, N] tallies, a "
                    "divisor of --n (multiple of 8) = hierarchical banded "
                    "quorum counts; default = SimConfig default (dense at "
                    "DST cluster sizes)")
    _cli_common.add_active_rows_arg(ap)
    ap.add_argument("--mutate", default=None,
                    help="run ONLY a mutation sweep with this broken-kernel "
                    "knob (e.g. commit_no_quorum) instead of stock+demo")
    ap.add_argument("--no-mutation-demo", action="store_true",
                    help="skip the detection self-test after the sweep")
    _cli_common.add_demo_arg(ap, "term-inflation",
                             "run ONLY the seed-pinned PreVote-neutralizes-"
                             "term-inflation scenario and exit")
    _cli_common.add_demo_arg(ap, "disruptive-rejoin",
                             "run ONLY the seed-pinned PreVote+CheckQuorum-"
                             "neutralize-rejoin-storm scenario and exit")
    _cli_common.add_demo_arg(ap, "transfer-abuse",
                             "run ONLY the seed-pinned cooldown-neutralizes-"
                             "transfer-thrash scenario and exit")
    _cli_common.add_demo_arg(ap, "lost-tail",
                             "run ONLY the seed-pinned ack-gating-makes-"
                             "committed-durable scenario (correlated "
                             "power-loss tail truncation) and exit")
    args = ap.parse_args(argv)
    prop_count = 2 if args.prop_count is None else args.prop_count

    if args.replay:
        return 0 if replay_artifact_file(args.replay)["matches_recorded"] \
            else 1

    if args.term_inflation_demo:
        demo = run_term_inflation_demo(
            min(args.schedules, 8), min(args.ticks, 60),
            args.seed if args.seed else 7, args.n, prop_count)
        return 0 if demo["neutralized"] else 1

    # the attack demos pin their tick counts: the churn bounds they
    # assert against are calibrated to the 120-tick window (a longer run
    # legitimately accumulates more cooldown-paced transfers)
    if args.disruptive_rejoin_demo:
        demo = run_disruptive_rejoin_demo(
            min(args.schedules, 8), seed=args.seed if args.seed else 7,
            n=args.n, prop_count=prop_count)
        return 0 if demo["neutralized"] else 1

    if args.transfer_abuse_demo:
        demo = run_transfer_abuse_demo(
            min(args.schedules, 8), seed=args.seed if args.seed else 7,
            n=args.n, prop_count=prop_count)
        return 0 if demo["neutralized"] else 1

    if args.lost_tail_demo:
        demo = run_lost_tail_demo(
            min(args.schedules, 8), seed=args.seed if args.seed else 7,
            n=args.n, prop_count=prop_count, out_path=args.out)
        return 0 if demo["neutralized"] else 1

    profiles = tuple(p for p in args.profiles.split(",") if p)
    for p in profiles:
        if p not in dst.PROFILES + dst.EXTRA_PROFILES:
            ap.error(f"unknown profile {p!r}")

    if args.mutate:
        demo = run_mutation_demo(args.schedules, args.ticks, args.seed,
                                 args.n, prop_count, args.mutate,
                                 out_path=args.out,
                                 peer_chunk=args.peer_chunk,
                                 active_rows=args.active_rows)
        return 0 if demo["caught"] and demo.get("replay_matches") else 1

    sweep = run_sweep(args.schedules, args.ticks, args.seed, args.n,
                      prop_count, profiles, reads=args.reads,
                      peer_chunk=args.peer_chunk,
                      active_rows=args.active_rows)
    ok = sweep["violations"] == 0
    if not ok:
        res, names = sweep["_result"], sweep["_names"]
        for s in res.violating[:8]:
            print(f"  VIOLATION schedule {s} ({names[s]}): "
                  f"{dst.bits_to_names(int(res.viol[s]))} "
                  f"at tick {int(res.first_tick[s])}", flush=True)

    if not args.no_mutation_demo:
        for mutation in (DEFAULT_MUTATION, "stale_lease_read"):
            demo = run_mutation_demo(
                min(args.schedules, 24), args.ticks, args.seed, args.n,
                prop_count, mutation,
                out_path=args.out if mutation == DEFAULT_MUTATION else None,
                peer_chunk=args.peer_chunk,
                active_rows=args.active_rows)
            ok = ok and demo["caught"] and demo.get("replay_matches", False)

    print("PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

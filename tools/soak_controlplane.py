"""Control-plane churn soak + gRPC agent-session load harness.

Two modes share this tool:

* **Churn soak** (default): the in-process cluster under continuous
  leader kills, drains, scaling and rolling updates for a wall-clock
  budget — the aux-subsystem analog of the reference's long-running
  integration/CI passes (SURVEY §5 failure detection/recovery).  Every
  cycle asserts the cluster converges back to the desired state, and
  the soak fails loudly on any wedge, crash, or leaked task.

* **Load harness** (``--agents N``): thousands of simulated agent
  sessions over the REAL gRPC wire — each agent registers through the
  dispatcher Session stream, heartbeats on its own timer (client-timed
  RTT), and a hot subset consumes Assignments streams and writes task
  statuses back, while a workload loop scales a service up and down to
  keep assignments flowing and a churn loop re-registers cold agents
  (node churn).  Managers run with the coalescing proposal pipeline
  (store/pipeline.py) and the jitted scheduler kernel enabled, so the
  harness is the end-to-end stage for the vectorized control plane:
  it reports assignments/s, proposals-per-batch, and heartbeat-RTT p99
  both client-side and through the server histogram ladder (PR 9).

Usage:
  python tools/soak_controlplane.py [--minutes 20] [--transport inproc|device]
  python tools/soak_controlplane.py --minutes 2 --agents 5000 [--active 256]
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

# Pin the platform only when jax is not yet live: standalone tool runs
# must never dial a wedged TPU tunnel, but an embedding caller (bench.py
# configs) already picked its backend and the pin would clobber it.
if "jax" not in sys.modules:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swarmkit_tpu.api import NodeAvailability  # noqa: E402
from swarmkit_tpu.manager.controlapi import FailedPrecondition  # noqa: E402
from swarmkit_tpu.raft.node import ErrLostLeadership  # noqa: E402
from tests.integration_harness import TestCluster  # noqa: E402


async def soak(minutes: float, transport: str) -> int:
    if transport == "device":
        from swarmkit_tpu.transport import DeviceMeshNet, DeviceMeshTransport
        c = TestCluster(network=DeviceMeshNet(seed=9, rows=8),
                        transport_factory=DeviceMeshTransport)
    else:
        c = TestCluster(seed=9)
    deadline = time.time() + minutes * 60
    cycles = 0
    try:
        await c.add_manager("m1")
        await c.add_manager("m2")
        await c.add_manager("m3")
        await c.add_agent("a1")
        await c.add_agent("a2")
        await c.poll_cluster_ready(managers=3, workers=2)
        svc = await c.create_service("soak", replicas=4)

        async def wait_running(want: int, timeout: float = 60.0,
                               pred=None, why: str = "") -> None:
            """Converge to `want` RUNNING tasks (the harness's notion of
            running), all additionally satisfying `pred` — the drain and
            rolling-update phases pass a predicate so the OLD task set
            cannot satisfy the wait before the orchestrator reacts."""
            await c.wait_leader()
            t0 = time.time()
            while time.time() - t0 < timeout:
                ts = [t for t in c.running_tasks(svc.id)
                      if pred is None or pred(t)]
                if len(ts) == want:
                    return
                await asyncio.sleep(0.1)
                await c.wait_leader()
            raise AssertionError(
                f"cycle {cycles}: never reached {want} running {why}")

        async def retry_update(fetch, update, mutate, what: str) -> None:
            """Read-modify-write with conflict retry: dispatcher
            heartbeat/status write-backs bump object versions
            concurrently, so out-of-sequence is an expected race the
            operator (here: the soak) retries — reference semantics.
            `fetch(lead)` returns the current object; `update(lead,
            spec, version)` awaits the write."""
            for _ in range(50):
                lead = await c.wait_leader()
                cur = fetch(lead)
                spec = cur.spec.copy()
                mutate(spec)
                try:
                    await update(lead, spec, cur.meta.version.index)
                    return
                except FailedPrecondition:
                    await asyncio.sleep(0.05)
                except ErrLostLeadership:
                    # a concurrent leader kill (phase 0 of an adjacent
                    # cycle, or CheckQuorum) raced the write: a real
                    # client re-resolves the leader and retries — so
                    # does the soak
                    await asyncio.sleep(0.1)
            raise AssertionError(
                f"cycle {cycles}: {what} update never won the race")

        async def update_node_retry(node_id: str, mutate) -> None:
            await retry_update(
                lambda lead: lead.store.get("node", node_id),
                lambda lead, spec, ver: lead.control_api.update_node(
                    node_id, spec, version=ver),
                mutate, f"node {node_id}")

        async def update_service_retry(mutate) -> None:
            await retry_update(
                lambda lead: lead.control_api.get_service(svc.id),
                lambda lead, spec, ver: lead.control_api.update_service(
                    svc.id, spec, version=ver),
                mutate, "service")

        await wait_running(4)
        while time.time() < deadline:
            cycles += 1
            phase = cycles % 4
            if phase == 0:
                # kill + restart the leader
                victim = (await c.wait_leader()).node_id
                await c.stop_node(victim)
                await c.wait_leader(timeout=60)
                await wait_running(4)
                await c.restart_node(victim)
                await c.wait_leader(timeout=60)
            elif phase == 1:
                # drain one agent, wait for re-placement, reactivate
                def _drain(spec):
                    spec.availability = NodeAvailability.DRAIN

                def _activate(spec):
                    spec.availability = NodeAvailability.ACTIVE

                await update_node_retry("a1", _drain)
                await wait_running(4, pred=lambda t: t.node_id != "a1",
                                   why="off the drained node")
                await update_node_retry("a1", _activate)
            elif phase == 2:
                # scale up then back down
                def _scale7(spec):
                    spec.replicated.replicas = 7

                def _scale4(spec):
                    spec.replicated.replicas = 4

                await update_service_retry(_scale7)
                await wait_running(7)
                await update_service_retry(_scale4)
                await wait_running(4)
            else:
                # rolling update to a fresh image
                img = f"img-{cycles}"

                def _reimage(spec):
                    spec.task.container.image = img

                await update_service_retry(_reimage)
                await wait_running(
                    4, pred=lambda t: t.spec.container.image == img,
                    why=f"on updated image {img}")
            if cycles % 5 == 0:
                lead = await c.wait_leader()
                n_tasks = len(lead.store.find("task"))
                print(f"[{time.strftime('%H:%M:%S')}] cycle {cycles} ok "
                      f"({n_tasks} task records)", flush=True)
                # leak guard: the reaper must keep history bounded
                assert n_tasks < 4 * 10 + 40, \
                    f"task records leaking: {n_tasks}"
        print(f"SOAK OK: {cycles} cycles on {transport} transport")
        return 0
    finally:
        await c.stop_all()


# ---------------------------------------------------------------------------
# gRPC agent-session load harness (--agents N)

def _pct(sorted_vals: list, p: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(p * len(sorted_vals)))]


def _hist_quantile(fam, q: float) -> float:
    """Interpolated quantile from a label-less catalog histogram child —
    the PR 9 ladder read-out (upper-edge interpolation, +Inf bucket
    reported as the top finite edge)."""
    child = fam._default()
    if child.count == 0:
        return 0.0
    target = q * child.count
    seen = 0
    lo = 0.0
    for i, n in enumerate(child.counts):
        if n == 0:
            continue
        hi = (child.buckets[i] if i < len(child.buckets)
              else child.buckets[-1])
        if seen + n >= target:
            frac = (target - seen) / n
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        seen += n
        lo = hi
    return lo


class _LoadStats:
    def __init__(self) -> None:
        self.heartbeats = 0
        self.hb_errors = 0
        self.rtt: list[float] = []
        self.assignments = 0
        self.statuses = 0
        self.churns = 0
        self.registrations = 0


class _SimAgent:
    """One simulated agent: register via the Session stream, heartbeat on
    a timer (client-timed RTT), optionally consume Assignments and write
    statuses back.  The session STREAM is closed after the first message
    — the registration sticks and heartbeats keep the TTL alive — so N
    agents cost N heartbeat timers, not N live node-event watchers."""

    def __init__(self, idx: int, node_id: str, desc, stats: _LoadStats,
                 dial, hb_interval: float, active: bool) -> None:
        self.idx = idx
        self.node_id = node_id
        self.desc = desc
        self.stats = stats
        self.dial = dial          # dial(idx) -> RemoteDispatcher (leader)
        self.hb = hb_interval
        self.active = active
        self.disp = None
        self.session_id = ""
        self.alive = False
        self.reported: dict[str, str] = {}

    async def register(self) -> None:
        from swarmkit_tpu.rpc import NotLeader

        delay = 0.1
        for _ in range(12):
            disp = self.dial(self.idx)
            gen = disp.session(self.node_id, self.desc, "", addr="")
            try:
                msg = await gen.__anext__()
                self.session_id = msg.session_id
                self.disp = disp
                self.alive = True
                self.stats.registrations += 1
                return
            except (NotLeader, Exception):
                await asyncio.sleep(delay)
                delay = min(2.0, delay * 2)
            finally:
                await gen.aclose()
        raise RuntimeError(f"{self.node_id}: registration never succeeded")

    async def heartbeat_loop(self, stop: asyncio.Event) -> None:
        import random as _random
        rng = _random.Random(self.idx)
        await asyncio.sleep(rng.uniform(0, self.hb))  # desynchronize
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                await self.disp.heartbeat(self.node_id, self.session_id)
                self.stats.rtt.append(time.perf_counter() - t0)
                self.stats.heartbeats += 1
                self.alive = True
            except Exception:
                self.stats.hb_errors += 1
                self.alive = False
                try:
                    await self.register()
                except Exception:
                    pass
            try:
                await asyncio.wait_for(
                    stop.wait(), self.hb * rng.uniform(0.8, 1.2))
            except asyncio.TimeoutError:
                pass

    async def assignments_loop(self, stop: asyncio.Event) -> None:
        from swarmkit_tpu.api import TaskState, TaskStatus
        from swarmkit_tpu.api.dispatcher_msgs import AssignmentAction

        while not stop.is_set():
            try:
                async for am in self.disp.assignments(self.node_id,
                                                      self.session_id):
                    updates = []
                    for ch in am.changes:
                        t = ch.assignment.task
                        if t is None:
                            continue
                        if ch.action == AssignmentAction.REMOVE:
                            self.reported.pop(t.id, None)
                            continue
                        if t.desired_state >= TaskState.SHUTDOWN:
                            if self.reported.get(t.id) != "down":
                                self.reported[t.id] = "down"
                                updates.append((t.id, TaskStatus(
                                    state=TaskState.SHUTDOWN,
                                    message="sim-agent")))
                        elif t.id not in self.reported:
                            self.reported[t.id] = "up"
                            self.stats.assignments += 1
                            updates.append((t.id, TaskStatus(
                                state=TaskState.RUNNING,
                                message="sim-agent")))
                    if updates:
                        await self.disp.update_task_status(
                            self.node_id, self.session_id, updates)
                        self.stats.statuses += len(updates)
                    if stop.is_set():
                        return
            except Exception:
                if stop.is_set():
                    return
                await asyncio.sleep(0.5)


async def load(minutes: float, agents: int, managers: int = 3,
               active: int = 0, heartbeat: float = 5.0,
               replicas: int = 0, update_every: float = 10.0,
               churn_per_s: int = 8, coalesce_window: float = 0.002,
               report_every: float = 15.0, use_kernel: bool = True,
               sustain_floor: float = 0.0) -> dict:
    """Drive `agents` simulated sessions over real sockets for `minutes`.
    Returns the summary dict (also printed as JSON by the CLI)."""
    import socket
    import tempfile

    import grpc

    from swarmkit_tpu.api import (
        Annotations, ContainerSpec, MembershipState, NodeDescription,
        NodeResources, NodeSpec, Placement, Platform, ReplicatedService,
        ServiceSpec, TaskSpec, TaskState,
    )
    from swarmkit_tpu.api.objects import Node as ApiNode, NodeStatus
    from swarmkit_tpu.manager.controlapi import FailedPrecondition
    from swarmkit_tpu.manager.manager import Manager
    from swarmkit_tpu.metrics import catalog as obs_catalog
    from swarmkit_tpu.raft.grpc_transport import GrpcNetwork
    from swarmkit_tpu.raft.node import ErrLostLeadership
    from swarmkit_tpu.rpc import ClusterService, RemoteDispatcher
    from swarmkit_tpu.store.pipeline import CoalesceConfig

    active = min(agents, active or max(32, min(256, agents // 4)))
    # one orchestrator reconcile writes the whole delta in one txn, so the
    # scale ceiling stays under MAX_CHANGES_PER_TRANSACTION (200)
    replicas = replicas or min(2 * active, 192)
    # everything — 3 managers, the raft wire, and every simulated agent —
    # shares ONE Python event loop, so the aggregate heartbeat rate is
    # the scaling ceiling: stretch the interval to keep it near 400/s
    # (5k agents -> 12.5s, 10k -> 25s; an explicit larger value wins)
    heartbeat = max(heartbeat, agents / 400.0)

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    net = GrpcNetwork()
    tmp = tempfile.TemporaryDirectory(prefix="swarm-load-")
    addrs = [f"127.0.0.1:{free_port()}" for _ in range(managers)]
    mgrs: list[Manager] = []
    stats = _LoadStats()
    stop = asyncio.Event()
    channels: dict[str, list] = {}
    pool = max(2, min(32, agents // 256))
    sims: list[_SimAgent] = []
    bg: list[asyncio.Task] = []
    try:
        for i, addr in enumerate(addrs):
            # a registration/heartbeat burst can stall the shared loop for
            # seconds; a 10s election timeout rides it out instead of
            # cascading into elections + wedge-triggered transfers
            m = Manager(node_id=f"m{i}", addr=addr, network=net,
                        state_dir=f"{tmp.name}/m{i}",
                        join_addr=addrs[0] if i else "",
                        tick_interval=0.25, election_tick=40, seed=70 + i,
                        coalesce=CoalesceConfig(window=coalesce_window),
                        sched_use_kernel=use_kernel)

            class _Ref:
                security = None

                def __init__(self, mgr):
                    self._mgr = mgr

                def _running_manager(self):
                    return self._mgr

            net.add_service(addr, ClusterService(
                lambda ref=_Ref(m): ref).handlers())
            await m.start()
            mgrs.append(m)
            if i == 0:
                while not m.is_leader():
                    await asyncio.sleep(0.02)

        def leader() -> Manager:
            for m in mgrs:
                if m.is_leader():
                    return m
            return mgrs[0]

        def dial(idx: int) -> RemoteDispatcher:
            addr = leader().addr
            chans = channels.setdefault(addr, [])
            while len(chans) < pool:
                chans.append(grpc.aio.insecure_channel(addr, options=[
                    ("grpc.max_send_message_length", 64 << 20),
                    ("grpc.max_receive_message_length", 64 << 20)]))
            return RemoteDispatcher(chans[idx % pool])

        # -- node records (the hot `active` subset is labeled for
        #    placement; everything else sustains sessions + heartbeats) --
        lead = leader()
        t_setup = time.perf_counter()

        # the dispatcher TTL is 3x ITS period (the cluster spec), not the
        # client's timer — align them or a slow ramp expires early
        # registrations before their first heartbeat
        if heartbeat > 5.0:
            def _set_period(tx):
                cl = tx.find("cluster")[0]
                cl.spec.dispatcher.heartbeat_period = heartbeat
                tx.update(cl)
            await lead.store.update(_set_period)

        async def mknode(i: int) -> None:
            pool_lbl = "hot" if i < active else "cold"
            for _ in range(10):
                try:
                    await leader().store.update(lambda tx: tx.create(ApiNode(
                        id=f"ld{i}",
                        spec=NodeSpec(
                            annotations=Annotations(name=f"ld{i}",
                                                    labels={"pool": pool_lbl}),
                            membership=MembershipState.ACCEPTED),
                        status=NodeStatus())))
                    return
                except Exception:
                    await asyncio.sleep(0.2)

        for base in range(0, agents, 512):
            await asyncio.gather(*(mknode(i)
                                   for i in range(base,
                                                  min(base + 512, agents))))
        setup_nodes_s = time.perf_counter() - t_setup

        for i in range(agents):
            desc = NodeDescription(
                hostname=f"ld{i}",
                platform=Platform(architecture="x86_64", os="linux"),
                resources=NodeResources(nano_cpus=4_000_000_000,
                                        memory_bytes=8 << 30))
            sims.append(_SimAgent(i, f"ld{i}", desc, stats, dial,
                                  heartbeat, active=i < active))

        # hot agents first (their nodes must be READY before the service
        # lands), then ramp the cold fleet in waves
        t_ramp = time.perf_counter()
        for base in range(0, active, 128):
            wave = sims[base:base + 128]
            await asyncio.gather(*(s.register() for s in wave))
            # heartbeats start per-wave so early registrations never
            # outlive the TTL while later waves are still ramping
            for s in wave:
                bg.append(asyncio.create_task(s.heartbeat_loop(stop)))
        for s in sims[:active]:
            bg.append(asyncio.create_task(s.assignments_loop(stop)))

        svc = await lead.control_api.create_service(ServiceSpec(
            annotations=Annotations(name="load"),
            task=TaskSpec(container=ContainerSpec(image="img-0"),
                          placement=Placement(
                              constraints=["node.labels.pool==hot"])),
            replicated=ReplicatedService(replicas=replicas)))

        for base in range(active, agents, 256):
            wave = sims[base:base + 256]
            await asyncio.gather(*(s.register() for s in wave))
            for s in wave:
                bg.append(asyncio.create_task(s.heartbeat_loop(stop)))
            await asyncio.sleep(0)
        ramp_s = time.perf_counter() - t_ramp

        # -- workload: scale between replicas and replicas//2 to keep
        #    assignments (and SHUTDOWN acks) flowing ----------------------
        async def scale_to(n: int) -> None:
            for _ in range(50):
                ld = leader()
                try:
                    cur = ld.control_api.get_service(svc.id)
                    spec = cur.spec.copy()
                    spec.replicated.replicas = n
                    await ld.control_api.update_service(
                        svc.id, spec, version=cur.meta.version.index)
                    return
                except (FailedPrecondition, ErrLostLeadership, Exception):
                    await asyncio.sleep(0.1)

        async def workload() -> None:
            hi, lo = replicas, max(1, replicas // 2)
            cur = hi
            while not stop.is_set():
                try:
                    await asyncio.wait_for(stop.wait(), update_every)
                    return
                except asyncio.TimeoutError:
                    pass
                cur = lo if cur == hi else hi
                await scale_to(cur)

        async def churn() -> None:
            # round-robin re-registration across the cold fleet; the
            # cycle length keeps any node under the dispatcher's
            # 3-per-8s rate limit
            cold = sims[active:] or sims
            i = 0
            k = max(1, min(churn_per_s, len(cold) // 16 or 1))
            while not stop.is_set():
                try:
                    await asyncio.wait_for(stop.wait(), 1.0)
                    return
                except asyncio.TimeoutError:
                    pass
                batch = [cold[(i + j) % len(cold)] for j in range(k)]
                i += k
                for s in batch:
                    try:
                        await s.register()
                        stats.churns += 1
                    except Exception:
                        pass

        async def reporter() -> None:
            last_hb = last_as = 0
            while not stop.is_set():
                try:
                    await asyncio.wait_for(stop.wait(), report_every)
                    return
                except asyncio.TimeoutError:
                    pass
                ld = leader()
                packed = obs_catalog.get(
                    ld.obs, "swarm_cpl_proposals_total").labels(
                    outcome="committed").value
                txns = obs_catalog.get(
                    ld.obs, "swarm_cpl_txns_total").labels(
                    outcome="committed").value
                rtt = sorted(stats.rtt[-20000:])
                print(f"[{time.strftime('%H:%M:%S')}] "
                      f"hb/s={(stats.heartbeats - last_hb) / report_every:.0f} "
                      f"rtt_p99={_pct(rtt, 0.99) * 1e3:.1f}ms "
                      f"assign/s={(stats.assignments - last_as) / report_every:.1f} "
                      f"entries/proposal="
                      f"{txns / packed if packed else 1.0:.1f} "
                      f"alive={sum(s.alive for s in sims)}/{agents} "
                      f"churns={stats.churns}", flush=True)
                last_hb, last_as = stats.heartbeats, stats.assignments

        bg += [asyncio.create_task(workload()),
               asyncio.create_task(churn()),
               asyncio.create_task(reporter())]

        t0 = time.perf_counter()
        await asyncio.sleep(minutes * 60)
        elapsed = time.perf_counter() - t0
        sustained = sum(s.alive for s in sims)
        stop.set()
        await asyncio.gather(*bg, return_exceptions=True)
        bg.clear()

        lead = leader()
        rtt = sorted(stats.rtt)
        packed = obs_catalog.get(lead.obs, "swarm_cpl_proposals_total") \
            .labels(outcome="committed").value
        txns = obs_catalog.get(lead.obs, "swarm_cpl_txns_total") \
            .labels(outcome="committed").value
        server_p99 = _hist_quantile(obs_catalog.get(
            lead.obs, "swarm_dispatcher_heartbeat_rtt_seconds"), 0.99)
        kernel_groups = obs_catalog.get(
            lead.obs, "swarm_sched_kernel_groups_total") \
            .labels(path="kernel").value
        result = {
            "agents": agents, "active": active, "managers": managers,
            "minutes": round(elapsed / 60, 2),
            "replicas": replicas,
            "setup_nodes_s": round(setup_nodes_s, 2),
            "ramp_s": round(ramp_s, 2),
            "heartbeats": stats.heartbeats,
            "heartbeats_per_s": round(stats.heartbeats / elapsed, 1),
            "hb_errors": stats.hb_errors,
            "rtt_p50_ms": round(_pct(rtt, 0.5) * 1e3, 2),
            "rtt_p99_ms": round(_pct(rtt, 0.99) * 1e3, 2),
            "server_rtt_p99_ms": round(server_p99 * 1e3, 2),
            "assignments": stats.assignments,
            "assignments_per_s": round(stats.assignments / elapsed, 2),
            "status_writes": stats.statuses,
            "entries_per_proposal": round(txns / packed, 2)
            if packed else 1.0,
            "kernel_groups": int(kernel_groups),
            "churns": stats.churns,
            "agents_sustained": sustained,
        }
        # publish the headline series through the telemetry registry so
        # bench_gate / scrapers see the same numbers the CLI prints
        cfg = f"grpc-{agents}"
        obs_catalog.get(lead.obs, "swarm_bench_assignments_per_second") \
            .labels(config=cfg).set(result["assignments_per_s"])
        obs_catalog.get(lead.obs, "swarm_bench_agents_sustained") \
            .labels(config=cfg).set(sustained)
        obs_catalog.get(lead.obs, "swarm_bench_heartbeat_rtt_p99_seconds") \
            .labels(config=cfg).set(_pct(rtt, 0.99))
        if sustain_floor and sustained < sustain_floor * agents:
            result["error"] = (f"only {sustained}/{agents} agents alive at "
                               f"deadline (floor {sustain_floor})")
        return result
    finally:
        stop.set()
        for t in bg:
            t.cancel()
        if bg:
            await asyncio.gather(*bg, return_exceptions=True)
        for chans in channels.values():
            for ch in chans:
                await ch.close()
        for m in mgrs:
            try:
                await m.stop()
            except Exception:
                pass
        await net.close()


def main() -> int:
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=20.0)
    ap.add_argument("--transport", choices=["inproc", "device"],
                    default="inproc")
    ap.add_argument("--agents", type=int, default=0,
                    help="run the gRPC load harness with N simulated "
                         "agent sessions instead of the churn soak")
    ap.add_argument("--active", type=int, default=0,
                    help="hot subset consuming assignments streams "
                         "(default: agents/4 clamped to [32, 256])")
    ap.add_argument("--managers", type=int, default=3)
    ap.add_argument("--heartbeat", type=float, default=5.0,
                    help="agent heartbeat interval seconds")
    ap.add_argument("--replicas", type=int, default=0,
                    help="service scale ceiling (default 2x active)")
    ap.add_argument("--update-every", type=float, default=10.0,
                    help="seconds between service scale flips")
    ap.add_argument("--churn", type=int, default=8,
                    help="cold-agent re-registrations per second")
    ap.add_argument("--coalesce-window", type=float, default=0.002)
    ap.add_argument("--report-every", type=float, default=15.0)
    ap.add_argument("--no-kernel", action="store_true",
                    help="schedule on the host path instead of the "
                         "jitted kernel")
    ap.add_argument("--sustain-floor", type=float, default=0.0,
                    help="fail unless this fraction of agents is alive "
                         "at the deadline (e.g. 0.99)")
    args = ap.parse_args()
    if args.agents > 0:
        result = asyncio.run(load(
            args.minutes, args.agents, managers=args.managers,
            active=args.active, heartbeat=args.heartbeat,
            replicas=args.replicas, update_every=args.update_every,
            churn_per_s=args.churn, coalesce_window=args.coalesce_window,
            report_every=args.report_every, use_kernel=not args.no_kernel,
            sustain_floor=args.sustain_floor))
        json.dump(result, sys.stdout)
        sys.stdout.write("\n")
        return 1 if "error" in result else 0
    return asyncio.run(soak(args.minutes, args.transport))


if __name__ == "__main__":
    raise SystemExit(main())

"""Control-plane churn soak: the in-process cluster under continuous
leader kills, drains, scaling and rolling updates for a wall-clock budget.

The aux-subsystem analog of the reference's long-running integration/CI
passes (SURVEY §5 failure detection/recovery): every cycle asserts the
cluster converges back to the desired state, and the soak fails loudly on
any wedge (convergence timeout), crash, or leaked task.

Usage:
  python tools/soak_controlplane.py [--minutes 20] [--transport inproc|device]
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swarmkit_tpu.api import NodeAvailability  # noqa: E402
from swarmkit_tpu.manager.controlapi import FailedPrecondition  # noqa: E402
from swarmkit_tpu.raft.node import ErrLostLeadership  # noqa: E402
from tests.integration_harness import TestCluster  # noqa: E402


async def soak(minutes: float, transport: str) -> int:
    if transport == "device":
        from swarmkit_tpu.transport import DeviceMeshNet, DeviceMeshTransport
        c = TestCluster(network=DeviceMeshNet(seed=9, rows=8),
                        transport_factory=DeviceMeshTransport)
    else:
        c = TestCluster(seed=9)
    deadline = time.time() + minutes * 60
    cycles = 0
    try:
        await c.add_manager("m1")
        await c.add_manager("m2")
        await c.add_manager("m3")
        await c.add_agent("a1")
        await c.add_agent("a2")
        await c.poll_cluster_ready(managers=3, workers=2)
        svc = await c.create_service("soak", replicas=4)

        async def wait_running(want: int, timeout: float = 60.0,
                               pred=None, why: str = "") -> None:
            """Converge to `want` RUNNING tasks (the harness's notion of
            running), all additionally satisfying `pred` — the drain and
            rolling-update phases pass a predicate so the OLD task set
            cannot satisfy the wait before the orchestrator reacts."""
            await c.wait_leader()
            t0 = time.time()
            while time.time() - t0 < timeout:
                ts = [t for t in c.running_tasks(svc.id)
                      if pred is None or pred(t)]
                if len(ts) == want:
                    return
                await asyncio.sleep(0.1)
                await c.wait_leader()
            raise AssertionError(
                f"cycle {cycles}: never reached {want} running {why}")

        async def retry_update(fetch, update, mutate, what: str) -> None:
            """Read-modify-write with conflict retry: dispatcher
            heartbeat/status write-backs bump object versions
            concurrently, so out-of-sequence is an expected race the
            operator (here: the soak) retries — reference semantics.
            `fetch(lead)` returns the current object; `update(lead,
            spec, version)` awaits the write."""
            for _ in range(50):
                lead = await c.wait_leader()
                cur = fetch(lead)
                spec = cur.spec.copy()
                mutate(spec)
                try:
                    await update(lead, spec, cur.meta.version.index)
                    return
                except FailedPrecondition:
                    await asyncio.sleep(0.05)
                except ErrLostLeadership:
                    # a concurrent leader kill (phase 0 of an adjacent
                    # cycle, or CheckQuorum) raced the write: a real
                    # client re-resolves the leader and retries — so
                    # does the soak
                    await asyncio.sleep(0.1)
            raise AssertionError(
                f"cycle {cycles}: {what} update never won the race")

        async def update_node_retry(node_id: str, mutate) -> None:
            await retry_update(
                lambda lead: lead.store.get("node", node_id),
                lambda lead, spec, ver: lead.control_api.update_node(
                    node_id, spec, version=ver),
                mutate, f"node {node_id}")

        async def update_service_retry(mutate) -> None:
            await retry_update(
                lambda lead: lead.control_api.get_service(svc.id),
                lambda lead, spec, ver: lead.control_api.update_service(
                    svc.id, spec, version=ver),
                mutate, "service")

        await wait_running(4)
        while time.time() < deadline:
            cycles += 1
            phase = cycles % 4
            if phase == 0:
                # kill + restart the leader
                victim = (await c.wait_leader()).node_id
                await c.stop_node(victim)
                await c.wait_leader(timeout=60)
                await wait_running(4)
                await c.restart_node(victim)
                await c.wait_leader(timeout=60)
            elif phase == 1:
                # drain one agent, wait for re-placement, reactivate
                def _drain(spec):
                    spec.availability = NodeAvailability.DRAIN

                def _activate(spec):
                    spec.availability = NodeAvailability.ACTIVE

                await update_node_retry("a1", _drain)
                await wait_running(4, pred=lambda t: t.node_id != "a1",
                                   why="off the drained node")
                await update_node_retry("a1", _activate)
            elif phase == 2:
                # scale up then back down
                def _scale7(spec):
                    spec.replicated.replicas = 7

                def _scale4(spec):
                    spec.replicated.replicas = 4

                await update_service_retry(_scale7)
                await wait_running(7)
                await update_service_retry(_scale4)
                await wait_running(4)
            else:
                # rolling update to a fresh image
                img = f"img-{cycles}"

                def _reimage(spec):
                    spec.task.container.image = img

                await update_service_retry(_reimage)
                await wait_running(
                    4, pred=lambda t: t.spec.container.image == img,
                    why=f"on updated image {img}")
            if cycles % 5 == 0:
                lead = await c.wait_leader()
                n_tasks = len(lead.store.find("task"))
                print(f"[{time.strftime('%H:%M:%S')}] cycle {cycles} ok "
                      f"({n_tasks} task records)", flush=True)
                # leak guard: the reaper must keep history bounded
                assert n_tasks < 4 * 10 + 40, \
                    f"task records leaking: {n_tasks}"
        print(f"SOAK OK: {cycles} cycles on {transport} transport")
        return 0
    finally:
        await c.stop_all()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=20.0)
    ap.add_argument("--transport", choices=["inproc", "device"],
                    default="inproc")
    args = ap.parse_args()
    return asyncio.run(soak(args.minutes, args.transport))


if __name__ == "__main__":
    raise SystemExit(main())

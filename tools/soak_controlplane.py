"""Control-plane churn soak: the in-process cluster under continuous
leader kills, drains, scaling and rolling updates for a wall-clock budget.

The aux-subsystem analog of the reference's long-running integration/CI
passes (SURVEY §5 failure detection/recovery): every cycle asserts the
cluster converges back to the desired state, and the soak fails loudly on
any wedge (convergence timeout), crash, or leaked task.

Usage:
  python tools/soak_controlplane.py [--minutes 20] [--transport inproc|device]
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from swarmkit_tpu.api import NodeAvailability, TaskState  # noqa: E402
from swarmkit_tpu.store.by import ByService  # noqa: E402
from tests.integration_harness import TestCluster  # noqa: E402


async def soak(minutes: float, transport: str) -> int:
    if transport == "device":
        from swarmkit_tpu.transport import DeviceMeshNet, DeviceMeshTransport
        c = TestCluster(network=DeviceMeshNet(seed=9, rows=8),
                        transport_factory=DeviceMeshTransport)
    else:
        c = TestCluster(seed=9)
    deadline = time.time() + minutes * 60
    cycles = 0
    try:
        await c.add_manager("m1")
        await c.add_manager("m2")
        await c.add_manager("m3")
        await c.add_agent("a1")
        await c.add_agent("a2")
        await c.poll_cluster_ready(managers=3, workers=2)
        svc = await c.create_service("soak", replicas=4)

        async def wait_running(want: int, timeout: float = 60.0) -> None:
            lead = await c.wait_leader()
            t0 = time.time()
            while time.time() - t0 < timeout:
                ts = [t for t in lead.store.find("task", ByService(svc.id))
                      if t.status.state == TaskState.RUNNING
                      and int(t.desired_state) == int(TaskState.RUNNING)]
                if len(ts) == want:
                    return
                await asyncio.sleep(0.1)
                lead = await c.wait_leader()
            raise AssertionError(
                f"cycle {cycles}: never reached {want} running")

        await wait_running(4)
        while time.time() < deadline:
            cycles += 1
            phase = cycles % 4
            lead = await c.wait_leader()
            if phase == 0:
                # kill + restart the leader
                victim = lead.node_id
                await c.stop_node(victim)
                await c.wait_leader(timeout=60)
                await wait_running(4)
                await c.restart_node(victim)
                await c.wait_leader(timeout=60)
            elif phase == 1:
                # drain one agent, wait for re-placement, reactivate
                node = lead.store.get("node", "a1")
                spec = node.spec.copy()
                spec.availability = NodeAvailability.DRAIN
                await lead.control_api.update_node(
                    "a1", spec, version=node.meta.version.index)
                await wait_running(4)
                node = (await c.wait_leader()).store.get("node", "a1")
                spec = node.spec.copy()
                spec.availability = NodeAvailability.ACTIVE
                await (await c.wait_leader()).control_api.update_node(
                    "a1", spec, version=node.meta.version.index)
            elif phase == 2:
                # scale up then back down
                cur = lead.control_api.get_service(svc.id)
                spec = cur.spec.copy()
                spec.replicated.replicas = 7
                await lead.control_api.update_service(
                    svc.id, spec, version=cur.meta.version.index)
                await wait_running(7)
                lead = await c.wait_leader()
                cur = lead.control_api.get_service(svc.id)
                spec = cur.spec.copy()
                spec.replicated.replicas = 4
                await lead.control_api.update_service(
                    svc.id, spec, version=cur.meta.version.index)
                await wait_running(4)
            else:
                # rolling update to a fresh image
                cur = lead.control_api.get_service(svc.id)
                spec = cur.spec.copy()
                spec.task.container.image = f"img-{cycles}"
                await lead.control_api.update_service(
                    svc.id, spec, version=cur.meta.version.index)
                await wait_running(4)
            if cycles % 5 == 0:
                lead = await c.wait_leader()
                n_tasks = len(lead.store.find("task"))
                print(f"[{time.strftime('%H:%M:%S')}] cycle {cycles} ok "
                      f"({n_tasks} task records)", flush=True)
                # leak guard: the reaper must keep history bounded
                assert n_tasks < 4 * 10 + 40, \
                    f"task records leaking: {n_tasks}"
        print(f"SOAK OK: {cycles} cycles on {transport} transport")
        return 0
    finally:
        await c.stop_all()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--minutes", type=float, default=20.0)
    ap.add_argument("--transport", choices=["inproc", "device"],
                    default="inproc")
    args = ap.parse_args()
    return asyncio.run(soak(args.minutes, args.transport))


if __name__ == "__main__":
    raise SystemExit(main())

"""Export the model checker's reached LTS as an Aldebaran ``.aut`` file.

The formal-model cross-validation bridge: the mCRL2/LNT Raft models
(PAPERS.md arXiv:2403.18916, arXiv:2004.13284) verify a hand-written
abstraction with explicit-state tools whose common interchange format is
Aldebaran —

    des (<initial>, <transitions>, <states>)
    (<src>, "<action label>", <dst>)
    ...

This tool runs ``mc.exhaustive_scan(collect_edges=True)`` on a smoke-
sized scope against the REAL tick kernel and emits the reached labeled
transition system in that format, so the kernel-derived behavior can be
loaded into the same toolchains (ltsconvert / ltscompare / CADP) that
checked the paper models — e.g. to minimize modulo branching
bisimulation or diff against an abstraction.  Labels are the scan's
action alphabet ("noop", "crash_1", "part_0v12", ...).

``--check`` validates the emitted file with the dependency-free
structural validator below (no mCRL2/CADP in this container): header
arity, transition count, id ranges, label quoting, determinism of the
(src, label) relation, and reachability of every state from the initial
one.

Usage:
    python tools/mc_export.py --scope smoke --out cluster.aut --check
"""

from __future__ import annotations

import argparse
import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import _cli_common  # noqa: E402

_cli_common.bootstrap()

_AUT_HEADER = re.compile(r'^des\s*\(\s*(\d+)\s*,\s*(\d+)\s*,\s*(\d+)\s*\)\s*$')
_AUT_EDGE = re.compile(r'^\(\s*(\d+)\s*,\s*"([^"]*)"\s*,\s*(\d+)\s*\)\s*$')


def write_aut(path: str, edges, num_states: int, names,
              initial: int = 0) -> None:
    """Write (src, action_idx, dst) edges as an Aldebaran LTS."""
    with open(path, "w", encoding="utf-8") as f:
        f.write(f"des ({initial}, {len(edges)}, {num_states})\n")
        for src, aid, dst in edges:
            f.write(f'({src}, "{names[aid]}", {dst})\n')


def validate_aut(path: str, deterministic: bool = True) -> list[str]:
    """Structural problems with an ``.aut`` file (empty = valid).

    Checks: one well-formed ``des`` header; exactly the declared number
    of well-formed transition lines; every state id in range; the
    initial state in range; every state reachable from the initial one
    (the scan emits the REACHED LTS, so an orphan means an exporter
    bug); and — for the kernel's deterministic tick — at most one
    successor per (src, label) pair.
    """
    problems: list[str] = []
    try:
        with open(path, encoding="utf-8") as f:
            lines = [ln.rstrip("\n") for ln in f if ln.strip()]
    except OSError as e:
        return [f"unreadable: {e}"]
    if not lines:
        return ["empty file"]
    m = _AUT_HEADER.match(lines[0])
    if not m:
        return [f"bad header {lines[0]!r} (want 'des (i, t, s)')"]
    initial, ntrans, nstates = (int(g) for g in m.groups())
    if initial >= nstates:
        problems.append(f"initial state {initial} >= state count {nstates}")
    if len(lines) - 1 != ntrans:
        problems.append(f"header declares {ntrans} transitions, file has "
                        f"{len(lines) - 1}")
    succ: dict[tuple, int] = {}
    adj: dict[int, list] = {}
    for i, ln in enumerate(lines[1:], start=2):
        e = _AUT_EDGE.match(ln)
        if not e:
            problems.append(f"line {i}: bad transition {ln!r}")
            continue
        src, label, dst = int(e.group(1)), e.group(2), int(e.group(3))
        if src >= nstates or dst >= nstates:
            problems.append(f"line {i}: state id out of range "
                            f"({src}, {dst}) >= {nstates}")
            continue
        if deterministic:
            prev = succ.setdefault((src, label), dst)
            if prev != dst:
                problems.append(f"line {i}: ({src}, {label!r}) maps to both "
                                f"{prev} and {dst} (kernel tick must be "
                                "deterministic)")
        adj.setdefault(src, []).append(dst)
    if not problems:
        seen = {initial}
        stack = [initial]
        while stack:
            for dst in adj.get(stack.pop(), ()):
                if dst not in seen:
                    seen.add(dst)
                    stack.append(dst)
        if len(seen) != nstates:
            problems.append(f"only {len(seen)} of {nstates} states "
                            "reachable from the initial state")
    return problems


def export_scope(scope_name: str, out_path: str, mutation=None,
                 verbose: bool = True):
    """Scan a scope with edge collection on and write its ``.aut``."""
    from swarmkit_tpu import mc

    scope = mc.SCOPES[scope_name]
    res = mc.exhaustive_scan(
        scope.cfg(), scope.alphabet(), scope.horizon,
        prop_count=scope.prop_count, mutation=mutation,
        budget=scope.budget, collect_edges=True, scope=scope_name,
        stop_on_violation=False,
        log=print if verbose else None)
    write_aut(out_path, res.edges, res.num_states, scope.alphabet().names)
    if verbose:
        print(f"wrote {out_path}: {res.num_states:,} states, "
              f"{len(res.edges):,} transitions "
              f"({len(scope.alphabet().names)} labels, horizon "
              f"{scope.horizon})", flush=True)
    return res


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--scope", default="smoke",
                    help="scope preset to export (default smoke: edge "
                    "collection walks every child on the host, keep it "
                    "small)")
    ap.add_argument("--out", default=None,
                    help=".aut destination (default: temp dir)")
    ap.add_argument("--mutate", default=None,
                    help="export the LTS of a mutated kernel instead "
                    "(violating states become deadlocks: their branches "
                    "are pruned)")
    ap.add_argument("--check", action="store_true",
                    help="validate the emitted file and exit nonzero on "
                    "any structural problem")
    ap.add_argument("--validate", default=None, metavar="AUT",
                    help="only validate an existing .aut file and exit")
    args = ap.parse_args(argv)

    if args.validate:
        problems = validate_aut(args.validate)
        for p in problems:
            print(f"AUT: {p}", flush=True)
        print(f"{len(problems)} problem(s) in {args.validate}", flush=True)
        return 1 if problems else 0

    out = args.out or os.path.join(tempfile.gettempdir(),
                                   f"mc_{args.scope}.aut")
    export_scope(args.scope, out, mutation=args.mutate)
    if args.check:
        problems = validate_aut(out)
        for p in problems:
            print(f"AUT: {p}", flush=True)
        print(("PASS" if not problems else "FAIL")
              + f" — {len(problems)} problem(s)", flush=True)
        return 1 if problems else 0
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

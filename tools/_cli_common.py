"""Shared CLI plumbing for the sweep tools (dst_sweep.py, mc_sweep.py).

Both sweeps are seed-pinned counterexample factories with the same
operational surface — a deterministic seed, an artifact destination, a
replay entry point — so the env bootstrap, the common flags and the
artifact-path resolution live here once.

`bootstrap()` MUST run before anything imports jax: it pins the CPU
backend and the 8-virtual-device XLA topology the sweeps shard over.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile


def bootstrap() -> None:
    """Idempotent env + sys.path setup; call before importing jax."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)


def add_common_args(ap: argparse.ArgumentParser) -> None:
    """The flags every sweep shares: determinism pin + artifact routing."""
    ap.add_argument("--seed", type=int, default=0,
                    help="PRNG seed pinned into every schedule and every "
                    "repro artifact (replays are exact)")
    ap.add_argument("--out", default=None,
                    help="repro-artifact destination: a .json path, or a "
                    "directory to drop default-named artifacts into "
                    "(default: the system temp dir)")
    ap.add_argument("--prop-count", type=int, default=None,
                    help="proposals injected per tick (default: the "
                    "sweep's own)")
    ap.add_argument("--replay", default=None, metavar="ARTIFACT",
                    help="replay a JSON repro artifact and exit (works on "
                    "DST and model-checker artifacts alike)")


def add_demo_arg(ap: argparse.ArgumentParser, name: str,
                 help_text: str) -> None:
    """Register a ``--<name>-demo`` flag.  The seed-pinned adversary
    demos (term-inflation, disruptive-rejoin, transfer-abuse) share one
    CLI idiom: run ONLY the named defense-off vs defense-on scenario,
    print the headline contrast, and exit 0 iff the defense neutralizes
    the attack with zero violations."""
    ap.add_argument(f"--{name}-demo", action="store_true", help=help_text)


def add_active_rows_arg(ap: argparse.ArgumentParser) -> None:
    """The role-sparse progress lowering knob both sweep vocabularies
    share (SimConfig.active_rows): 0 = dense elementwise per-peer
    writes, a multiple of 8 below n = [A, N] slab lowering with the
    dense fallback armed.  None leaves the SimConfig default."""
    ap.add_argument("--active-rows", type=int, default=None, metavar="A",
                    help="role-sparse progress lowering "
                    "(SimConfig.active_rows): 0 = dense elementwise "
                    "per-peer writes, a multiple of 8 < n = [A, N] slab "
                    "kernel; default = SimConfig default")


def active_rows_kw(active_rows) -> dict:
    """SimConfig kwargs for an --active-rows value (None = default)."""
    return {} if active_rows is None else {"active_rows": active_rows}


def artifact_path(out, default_name: str) -> str:
    """Resolve --out (None | directory | file path) to a file path."""
    if out is None:
        return os.path.join(tempfile.gettempdir(), default_name)
    if os.path.isdir(out) or out.endswith(os.sep):
        os.makedirs(out, exist_ok=True)
        return os.path.join(out, default_name)
    parent = os.path.dirname(os.path.abspath(out))
    os.makedirs(parent, exist_ok=True)
    return out

"""Live-daemon debug surface: the `swarmd --listen-debug` analog.

Reference: cmd/swarmd/main.go:4-8,183 serves Go pprof + expvar over HTTP
when --listen-debug is set, so an operator can inspect a WEDGED running
daemon.  The asyncio build's equivalents:

  /debug/tasks    asyncio task dump (name, coro, current stack frame) —
                  the goroutine-stack-dump analog (signal.DumpStacks)
  /debug/store    write-lock state, in-flight proposal ages, WEDGED flag
                  (store.wedged(), reference memory.go:972), object
                  counts, current version
  /debug/queues   watch-queue fan-out: watcher count + per-watcher buffer
                  depth/overflow (watch/queue.go LimitQueue state)
  /debug/metrics  the metrics registry snapshot (expvar analog)
  /debug/vars     everything above in one JSON document

Served over a unix control socket or TCP with a minimal HTTP/1.0
responder — no framework, read-only, JSON bodies.
"""

from __future__ import annotations

import asyncio
import json
import logging
import sys
import time
from typing import Optional

log = logging.getLogger("swarmkit_tpu.debug")


def _task_dump() -> list[dict]:
    out = []
    for t in asyncio.all_tasks():
        coro = t.get_coro()
        frame = getattr(coro, "cr_frame", None)
        where = None
        if frame is not None:
            where = f"{frame.f_code.co_filename}:{frame.f_lineno}"
        out.append({
            "name": t.get_name(),
            "coro": getattr(coro, "__qualname__", str(coro)),
            "state": ("cancelled" if t.cancelled() else
                      "done" if t.done() else "pending"),
            "at": where,
        })
    return sorted(out, key=lambda d: d["name"])


def _store_dump(store) -> dict:
    now = store._now()
    # oldest first: the stuck proposal IS the diagnostic
    in_flight = sorted((round(now - t0, 3)
                        for t0 in store._in_flight.values()), reverse=True)
    counts = {}
    for kind, table in store._tables.items():
        counts[kind] = len(table.objects)
    return {
        "wedged": store.wedged(),
        "wedge_timeout_s": store.WEDGE_TIMEOUT,
        "write_lock_held": store._write_lock.locked(),
        "in_flight_proposals": len(in_flight),
        "in_flight_ages_s": in_flight[:32],
        "version": store._local_version,
        "objects": counts,
    }


def _queue_dump(store) -> dict:
    q = store.queue
    watchers = []
    for w in list(q._watchers):
        watchers.append({
            "depth": len(w),
            "limit": w._limit,
            "overflowed": w.overflowed,
            "closed": w.closed,
        })
    return {
        "watchers": len(watchers),
        "max_depth": max((w["depth"] for w in watchers), default=0),
        "detail": sorted(watchers, key=lambda d: -d["depth"])[:64],
    }


class DebugServer:
    """Read-only diagnostic HTTP server bound to a unix socket or TCP
    port.  Takes the Node (manager may come and go with role changes);
    every request re-resolves the live store."""

    def __init__(self, node) -> None:
        self.node = node
        self._server: Optional[asyncio.AbstractServer] = None

    def _store(self):
        m = self.node._running_manager()
        return None if m is None else m.store

    def _registry(self):
        m = self.node._running_manager()
        return None if m is None else m.metrics_registry

    # ------------------------------------------------------------------
    def snapshot(self, path: str) -> tuple[int, dict]:
        store = self._store()
        if path in ("/", "/debug", "/debug/vars"):
            body = {
                "node_id": self.node.node_id,
                "is_manager": store is not None,
                "is_leader": self.node.is_leader(),
                "time": time.time(),
                "python": sys.version.split()[0],
                "tasks": _task_dump(),
            }
            if store is not None:
                body["store"] = _store_dump(store)
                body["queues"] = _queue_dump(store)
            reg = self._registry()
            if reg is not None:
                body["metrics"] = reg.snapshot()
            return 200, body
        if path == "/debug/tasks":
            return 200, {"tasks": _task_dump()}
        if store is None:
            return 503, {"error": "no running manager on this node"}
        if path == "/debug/store":
            return 200, _store_dump(store)
        if path == "/debug/queues":
            return 200, _queue_dump(store)
        if path == "/debug/metrics":
            reg = self._registry()
            return 200, reg.snapshot() if reg is not None else {}
        return 404, {"error": f"unknown path {path}",
                     "paths": ["/debug/vars", "/debug/tasks",
                               "/debug/store", "/debug/queues",
                               "/debug/metrics"]}

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            line = await asyncio.wait_for(reader.readline(), timeout=5.0)
            parts = line.decode("latin-1").split()
            path = parts[1] if len(parts) >= 2 else "/"
            # drain headers (HTTP/1.0, ignore body); bounded so a
            # slow-drip client cannot pin the handler forever
            for _ in range(100):
                h = await asyncio.wait_for(reader.readline(), timeout=5.0)
                if h in (b"\r\n", b"\n", b""):
                    break
            status, body = self.snapshot(path.split("?")[0])
            payload = json.dumps(body, default=str).encode()
            reason = {200: "OK", 404: "Not Found",
                      503: "Service Unavailable"}.get(status, "OK")
            writer.write(
                f"HTTP/1.0 {status} {reason}\r\n"
                f"Content-Type: application/json\r\n"
                f"Content-Length: {len(payload)}\r\n\r\n".encode())
            writer.write(payload)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError):
            pass
        except Exception:
            log.exception("debug request failed")
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def start(self, listen: str) -> None:
        """listen: 'host:port' (IPv6 hosts bracketed, '[::1]:8080') for
        TCP, anything else = unix socket path."""
        if ":" in listen and not listen.startswith(("/", ".")):
            host, port = listen.rsplit(":", 1)
            host = host.strip("[]")
            self._server = await asyncio.start_server(
                self._handle, host or "127.0.0.1", int(port))
        else:
            self._server = await asyncio.start_unix_server(
                self._handle, path=listen)
        log.info("debug server listening on %s", listen)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

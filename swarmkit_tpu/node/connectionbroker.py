"""Chooses which manager an agent-side RPC talks to.

Reference: connectionbroker/broker.go (123 LoC) — prefer the local manager
when this node runs one (the reference dials the local socket and lets the
generated raft proxies forward to the leader); otherwise pick a remote from
the weighted address book.  In this in-process build the "dial" is a
``dialer(addr) -> Manager`` lookup, and instead of RPC-level proxying we
resolve to the current LEADER's dispatcher directly (the proxy's net
effect).
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from swarmkit_tpu.node.remotes import Remotes

log = logging.getLogger("swarmkit_tpu.connectionbroker")


class NoManagerError(Exception):
    pass


class ConnectionBroker:
    def __init__(self, remotes: Remotes,
                 dialer: Callable[[str], Optional[object]],
                 local_manager: Callable[[], Optional[object]] = lambda: None
                 ) -> None:
        self.remotes = remotes
        self.dialer = dialer
        self.local_manager = local_manager

    def _leader_of(self, manager) -> Optional[object]:
        """Resolve a manager to the cluster leader's Manager object."""
        if manager is None:
            return None
        try:
            if manager.is_leader():
                return manager
            leader_addr = manager.leader_addr
        except Exception:
            return None
        if not leader_addr:
            return None
        return self.dialer(leader_addr)

    def select_leader(self):
        """Resolve the cluster leader's Manager, preferring the local
        manager as the route in (reference: broker.Select, local socket
        first).  Raises NoManagerError when unreachable."""
        candidates = []
        local = self.local_manager()
        if local is not None:
            candidates.append(local)
        tried = {id(local)} if local is not None else set()
        for addr in sorted(self.remotes.weights(),
                           key=lambda a: -self.remotes.weights()[a]):
            m = self.dialer(addr)
            if m is not None and id(m) not in tried:
                candidates.append(m)
                tried.add(id(m))
        for m in candidates:
            leader = self._leader_of(m)
            if leader is not None:
                return leader
        raise NoManagerError("cannot locate the cluster leader")

    def select_dispatcher(self):
        d = self.select_leader().dispatcher
        if d is None:
            # a RemoteManager whose channel hasn't connected yet
            raise NoManagerError("leader connection not established yet")
        return d

    def select_logbroker(self):
        lb = getattr(self.select_leader(), "logbroker", None)
        if lb is None:
            raise NoManagerError("leader connection not established yet")
        return lb

    def select_control(self):
        c = self.select_leader().control_api
        if c is None:
            raise NoManagerError("leader connection not established yet")
        return c

    def select_ca(self):
        ca = self.select_leader().ca_server
        if ca is None:
            raise NoManagerError("leader has no CA server")
        return ca

"""Node lifecycle: one process-level member running an agent and, when its
role demands, a manager — with automatic promotion/demotion.

Reference: node/node.go (1352 LoC) — New (:194), Start (:251), run (:272):
load identity, start the agent (runAgent :559), supervise the manager
(superviseManager :1080: waitRole("manager") → runManager), tear the
manager down on demotion.  The reference learns its role from certificate
renewals; here the role arrives on the dispatcher session's node object
(the CA layer adds the certificate path on top of this seam).
"""

from __future__ import annotations

import asyncio
import logging
import os
from dataclasses import dataclass, field
from typing import Callable, Optional

from swarmkit_tpu.agent import Agent, AgentConfig
from swarmkit_tpu.agent.exec import Executor
from swarmkit_tpu.api import NodeRole, Peer
from swarmkit_tpu.manager.manager import Manager
from swarmkit_tpu.node.connectionbroker import ConnectionBroker
from swarmkit_tpu.node.remotes import Remotes
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.node")


@dataclass
class NodeConfig:
    """reference: node.Config node/node.go:194."""

    node_id: str
    state_dir: str
    executor: Executor
    network: object                      # raft transport Network
    dialer: Callable[[str], Optional[Manager]]   # addr -> Manager lookup
    listen_addr: str = ""
    join_addr: str = ""
    join_token: str = ""
    is_manager: bool = False             # initial role
    force_new_cluster: bool = False
    tick_interval: float = 1.0
    election_tick: int = 10
    heartbeat_tick: int = 1
    clock: Optional[Clock] = None
    seed: int = 0


class Node:
    def __init__(self, config: NodeConfig) -> None:
        self.config = config
        self.clock = config.clock or SystemClock()
        self.node_id = config.node_id
        self.addr = config.listen_addr or f"{config.node_id}:4242"
        self.manager: Optional[Manager] = None
        self.remotes = Remotes()
        if config.join_addr:
            self.remotes.observe(Peer(addr=config.join_addr))
        self.broker = ConnectionBroker(
            self.remotes, config.dialer, lambda: self._running_manager())
        self.agent: Optional[Agent] = None
        self._desired_manager = config.is_manager
        self._role_evt = asyncio.Event()
        self._supervisor: Optional[asyncio.Task] = None
        self._running = False

    # ------------------------------------------------------------------
    def _running_manager(self) -> Optional[Manager]:
        m = self.manager
        return m if m is not None and m._running else None

    def is_manager(self) -> bool:
        return self._running_manager() is not None

    def is_leader(self) -> bool:
        m = self._running_manager()
        return m is not None and m.is_leader()

    # ------------------------------------------------------------------
    async def start(self) -> None:
        """reference: node.Start node/node.go:251 → run :272."""
        self._running = True
        if self.config.is_manager:
            await self._start_manager()
        self.agent = Agent(AgentConfig(
            node_id=self.node_id,
            executor=self.config.executor,
            connect=self.broker.select_dispatcher,
            addr=self.addr,
            db_path=os.path.join(self.config.state_dir, "tasks.db")
            if self.config.state_dir != ":memory:" else ":memory:",
            clock=self.clock,
            on_node_change=self._on_node_change,
            on_managers_change=self._on_managers_change))
        await self.agent.start()
        self._supervisor = asyncio.get_running_loop().create_task(
            self._supervise_manager())

    async def stop(self) -> None:
        self._running = False
        if self._supervisor is not None:
            self._supervisor.cancel()
            try:
                await self._supervisor
            except (asyncio.CancelledError, Exception):
                pass
            self._supervisor = None
        if self.agent is not None:
            await self.agent.stop()
            self.agent = None
        if self.manager is not None:
            await self.manager.stop()
            self.manager = None

    # ------------------------------------------------------------------
    def _on_node_change(self, node) -> None:
        """Role flips observed via the session stream
        (reference: the cert-renewal waitRole seam node/node.go:933)."""
        want = node.role == NodeRole.MANAGER
        if want != self._desired_manager:
            self._desired_manager = want
            self._role_evt.set()

    def _on_managers_change(self, managers) -> None:
        for wp in managers:
            self.remotes.observe(wp.peer)

    async def _supervise_manager(self) -> None:
        """reference: superviseManager node/node.go:1080."""
        try:
            while self._running:
                await self._role_evt.wait()
                self._role_evt.clear()
                if self._desired_manager and self.manager is None:
                    log.info("node %s promoted; starting manager",
                             self.node_id)
                    try:
                        await self._start_manager(join=True)
                    except Exception:
                        log.exception("manager start failed; will retry")
                        if self.manager is not None:
                            try:
                                await self.manager.stop()
                            except Exception:
                                pass
                            self.manager = None
                        self._role_evt.set()
                        await self.clock.sleep(1.0)
                elif not self._desired_manager and self.manager is not None:
                    log.info("node %s demoted; stopping manager",
                             self.node_id)
                    m, self.manager = self.manager, None
                    await m.stop()
        except asyncio.CancelledError:
            pass

    async def _start_manager(self, join: bool = False) -> None:
        join_addr = self.config.join_addr
        if join:
            # join via the current leader if we know one
            join_addr = self._leader_addr() or join_addr
        state_dir = self.config.state_dir
        if state_dir == ":memory:":
            # raft storage is always file-backed; give ephemeral nodes a
            # throwaway dir instead of a literal ":memory:" path in cwd
            import tempfile

            self._ephemeral_dir = tempfile.TemporaryDirectory(
                prefix=f"swarmkit-{self.node_id}-")
            state_dir = self._ephemeral_dir.name
        # raft storage appends its own "raft" subdir (raft/storage.py)
        self.manager = Manager(
            node_id=self.node_id, addr=self.addr,
            network=self.config.network, state_dir=state_dir,
            clock=self.clock, join_addr=join_addr,
            force_new_cluster=self.config.force_new_cluster,
            tick_interval=self.config.tick_interval,
            election_tick=self.config.election_tick,
            heartbeat_tick=self.config.heartbeat_tick,
            seed=self.config.seed)
        await self.manager.start()

    def _leader_addr(self) -> str:
        for addr in self.remotes.weights():
            m = self.config.dialer(addr)
            if m is not None:
                try:
                    if m.is_leader():
                        return m.addr
                    if m.leader_addr:
                        return m.leader_addr
                except Exception:
                    continue
        return ""

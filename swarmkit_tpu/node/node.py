"""Node lifecycle: one process-level member running an agent and, when its
role demands, a manager — with automatic promotion/demotion.

Reference: node/node.go (1352 LoC) — New (:194), Start (:251), run (:272):
load identity, start the agent (runAgent :559), supervise the manager
(superviseManager :1080: waitRole("manager") → runManager), tear the
manager down on demotion.  The reference learns its role from certificate
renewals; here the role arrives on the dispatcher session's node object
(the CA layer adds the certificate path on top of this seam).
"""

from __future__ import annotations

import asyncio
import logging
import os
from dataclasses import dataclass, field
from typing import Callable, Optional

from swarmkit_tpu.agent import Agent, AgentConfig
from swarmkit_tpu.agent.exec import Executor
from swarmkit_tpu.api import NodeRole, Peer
from swarmkit_tpu.ca import (
    MANAGER_ROLE_OU, KeyReadWriter, RootCA, SecurityConfig, TLSRenewer,
    create_csr, parse_identity,
)
from swarmkit_tpu.manager.manager import Manager
from swarmkit_tpu.node.connectionbroker import ConnectionBroker
from swarmkit_tpu.node.remotes import Remotes
from swarmkit_tpu.utils.clock import Clock, SystemClock
from swarmkit_tpu.utils.identity import new_id

log = logging.getLogger("swarmkit_tpu.node")


class _RenewClient:
    """Renews via the cluster CA and persists the result
    (reference: agent-side CA client in ca/renewer.go)."""

    def __init__(self, node: "Node") -> None:
        self.node = node

    async def renew_node_certificate(self, node_id: str, cert_pem: bytes):
        from swarmkit_tpu.ca import create_csr_from_key

        ca = self.node._ca_client()
        if ca is None:
            raise RuntimeError("no CA reachable for renewal")
        csr = create_csr_from_key(self.node.security.key_pem, node_id)
        issued = await ca.renew_node_certificate(node_id, cert_pem, csr)
        if self.node.keyrw is not None:
            self.node.keyrw.write(issued.cert_pem,
                                  self.node.security.key_pem)
        if issued.root_bundle:
            # a root rotation is distributing new trust: persist it and
            # refresh the in-memory trust store (old+new during the
            # transition, new-only once the rotation finalizes)
            from swarmkit_tpu.ca import RootCA

            if self.node.keyrw is not None:
                self.node.keyrw.write_root_ca(issued.root_bundle)
            self.node.security.root_ca = RootCA(issued.root_bundle)
        return issued


@dataclass
class NodeConfig:
    """reference: node.Config node/node.go:194."""

    node_id: str
    state_dir: str
    executor: Executor
    network: object                      # raft transport Network
    dialer: Callable[[str], Optional[Manager]]   # addr -> Manager lookup
    listen_addr: str = ""
    # address peers should DIAL (reference swarmd --advertise-remote-api):
    # differs from listen_addr when binding a wildcard/NAT-internal address
    advertise_addr: str = ""
    join_addr: str = ""
    join_token: str = ""
    is_manager: bool = False             # initial role
    force_new_cluster: bool = False
    unlock_key: Optional[bytes] = None   # autolock KEK for the node key
    tick_interval: float = 1.0
    election_tick: int = 10
    heartbeat_tick: int = 1
    clock: Optional[Clock] = None
    seed: int = 0
    # raft Transport selection (raft/node.py NodeOpts.transport_factory):
    # None = in-process Transport; DeviceMeshTransport (with a
    # DeviceMeshNet network) runs the manager quorum over the device
    # mailbox wire
    transport_factory: object = None


class Node:
    def __init__(self, config: NodeConfig) -> None:
        self.config = config
        self.clock = config.clock or SystemClock()
        self.node_id = config.node_id
        # self.addr is what this node ADVERTISES (raft member context, CSR,
        # manager address book); the listener binds listen_addr separately
        self.addr = (config.advertise_addr or config.listen_addr
                     or f"{config.node_id}:4242")
        self.manager: Optional[Manager] = None
        self.security: Optional[SecurityConfig] = None
        self.keyrw: Optional[KeyReadWriter] = None
        self.remotes = Remotes()
        if config.join_addr:
            self.remotes.observe(Peer(addr=config.join_addr))
        self.broker = ConnectionBroker(
            self.remotes, config.dialer, lambda: self._running_manager())
        self.agent: Optional[Agent] = None
        self._renewer: Optional[TLSRenewer] = None
        self._desired_manager = config.is_manager
        self._role_evt = asyncio.Event()
        self._supervisor: Optional[asyncio.Task] = None
        self._running = False

    # ------------------------------------------------------------------
    def _running_manager(self) -> Optional[Manager]:
        m = self.manager
        return m if m is not None and m._running else None

    def is_manager(self) -> bool:
        return self._running_manager() is not None

    def is_leader(self) -> bool:
        m = self._running_manager()
        return m is not None and m.is_leader()

    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    def _ca_client(self):
        """The leader's CA server, resolved like any agent-side RPC."""
        from swarmkit_tpu.node.connectionbroker import NoManagerError

        try:
            return self.broker.select_ca()
        except NoManagerError:
            return None

    @staticmethod
    def _cert_has_tls_san(cert_pem: bytes) -> bool:
        from cryptography import x509

        from swarmkit_tpu.ca.certificates import (
            TLS_SERVER_NAME, cert_from_pem,
        )

        try:
            san = cert_from_pem(cert_pem).extensions.get_extension_for_class(
                x509.SubjectAlternativeName)
        except x509.ExtensionNotFound:
            return False
        return TLS_SERVER_NAME in san.value.get_values_for_type(x509.DNSName)

    async def _load_security_config(self) -> None:
        """Obtain (or restore) this node's TLS identity
        (reference: loadSecurityConfig node/node.go:305 — may block on the
        CA join; sets node id + role from the certificate)."""
        state_dir = self.config.state_dir
        if state_dir == ":memory:":
            import tempfile

            self._cert_tmp = tempfile.TemporaryDirectory(
                prefix=f"swarmkit-certs-{self.node_id}-")
            cert_dir = self._cert_tmp.name
        else:
            cert_dir = os.path.join(state_dir, "certificates")
        self.keyrw = KeyReadWriter(cert_dir, kek=self.config.unlock_key)

        cert, key = self.keyrw.read()
        root_pem = self.keyrw.read_root_ca()
        if cert and key and root_pem:
            node_id, role_ou, org = parse_identity(cert)
            self.node_id = node_id
            self.security = SecurityConfig(RootCA(root_pem), node_id,
                                           role_ou, org, cert, key)
            self._desired_manager = role_ou == MANAGER_ROLE_OU
            # Migration: certificates issued before TLS SANs existed fail
            # gRPC hostname checks when used as SERVER certs. They still
            # work as CLIENT certs (no hostname check), so the renewal RPC
            # goes through — force it immediately (start() wires the
            # renewer after this returns).
            self._needs_cert_refresh = not self._cert_has_tls_san(cert)
            if self._needs_cert_refresh:
                log.warning(
                    "node %s: stored certificate lacks the TLS SAN; "
                    "forcing renewal so peers can dial this node",
                    self.node_id)
            return

        if self.config.join_token and self.config.join_addr:
            # remote CA join (reference: RequestAndSaveNewCertificates)
            csr_pem, key_pem = create_csr()
            ca = None
            for _ in range(200):
                ca = self._ca_client()
                if ca is not None:
                    break
                await self.clock.sleep(0.05)
            if ca is None:
                raise RuntimeError("cannot reach a CA to join the cluster")
            node_id, issued = await ca.issue_node_certificate(
                csr_pem, self.config.join_token, addr=self.addr,
                requested_node_id=self.node_id)
            root_pem = ca.get_root_ca_certificate()
            # Join-token pin: the fetched bundle MUST contain a cert whose
            # digest matches the SWMTKN pin, and ONLY that cert becomes
            # trust from this unauthenticated fetch (a MITM could append a
            # rogue root to the bundle otherwise).  The full rotation
            # bundle, if any, is installed below from the issuance
            # response, which rode a channel verified against the pin.
            from swarmkit_tpu.ca.config import pinned_cert

            pin = pinned_cert(root_pem, self.config.join_token)
            if pin is None:
                raise RuntimeError(
                    "root CA digest from the remote CA does not match the "
                    "join token pin — refusing to join")
            root_pem = issued.root_bundle or pin
            self.keyrw.write_root_ca(root_pem)
            self.keyrw.write(issued.cert_pem, key_pem)
            self.node_id = node_id
            _, role_ou, org = parse_identity(issued.cert_pem)
            self.security = SecurityConfig(RootCA(root_pem), node_id,
                                           role_ou, org, issued.cert_pem,
                                           key_pem)
            self._desired_manager = role_ou == MANAGER_ROLE_OU
            return

        if self.config.is_manager and self.config.join_addr:
            # a manager joining an existing cluster without a token gets no
            # identity here (legacy/test path) — minting an unrelated root
            # CA would break the org == cluster-id invariant
            log.warning("manager %s joining without a join token; running "
                        "without a certificate identity", self.node_id)
            return

        if self.config.is_manager:
            # bootstrap: self-signed root CA; the manager seeds the cluster
            # from it and the org becomes the cluster id (reference:
            # node.go bootstrap path in loadSecurityConfig)
            from swarmkit_tpu.ca.certificates import HAVE_CRYPTOGRAPHY
            if not HAVE_CRYPTOGRAPHY:
                log.warning("manager %s: cryptography unavailable; running "
                            "without a certificate identity", self.node_id)
                return
            root = RootCA.create()
            org = "cluster-" + new_id()
            issued = root.issue_node_certificate(
                self.node_id, MANAGER_ROLE_OU, org)
            self.keyrw.write_root_ca(root.cert_pem)
            self.keyrw.write(issued.cert_pem, issued.key_pem)
            self.security = SecurityConfig(
                root, self.node_id, MANAGER_ROLE_OU, org,
                issued.cert_pem, issued.key_pem)
        # else: no token, not a manager — legacy identityless worker; the
        # harness (or operator) must have pre-created the node record

    async def start(self) -> None:
        """reference: node.Start node/node.go:251 → run :272."""
        self._running = True
        await self._load_security_config()
        # the restored certificate's role wins over the configured one
        # (reference: role is derived from the cert, node.go:305)
        if self._desired_manager:
            await self._start_manager()
        if self.security is not None:
            self._renewer = TLSRenewer(self.security,
                                       _RenewClient(self),
                                       clock=self.clock)
            self._renewer.start()
            if getattr(self, "_needs_cert_refresh", False):
                self._renewer.renew_soon()
        self.agent = Agent(AgentConfig(
            node_id=self.node_id,
            executor=self.config.executor,
            connect=self.broker.select_dispatcher,
            connect_logs=self.broker.select_logbroker,
            addr=self.addr,
            db_path=os.path.join(self.config.state_dir, "tasks.db")
            if self.config.state_dir != ":memory:" else ":memory:",
            clock=self.clock,
            on_node_change=self._on_node_change,
            on_managers_change=self._on_managers_change))
        await self.agent.start()
        self._supervisor = asyncio.get_running_loop().create_task(
            self._supervise_manager())

    async def stop(self) -> None:
        self._running = False
        self._cancel_role_watches()
        # embedder-attached background tasks (e.g. swarmd's autolock
        # bootstrap) die with the node instead of outliving it
        for t in getattr(self, "_aux_tasks", ()):
            t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        if getattr(self, "_renewer", None) is not None:
            await self._renewer.stop()
            self._renewer = None
        if self._supervisor is not None:
            self._supervisor.cancel()
            try:
                await self._supervisor
            except (asyncio.CancelledError, Exception):
                pass
            self._supervisor = None
        if self.agent is not None:
            await self.agent.stop()
            self.agent = None
        if self.manager is not None:
            await self.manager.stop()
            self.manager = None
        # close any gRPC RemoteManager clients the dialer created
        for rm in getattr(self, "_remote_managers", {}).values():
            try:
                await rm.close()
            except Exception:
                pass
        self._remote_managers = {}

    # ------------------------------------------------------------------
    def _on_node_change(self, node) -> None:
        """Role flips observed via the session stream
        (reference: the cert-renewal waitRole seam node/node.go:933; the
        renewal forcing mirrors renewer.go SetExpectedRole)."""
        self._set_desired_role(manager=node.role == NodeRole.MANAGER)
        # a ROTATE-marked certificate means the cluster root is rotating:
        # renew NOW so the rotation can converge (reference:
        # rootRotationReconciler marking + renewer pickup)
        from swarmkit_tpu.api.types import IssuanceState

        if self._renewer is not None and node.certificate is not None \
                and node.certificate.status_state \
                == int(IssuanceState.ROTATE):
            self._renewer.renew_soon()

    def _on_managers_change(self, managers) -> None:
        for wp in managers:
            self.remotes.observe(wp.peer)

    async def _supervise_manager(self) -> None:
        """reference: superviseManager node/node.go:1080."""
        try:
            while self._running:
                await self._role_evt.wait()
                self._role_evt.clear()
                if self._desired_manager and self.manager is None:
                    log.info("node %s promoted; starting manager",
                             self.node_id)
                    try:
                        await self._start_manager(join=True)
                    except Exception:
                        log.exception("manager start failed; will retry")
                        if self.manager is not None:
                            try:
                                await self.manager.stop()
                            except Exception:
                                pass
                            self.manager = None
                        self._role_evt.set()
                        await self.clock.sleep(1.0)
                elif not self._desired_manager and self.manager is not None:
                    log.info("node %s demoted; stopping manager",
                             self.node_id)
                    self._cancel_role_watches()
                    m, self.manager = self.manager, None
                    await m.stop()
        except asyncio.CancelledError:
            pass

    async def _start_manager(self, join: bool = False) -> None:
        join_addr = self.config.join_addr
        if join:
            # join via the current leader if we know one
            join_addr = self._leader_addr() or join_addr
        state_dir = self.config.state_dir
        if state_dir == ":memory:":
            # raft storage is always file-backed; give ephemeral nodes a
            # throwaway dir instead of a literal ":memory:" path in cwd
            import tempfile

            self._ephemeral_dir = tempfile.TemporaryDirectory(
                prefix=f"swarmkit-{self.node_id}-")
            state_dir = self._ephemeral_dir.name
        # raft storage appends its own "raft" subdir (raft/storage.py)
        encrypter, decrypter = self._raft_dek_crypters()
        self.manager = Manager(
            node_id=self.node_id, addr=self.addr,
            network=self.config.network, state_dir=state_dir,
            clock=self.clock, join_addr=join_addr,
            force_new_cluster=self.config.force_new_cluster,
            tick_interval=self.config.tick_interval,
            election_tick=self.config.election_tick,
            heartbeat_tick=self.config.heartbeat_tick,
            seed=self.config.seed, security=self.security,
            encrypter=encrypter, decrypter=decrypter,
            transport_factory=self.config.transport_factory)
        await self.manager.start()
        # Demotion safety net: the dispatcher session is the primary
        # role-change channel, but during a demotion the session churns
        # with leadership at the exact moment the role flips, and by then
        # this node's raft member is already removed — so its local store
        # never sees the flip either. Member removal itself is therefore
        # the authoritative demotion signal (reference: superviseManager
        # treats ErrMemberRemoved as demotion, node/node.go:1080).
        self._removal_watch = asyncio.get_running_loop().create_task(
            self._watch_member_removal(self.manager))
        self._autolock_watch = asyncio.get_running_loop().create_task(
            self._watch_autolock(self.manager))

    async def _watch_autolock(self, manager) -> None:
        """Apply the cluster's manager autolock KEK to this node's key
        store as it changes in the replicated state (reference:
        manager.go handleKEKChange / keyreadwriter RotateKEK).  With
        autolock on, a restarted manager cannot load its TLS key without
        --unlock-key."""
        from swarmkit_tpu.store.memory import match
        from swarmkit_tpu.watch.queue import watch_with_sweep

        def current_kek():
            clusters = manager.store.find("cluster")
            if not clusters:
                return None
            return next((k.key for k in clusters[0].unlock_keys
                         if k.subsystem == "manager"), None)

        try:
            watcher = manager.store.watch(match(kind="cluster"))
            async for _ev in watch_with_sweep(watcher, self.clock, 2.0):
                if manager is not self.manager or not manager._running:
                    return
                kek = current_kek()
                if self.keyrw is not None and self.security is not None \
                        and self.keyrw.set_kek(kek):
                    log.info("node %s: manager autolock %s", self.node_id,
                             "engaged" if kek else "released")
                    self._rotate_raft_dek()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("autolock watch crashed")

    def _raft_dek_crypters(self):
        """The raft WAL/snapshot data-encryption key, minted on first
        manager start and persisted in the KEK-protected key-store headers
        (reference: manager/deks.go — the DEK rides the TLS key headers so
        autolock covers it).  Returns (encrypter, decrypter); plaintext
        only for keyrw-less harness nodes."""
        from swarmkit_tpu.encryption.encryption import (
            MultiDecrypter, SecretboxCrypter,
        )

        if self.keyrw is None or self.security is None:
            return None, None
        dek, history = self.keyrw.get_raft_deks()
        if dek is None:
            dek = os.urandom(32)
            self.keyrw.set_raft_deks(dek, history)
        crypter = SecretboxCrypter(dek)
        history = [h for h in history if h != dek]
        if history:
            return crypter, MultiDecrypter(
                crypter, *(SecretboxCrypter(h) for h in history))
        return crypter, crypter

    def _rotate_raft_dek(self) -> None:
        """KEK change => DEK rotation (reference: deks.go NeedsRotation —
        a key that protected the old DEK may be known to holders of the
        old KEK)."""
        from swarmkit_tpu.encryption.encryption import SecretboxCrypter

        if self.keyrw is None or self.manager is None:
            return
        old, history = self.keyrw.get_raft_deks()
        if old is None:
            return
        new = os.urandom(32)
        # History is NEVER auto-drained: a same-index snapshot can keep a
        # live WAL segment with old-generation records, so dropping a
        # generation on "snapshot success" risks an unbootable state dir.
        # Generations are 32 bytes per KEK rotation — keeping them all is
        # the safe trade (the snapshot below still re-encrypts history so
        # old keys stop MATTERING; they just remain available).
        self.keyrw.set_raft_deks(new, history + [old])
        self.manager.raft.storage.rotate_encryption_key(
            SecretboxCrypter(new), SecretboxCrypter(new))
        try:
            # re-encrypt the log under the new key ASAP (reference:
            # deks.go triggers a snapshot to complete rotation)
            self.manager.raft.snapshot_now()
        except Exception:
            log.exception("post-rotation snapshot failed; the old DEK "
                          "generation still decrypts existing segments")
        log.info("node %s: raft DEK rotated with the KEK", self.node_id)

    async def _watch_member_removal(self, manager) -> None:
        try:
            while manager is self.manager and manager._running:
                if manager.raft.removed:
                    log.info("node %s: raft member removed; demoting",
                             self.node_id)
                    self._note_demoted()
                    return
                await self.clock.sleep(0.5)
        except asyncio.CancelledError:
            raise

    def _note_demoted(self) -> None:
        self._set_desired_role(manager=False)

    def _set_desired_role(self, manager: bool) -> None:
        """One place for the role-flip invariant (both the session path
        and the member-removal path): update the desired role, force
        certificate renewal when the cert's role no longer matches, and
        wake the supervisor."""
        if manager == self._desired_manager:
            return
        self._desired_manager = manager
        if not manager and self.keyrw is not None:
            # a worker runs no autolock watch and must never be locked
            # out of its own key: release the manager KEK at-rest
            # encryption on EVERY demotion path (reference: keyreadwriter
            # RotateKEK(nil) on demotion)
            try:
                self.keyrw.set_kek(None)
            except Exception:
                log.exception("cannot release the autolock KEK on demotion")
        if self.security is not None and self._renewer is not None:
            have_mgr_cert = self.security.role_ou == MANAGER_ROLE_OU
            if manager != have_mgr_cert:
                self._renewer.renew_soon()
        self._role_evt.set()

    def _cancel_role_watches(self) -> None:
        for attr in ("_removal_watch", "_autolock_watch"):
            t = getattr(self, attr, None)
            if t is not None:
                t.cancel()
                setattr(self, attr, None)

    def _leader_addr(self) -> str:
        for addr in self.remotes.weights():
            m = self.config.dialer(addr)
            if m is not None:
                try:
                    if m.is_leader():
                        return m.addr
                    if m.leader_addr:
                        return m.leader_addr
                except Exception:
                    continue
        return ""

"""Weighted-random manager address book.

Reference: remotes/remotes.go (:21-136) — tracks known manager addresses
with observation weights: successful contact raises the weight
(DefaultObservationWeight 10), failure penalizes it; selection is weighted
random so agents spread across managers but avoid flaky ones.
"""

from __future__ import annotations

import random
from typing import Optional

from swarmkit_tpu.api import Peer

DEFAULT_OBSERVATION_WEIGHT = 10   # reference: remotes.go:21
MAX_OBSERVATION_WEIGHT = 100


class Remotes:
    def __init__(self, *peers: Peer, rng: Optional[random.Random] = None
                 ) -> None:
        self._weights: dict[str, int] = {}   # addr -> weight
        self._peers: dict[str, Peer] = {}
        self._rng = rng or random.Random()
        for p in peers:
            self.observe(p, DEFAULT_OBSERVATION_WEIGHT)

    def observe(self, peer: Peer, weight: int = DEFAULT_OBSERVATION_WEIGHT
                ) -> None:
        """Record an observation; positive reinforces, negative penalizes
        (reference: Observe/ObserveIfExists remotes.go:60)."""
        if not peer.addr:
            return
        cur = self._weights.get(peer.addr, 0)
        nxt = max(-MAX_OBSERVATION_WEIGHT,
                  min(MAX_OBSERVATION_WEIGHT, cur + weight))
        self._weights[peer.addr] = nxt
        self._peers[peer.addr] = peer

    def remove(self, *addrs: str) -> None:
        for a in addrs:
            self._weights.pop(a, None)
            self._peers.pop(a, None)

    def select(self, *excludes: str) -> Peer:
        """Weighted random pick (reference: Select remotes.go:94)."""
        pool = [(a, w) for a, w in self._weights.items()
                if a not in excludes]
        if not pool:
            raise LookupError("no manager addresses known")
        # shift so the lowest weight still has a small chance
        low = min(w for _, w in pool)
        shifted = [(a, (w - low) + 1) for a, w in pool]
        total = sum(w for _, w in shifted)
        pick = self._rng.uniform(0, total)
        acc = 0.0
        for a, w in shifted:
            acc += w
            if pick <= acc:
                return self._peers[a]
        return self._peers[shifted[-1][0]]

    def weights(self) -> dict[str, int]:
        return dict(self._weights)

    def __len__(self) -> int:
        return len(self._weights)

from swarmkit_tpu.node.node import Node, NodeConfig
from swarmkit_tpu.node.remotes import Remotes

__all__ = ["Node", "NodeConfig", "Remotes"]

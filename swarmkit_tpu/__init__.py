"""swarmkit_tpu — a TPU-native cluster-orchestration framework.

A ground-up rebuild of the capabilities of SwarmKit (reference: wk8/swarmkit,
pure Go) designed TPU-first:

- The Raft consensus core is a *batched, pure-functional* state machine:
  N simulated managers are rows of device arrays and one jit-compiled tick
  kernel advances all of them at once (``swarmkit_tpu.raft.kernel``).  Vote
  counting and append acknowledgements are reductions over sharded axes, so
  under a ``jax.sharding.Mesh`` they lower to XLA collectives (psum) over
  ICI — replacing the reference's goroutine-per-peer gRPC fan-out
  (reference: manager/state/raft/transport/).
- The replicated state machine (MemoryStore), orchestrators, scheduler,
  dispatcher and agent are an asyncio control plane with deterministic
  fake-clock testing, mirroring the reference's component inventory
  (reference: manager/, agent/).

Layout:
    api/        data model: objects, specs, task states, store actions
    watch/      event bus (reference: watch/watch.go)
    store/      transactional in-memory object store (manager/state/store)
    raft/       golden model, JAX tick kernel (sim/), Node shell, storage,
                in-process + gRPC transports, binary wire codec
    transport/  device-mesh mailbox transport behind the Transport seam
    parallel/   mesh + sharding helpers for the batched raft state
    manager/    control plane services and leader loops
    agent/      worker/executor side (incl. the TPU task runtime)
    node/       node lifecycle: joins, role flips, manager supervision
    ca/         certificate authority + TLS identities
    encryption/ at-rest encryption primitives (WAL/snap DEKs)
    native/     C++ hot-path components (WAL codec), ctypes-loaded
    cmd/        swarmd / swarmctl / rafttool / swarm-bench / external-ca
    utils/      ids, clocks, metrics, logging
"""

__version__ = "0.1.0"

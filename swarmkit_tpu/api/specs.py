"""User-intent specs. Reference: api/specs.proto."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

from swarmkit_tpu.api.serde import Message
from swarmkit_tpu.api.types import (
    Annotations, Driver, EndpointSpecRef, IPAMOptions, NodeAvailability,
    NodeRole, PortConfig,
)


class Mode(enum.IntEnum):
    REPLICATED = 0
    GLOBAL = 1


@dataclass
class NodeSpec(Message):
    annotations: Annotations = field(default_factory=Annotations)
    desired_role: NodeRole = NodeRole.WORKER
    membership: int = 1  # MembershipState.ACCEPTED
    availability: NodeAvailability = NodeAvailability.ACTIVE


@dataclass
class Resources(Message):
    nano_cpus: int = 0
    memory_bytes: int = 0
    generic: dict[str, int] = field(default_factory=dict)


@dataclass
class ResourceRequirements(Message):
    limits: Optional[Resources] = None
    reservations: Optional[Resources] = None


class RestartCondition(enum.IntEnum):
    NONE = 0
    ON_FAILURE = 1
    ANY = 2


@dataclass
class RestartPolicy(Message):
    condition: RestartCondition = RestartCondition.ANY
    delay: float = 5.0
    max_attempts: int = 0  # 0 = unlimited
    window: float = 0.0    # seconds; 0 = unbounded attempt window


@dataclass
class Placement(Message):
    constraints: list[str] = field(default_factory=list)
    preferences: list[str] = field(default_factory=list)  # "spread=node.labels.X"
    max_replicas: int = 0  # max replicas per node; 0 = unlimited
    platforms: list[str] = field(default_factory=list)  # "os/arch"


@dataclass
class Mount(Message):
    """Filesystem mount carried on the container spec (reference:
    api/types.proto Mount — bind/volume/tmpfs/npipe). The TPU executor has
    no container filesystem, so mounts ride the data model for executor
    implementations that do (and for API parity); source/target are
    template-expanded per task like the reference's expandMounts."""
    type: str = "bind"            # bind | volume | tmpfs | npipe
    source: str = ""
    target: str = ""
    read_only: bool = False
    volume_labels: dict[str, str] = field(default_factory=dict)


@dataclass
class ContainerSpec(Message):
    image: str = ""
    command: list[str] = field(default_factory=list)
    args: list[str] = field(default_factory=list)
    env: list[str] = field(default_factory=list)
    dir: str = ""
    user: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    secrets: list["SecretReference"] = field(default_factory=list)
    configs: list["ConfigReference"] = field(default_factory=list)
    hostname: str = ""
    stop_grace_period: float = 10.0
    pull_options: dict[str, str] = field(default_factory=dict)
    hosts: list[str] = field(default_factory=list)
    healthcheck: Optional[dict] = None
    mounts: list[Mount] = field(default_factory=list)


@dataclass
class SecretReference(Message):
    secret_id: str = ""
    secret_name: str = ""
    target_name: str = ""
    mode: int = 0o444
    uid: str = "0"
    gid: str = "0"


@dataclass
class ConfigReference(Message):
    config_id: str = ""
    config_name: str = ""
    target_name: str = ""
    mode: int = 0o444
    uid: str = "0"
    gid: str = "0"


@dataclass
class TaskSpec(Message):
    # runtime oneof — exactly one of container/attachment set.
    container: Optional[ContainerSpec] = None
    attachment: Optional[dict] = None  # network-attachment tasks
    resources: Optional[ResourceRequirements] = None
    restart: Optional[RestartPolicy] = None
    placement: Optional[Placement] = None
    networks: list[str] = field(default_factory=list)  # network ids
    log_driver: Optional[Driver] = None
    force_update: int = 0


class UpdateFailureAction(enum.IntEnum):
    PAUSE = 0
    CONTINUE = 1
    ROLLBACK = 2


class UpdateOrder(enum.IntEnum):
    STOP_FIRST = 0
    START_FIRST = 1


@dataclass
class UpdateConfig(Message):
    parallelism: int = 0  # 0 = all at once
    delay: float = 0.0
    failure_action: UpdateFailureAction = UpdateFailureAction.PAUSE
    monitor: float = 5.0
    max_failure_ratio: float = 0.0
    order: UpdateOrder = UpdateOrder.STOP_FIRST


@dataclass
class ReplicatedService(Message):
    replicas: int = 1


@dataclass
class GlobalService(Message):
    pass


@dataclass
class ServiceSpec(Message):
    annotations: Annotations = field(default_factory=Annotations)
    task: TaskSpec = field(default_factory=TaskSpec)
    mode: Mode = Mode.REPLICATED
    replicated: Optional[ReplicatedService] = None
    global_: Optional[GlobalService] = None
    update: Optional[UpdateConfig] = None
    rollback: Optional[UpdateConfig] = None
    networks: list[str] = field(default_factory=list)
    endpoint: Optional[EndpointSpecRef] = None

    def replica_count(self) -> int:
        if self.mode == Mode.GLOBAL:
            return 0
        return self.replicated.replicas if self.replicated else 1


EndpointSpec = EndpointSpecRef


@dataclass
class NetworkSpec(Message):
    annotations: Annotations = field(default_factory=Annotations)
    driver_config: Optional[Driver] = None
    ipv6_enabled: bool = False
    internal: bool = False
    ipam: Optional[IPAMOptions] = None
    attachable: bool = False
    ingress: bool = False


@dataclass
class SecretSpec(Message):
    annotations: Annotations = field(default_factory=Annotations)
    data: bytes = b""
    driver: Optional[Driver] = None
    # reference api/specs.proto SecretSpec.Templating: when set (driver
    # name "golang"), the payload is template-expanded PER TASK when
    # served to a workload (template/expand.go:132 ExpandSecretSpec)
    templating: Optional[Driver] = None


@dataclass
class ConfigSpec(Message):
    annotations: Annotations = field(default_factory=Annotations)
    data: bytes = b""
    templating: Optional[Driver] = None


# ---- cluster-level config (api/specs.proto ClusterSpec) -------------------

@dataclass
class RaftConfig(Message):
    snapshot_interval: int = 10000       # entries between snapshots (raft.go:499)
    keep_old_snapshots: int = 0
    log_entries_for_slow_followers: int = 500
    heartbeat_tick: int = 1
    election_tick: int = 10


@dataclass
class ExternalCA(Message):
    protocol: str = "cfssl"
    url: str = ""
    options: dict[str, str] = field(default_factory=dict)
    ca_cert: bytes = b""


@dataclass
class CAConfig(Message):
    node_cert_expiry: float = 90 * 24 * 3600.0
    external_cas: list[ExternalCA] = field(default_factory=list)
    signing_ca_cert: bytes = b""
    signing_ca_key: bytes = b""
    force_rotate: int = 0


@dataclass
class DispatcherConfig(Message):
    heartbeat_period: float = 5.0  # dispatcher.go:31


@dataclass
class TaskDefaults(Message):
    log_driver: Optional[Driver] = None


@dataclass
class EncryptionConfig(Message):
    auto_lock_managers: bool = False


@dataclass
class OrchestrationConfig(Message):
    task_history_retention_limit: int = 5


@dataclass
class ClusterSpec(Message):
    annotations: Annotations = field(default_factory=Annotations)
    acceptance_policy: dict = field(default_factory=dict)
    orchestration: OrchestrationConfig = field(default_factory=OrchestrationConfig)
    raft: RaftConfig = field(default_factory=RaftConfig)
    dispatcher: DispatcherConfig = field(default_factory=DispatcherConfig)
    ca_config: CAConfig = field(default_factory=CAConfig)
    task_defaults: TaskDefaults = field(default_factory=TaskDefaults)
    encryption_config: EncryptionConfig = field(default_factory=EncryptionConfig)

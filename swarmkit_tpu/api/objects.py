"""Cluster state objects. Reference: api/objects.proto.

Every object: ``id`` + ``meta`` (version = raft index of last write) + a
user-intent ``spec`` + runtime state.  ``OBJECT_KINDS`` is the registry the
store's tables are generated from (replacing the reference's storeobject
protobuf plugin, protobuf/plugin/storeobject/).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from swarmkit_tpu.api.serde import Message
from swarmkit_tpu.api.specs import (
    ClusterSpec, ConfigSpec, NetworkSpec, NodeSpec, SecretSpec, ServiceSpec,
    TaskSpec,
)
from swarmkit_tpu.api.types import (
    Annotations, Certificate, Endpoint, Meta, NetworkAttachment,
    NodeDescription, NodeRole, NodeState, TaskStatus, Driver, IPAMOptions,
)


@dataclass
class NodeStatus(Message):
    state: NodeState = NodeState.UNKNOWN
    message: str = ""
    addr: str = ""


@dataclass
class Node(Message):
    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    description: Optional[NodeDescription] = None
    status: NodeStatus = field(default_factory=NodeStatus)
    manager_status: Optional[dict] = None  # {raft_id, addr, leader, reachability}
    attachment: Optional[NetworkAttachment] = None
    certificate: Certificate = field(default_factory=Certificate)
    role: NodeRole = NodeRole.WORKER  # observed role (cert-derived)

    @property
    def annotations(self) -> Annotations:
        return self.spec.annotations


@dataclass
class UpdateStatus(Message):
    state: str = ""  # updating|paused|completed|rollback_started|rollback_paused|rollback_completed
    started_at: float = 0.0
    completed_at: float = 0.0
    message: str = ""


@dataclass
class Service(Message):
    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    previous_spec: Optional[ServiceSpec] = None
    endpoint: Optional[Endpoint] = None
    update_status: Optional[UpdateStatus] = None
    pending_delete: bool = False

    @property
    def annotations(self) -> Annotations:
        return self.spec.annotations


@dataclass
class Task(Message):
    id: str = ""
    meta: Meta = field(default_factory=Meta)
    annotations: Annotations = field(default_factory=Annotations)
    spec: TaskSpec = field(default_factory=TaskSpec)
    service_id: str = ""
    slot: int = 0
    node_id: str = ""
    status: TaskStatus = field(default_factory=TaskStatus)
    desired_state: int = 0  # TaskState value
    networks: list[NetworkAttachment] = field(default_factory=list)
    endpoint: Optional[Endpoint] = None
    log_driver: Optional[Driver] = None
    service_annotations: Annotations = field(default_factory=Annotations)
    # specific named-resource ids claimed by the scheduler for this task
    # (reference: Task.AssignedGenericResources, api/genericresource)
    assigned_generic: dict[str, list[str]] = field(default_factory=dict)


@dataclass
class Network(Message):
    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: NetworkSpec = field(default_factory=NetworkSpec)
    driver_state: Optional[Driver] = None
    ipam: Optional[IPAMOptions] = None

    @property
    def annotations(self) -> Annotations:
        return self.spec.annotations


@dataclass
class RootRotation(Message):
    """In-flight root-CA rotation (reference: api/ca.proto RootRotation):
    the new root + its cert cross-signed by the old root."""
    ca_cert: bytes = b""
    ca_key: bytes = b""
    cross_signed_ca_cert: bytes = b""


@dataclass
class RootCA(Message):
    ca_key: bytes = b""
    ca_cert: bytes = b""
    ca_cert_hash: str = ""
    join_token_worker: str = ""
    join_token_manager: str = ""
    root_rotation: Optional[RootRotation] = None


@dataclass
class EncryptionKey(Message):
    subsystem: str = ""
    algorithm: int = 0
    key: bytes = b""
    lamport_time: int = 0


@dataclass
class Cluster(Message):
    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: ClusterSpec = field(default_factory=ClusterSpec)
    root_ca: RootCA = field(default_factory=RootCA)
    network_bootstrap_keys: list[EncryptionKey] = field(default_factory=list)
    encryption_key_lamport_clock: int = 0
    unlock_keys: list[EncryptionKey] = field(default_factory=list)
    fips: bool = False

    @property
    def annotations(self) -> Annotations:
        return self.spec.annotations


@dataclass
class Secret(Message):
    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: SecretSpec = field(default_factory=SecretSpec)
    internal: bool = False

    @property
    def annotations(self) -> Annotations:
        return self.spec.annotations


@dataclass
class Config(Message):
    id: str = ""
    meta: Meta = field(default_factory=Meta)
    spec: ConfigSpec = field(default_factory=ConfigSpec)

    @property
    def annotations(self) -> Annotations:
        return self.spec.annotations


@dataclass
class Resource(Message):
    id: str = ""
    meta: Meta = field(default_factory=Meta)
    annotations: Annotations = field(default_factory=Annotations)
    kind: str = ""
    payload: bytes = b""


@dataclass
class Extension(Message):
    id: str = ""
    meta: Meta = field(default_factory=Meta)
    annotations: Annotations = field(default_factory=Annotations)
    description: str = ""


# Registry: kind name -> class (drives store table creation and StoreAction
# routing; replaces generated StoreObject plumbing).
OBJECT_KINDS: dict[str, type] = {
    "node": Node,
    "service": Service,
    "task": Task,
    "network": Network,
    "cluster": Cluster,
    "secret": Secret,
    "config": Config,
    "resource": Resource,
    "extension": Extension,
}

_CLASS_TO_KIND = {v: k for k, v in OBJECT_KINDS.items()}


def kind_of(obj) -> str:
    return _CLASS_TO_KIND[type(obj)]

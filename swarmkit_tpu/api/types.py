"""Core enums and shared message types.

Reference: api/types.proto (TaskState at :~500 — lamport-ordered enum with
gaps of 64 so states can be inserted), api/objects.proto Meta/Version.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

from swarmkit_tpu.api.serde import Message


class TaskState(enum.IntEnum):
    """Observed/desired task states; ordering is meaningful (monotonic FSM).

    Values keep the reference's gaps of 64 (api/types.proto TaskState).
    """

    NEW = 0
    PENDING = 64
    ASSIGNED = 128
    ACCEPTED = 192
    PREPARING = 256
    READY = 320
    STARTING = 384
    RUNNING = 448
    COMPLETE = 512
    SHUTDOWN = 576
    FAILED = 640
    REJECTED = 704
    REMOVE = 768
    ORPHANED = 832


# States at or beyond which a task no longer consumes resources.
TERMINAL_STATES = (TaskState.COMPLETE, TaskState.SHUTDOWN, TaskState.FAILED,
                   TaskState.REJECTED, TaskState.REMOVE, TaskState.ORPHANED)


class NodeRole(enum.IntEnum):
    WORKER = 0
    MANAGER = 1


class NodeState(enum.IntEnum):
    UNKNOWN = 0
    DOWN = 1
    READY = 2
    DISCONNECTED = 3


class NodeAvailability(enum.IntEnum):
    ACTIVE = 0
    PAUSE = 1
    DRAIN = 2


class MembershipState(enum.IntEnum):
    PENDING = 0
    ACCEPTED = 1


@dataclass
class Version(Message):
    """Raft index of the last modification; optimistic-concurrency token
    (reference: api/objects.proto Meta.version)."""

    index: int = 0


@dataclass
class Meta(Message):
    version: Version = field(default_factory=Version)
    created_at: float = 0.0
    updated_at: float = 0.0


@dataclass
class Annotations(Message):
    name: str = ""
    labels: dict[str, str] = field(default_factory=dict)


@dataclass
class TaskStatus(Message):
    timestamp: float = 0.0
    state: TaskState = TaskState.NEW
    message: str = ""
    err: str = ""
    container_exit_code: Optional[int] = None


@dataclass
class Peer(Message):
    node_id: str = ""
    addr: str = ""


@dataclass
class WeightedPeer(Message):
    peer: Peer = field(default_factory=Peer)
    weight: int = 1


@dataclass
class RaftMemberStatus(Message):
    leader: bool = False
    reachability: int = 0  # 0 unknown, 1 unreachable, 2 reachable
    message: str = ""


@dataclass
class RaftMember(Message):
    raft_id: int = 0
    node_id: str = ""
    addr: str = ""
    status: RaftMemberStatus = field(default_factory=RaftMemberStatus)


@dataclass
class Platform(Message):
    architecture: str = ""
    os: str = ""


@dataclass
class EngineDescription(Message):
    engine_version: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    plugins: list[str] = field(default_factory=list)


@dataclass
class NodeDescription(Message):
    hostname: str = ""
    platform: Platform = field(default_factory=Platform)
    resources: Optional["NodeResources"] = None
    engine: EngineDescription = field(default_factory=EngineDescription)
    tls_info: Optional["NodeTLSInfo"] = None
    fips: bool = False


@dataclass
class NodeResources(Message):
    nano_cpus: int = 0
    memory_bytes: int = 0
    generic: dict[str, int] = field(default_factory=dict)
    # Named generic resources (reference: api/genericresource
    # NamedGenericResource): a SET of claimable string ids per kind (e.g.
    # tpu-chip -> ["0","1",...]); discrete `generic` counts and named sets
    # may coexist under different kinds
    generic_named: dict[str, list[str]] = field(default_factory=dict)


@dataclass
class NodeTLSInfo(Message):
    trust_root: bytes = b""
    cert_issuer_subject: bytes = b""
    cert_issuer_public_key: bytes = b""


@dataclass
class Certificate(Message):
    role: NodeRole = NodeRole.WORKER
    csr: bytes = b""
    status_state: int = 0  # IssuanceState: 0 unknown,1 renew,2 pending,3 issued,4 failed,5 rotate
    certificate: bytes = b""
    cn: str = ""


class IssuanceState(enum.IntEnum):
    UNKNOWN = 0
    RENEW = 1
    PENDING = 2
    ISSUED = 3
    FAILED = 4
    ROTATE = 5


@dataclass
class Endpoint(Message):
    spec: Optional["EndpointSpecRef"] = None
    ports: list["PortConfig"] = field(default_factory=list)
    virtual_ips: list["EndpointVIP"] = field(default_factory=list)


@dataclass
class EndpointVIP(Message):
    network_id: str = ""
    addr: str = ""


@dataclass
class PortConfig(Message):
    name: str = ""
    protocol: str = "tcp"
    target_port: int = 0
    published_port: int = 0
    publish_mode: str = "ingress"  # ingress | host


@dataclass
class EndpointSpecRef(Message):
    mode: str = "vip"
    ports: list[PortConfig] = field(default_factory=list)


@dataclass
class NetworkAttachment(Message):
    network_id: str = ""
    addresses: list[str] = field(default_factory=list)
    aliases: list[str] = field(default_factory=list)
    # resolved network driver name (reference: NetworkAttachment.Network
    # .DriverState carried into the task so the scheduler's PluginFilter
    # needs no store lookup); "" = default driver
    driver: str = ""


@dataclass
class IPAMConfig(Message):
    family: str = "ipv4"
    subnet: str = ""
    ip_range: str = ""
    gateway: str = ""
    reserved: dict[str, str] = field(default_factory=dict)


@dataclass
class IPAMOptions(Message):
    driver: str = "default"
    configs: list[IPAMConfig] = field(default_factory=list)


@dataclass
class Driver(Message):
    name: str = ""
    options: dict[str, str] = field(default_factory=dict)

"""Raft wire/log types. Reference: api/raft.proto, api/snapshot.proto."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

from swarmkit_tpu.api.objects import OBJECT_KINDS, kind_of
from swarmkit_tpu.api.serde import Message
from swarmkit_tpu.api.types import RaftMember


class StoreActionKind(enum.IntEnum):
    UNKNOWN = 0
    CREATE = 1
    UPDATE = 2
    REMOVE = 3


@dataclass
class StoreAction(Message):
    """One object mutation inside a raft log entry
    (api/raft.proto StoreAction :127-139)."""

    action: StoreActionKind = StoreActionKind.UNKNOWN
    kind: str = ""          # object kind name from OBJECT_KINDS
    target: dict = field(default_factory=dict)  # serialized object

    @classmethod
    def make(cls, action: StoreActionKind, obj) -> "StoreAction":
        return cls(action=action, kind=kind_of(obj), target=obj.to_dict())

    def object(self):
        return OBJECT_KINDS[self.kind].from_dict(self.target)


@dataclass
class InternalRaftRequest(Message):
    """The unit proposed to raft (api/raft.proto InternalRaftRequest :116)."""

    id: int = 0
    actions: list[StoreAction] = field(default_factory=list)


@dataclass
class StoreSnapshot(Message):
    """Full dump of every object table (api/snapshot.proto StoreSnapshot)."""

    objects: dict[str, list] = field(default_factory=dict)  # kind -> [obj dicts]


@dataclass
class ClusterMember(Message):
    raft_id: int = 0
    node_id: str = ""
    addr: str = ""


@dataclass
class ClusterSnapshot(Message):
    members: list[ClusterMember] = field(default_factory=list)
    removed: list[int] = field(default_factory=list)


@dataclass
class Snapshot(Message):
    version: int = 0
    membership: ClusterSnapshot = field(default_factory=ClusterSnapshot)
    store: StoreSnapshot = field(default_factory=StoreSnapshot)

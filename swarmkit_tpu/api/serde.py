"""Tiny message framework: dataclasses with generic (de)serialization.

Replaces the reference's gogoproto codegen (api/*.pb.go, ~70k generated LoC)
with introspection: every API type is a dataclass deriving ``Message`` and
gets ``to_dict``/``from_dict``/``copy``/``encode``/``decode`` for free.
Wire format is canonical JSON (stable key order) — adequate for WAL entries,
snapshots and the in-process transports; a binary codec for device-packed
raft entries lives in swarmkit_tpu.raft (fixed-width, array-friendly).
"""

from __future__ import annotations

import base64
import dataclasses
import enum
import hashlib
import json
import sys
import typing
from typing import Any, Optional, Union, get_args, get_origin

_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def _resolve_refs(tp: Any, globalns: dict) -> Any:
    """Resolve forward references `get_type_hints` leaves behind.

    Quoted args inside builtin generics — ``list["PortConfig"]`` — survive
    hint resolution as bare strings (the subscript value is never
    evaluated), so decoding would silently hand back raw dicts instead of
    rehydrated dataclasses.  Walk the hint tree and look such strings up in
    the defining module's namespace.
    """
    if isinstance(tp, str):
        return globalns.get(tp, tp)
    if type(tp) is typing.ForwardRef:
        return globalns.get(tp.__forward_arg__, tp)
    origin = get_origin(tp)
    if origin is None:
        return tp
    args = get_args(tp)
    new = tuple(_resolve_refs(a, globalns) for a in args)
    if new == args:
        return tp
    if origin is Union:
        return Union[new]
    return origin[new]


def _hints(cls: type) -> dict[str, Any]:
    h = _HINTS_CACHE.get(cls)
    if h is None:
        g = vars(sys.modules.get(cls.__module__, typing)) \
            if cls.__module__ in sys.modules else {}
        h = {k: _resolve_refs(v, g)
             for k, v in typing.get_type_hints(cls).items()}
        _HINTS_CACHE[cls] = h
    return h


def _enc(value: Any) -> Any:
    if value is None or isinstance(value, (int, float, str, bool)):
        return value
    if isinstance(value, enum.Enum):
        return value.value
    if isinstance(value, bytes):
        return {"__b64__": base64.b64encode(value).decode("ascii")}
    if dataclasses.is_dataclass(value):
        out = {}
        for f in dataclasses.fields(value):
            v = getattr(value, f.name)
            if v is None:
                continue
            out[f.name] = _enc(v)
        return out
    if isinstance(value, (list, tuple)):
        return [_enc(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _enc(v) for k, v in value.items()}
    raise TypeError(f"cannot serialize {type(value)!r}")


def _dec(tp: Any, data: Any) -> Any:
    if data is None:
        return None
    origin = get_origin(tp)
    if origin is Union:  # Optional[X]
        args = [a for a in get_args(tp) if a is not type(None)]
        return _dec(args[0], data)
    if origin in (list, tuple):
        (item_tp,) = get_args(tp) or (Any,)
        return [_dec(item_tp, v) for v in data]
    if origin is dict:
        args = get_args(tp)
        item_tp = args[1] if len(args) == 2 else Any
        return {k: _dec(item_tp, v) for k, v in data.items()}
    if isinstance(tp, type):
        if tp is bytes:
            if isinstance(data, dict) and "__b64__" in data:
                return base64.b64decode(data["__b64__"])
            return bytes(data)
        if issubclass(tp, enum.Enum):
            return tp(data)
        if dataclasses.is_dataclass(tp):
            return _from_dict(tp, data)
        if tp in (int, float, str, bool):
            return tp(data)
    return data


def _from_dict(cls: type, data: dict) -> Any:
    hints = _hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _dec(hints[f.name], data[f.name])
    return cls(**kwargs)


class Message:
    """Mixin for API dataclasses: serialization, deep copy, canonical bytes."""

    def to_dict(self) -> dict:
        return _enc(self)

    @classmethod
    def from_dict(cls, data: dict):
        return _from_dict(cls, data)

    def copy(self):
        return _from_dict(type(self), _enc(self))

    def encode(self) -> bytes:
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":")).encode()

    def fingerprint(self) -> int:
        """Stable fingerprint of the canonical encoding — plays the
        reference's SpecVersion role wherever spec-change detection is
        needed (restart history, scheduler failure taints).  blake2b, not
        hash(): str/bytes hashing is salted per process
        (PYTHONHASHSEED), and these fingerprints outlive a process via
        WAL/snapshot restore and cross-manager comparison."""
        return int.from_bytes(
            hashlib.blake2b(self.encode(), digest_size=8).digest(), "big")

    @classmethod
    def decode(cls, raw: bytes):
        return cls.from_dict(json.loads(raw.decode()))

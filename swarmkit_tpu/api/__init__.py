from swarmkit_tpu.api.types import (
    TaskState, NodeRole, NodeState, NodeAvailability, Meta, Version,
    Annotations, TaskStatus, NodeDescription, NodeResources, Platform,
    EngineDescription, Endpoint, EndpointVIP, PortConfig, NetworkAttachment,
    Driver, Peer, WeightedPeer, IPAMConfig, IPAMOptions, MembershipState,
)
from swarmkit_tpu.api.specs import (
    NodeSpec, ServiceSpec, TaskSpec, ClusterSpec, NetworkSpec, SecretSpec,
    ConfigSpec, RaftConfig, CAConfig, DispatcherConfig, TaskDefaults,
    EndpointSpec, Mode, RestartPolicy, UpdateConfig, Placement,
    ContainerSpec, Resources, ResourceRequirements, ReplicatedService,
    GlobalService, RestartCondition, UpdateFailureAction, UpdateOrder,
    OrchestrationConfig, EncryptionConfig,
)
from swarmkit_tpu.api.objects import (
    Node, Service, Task, Network, Cluster, Secret, Config, Resource,
    Extension, OBJECT_KINDS, kind_of,
)
from swarmkit_tpu.api.raft_msgs import (
    StoreAction, StoreActionKind, InternalRaftRequest, Snapshot,
    StoreSnapshot, ClusterMember, ClusterSnapshot,
)

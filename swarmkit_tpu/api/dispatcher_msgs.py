"""Dispatcher wire messages. Reference: api/dispatcher.proto.

The reference defines the Dispatcher gRPC service (Session, Heartbeat,
UpdateTaskStatus, Tasks, Assignments) plus its message types.  Here they are
plain dataclasses flowing over in-process async streams; the gRPC bridge
(transport impl #2) serializes them when crossing hosts.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional

from swarmkit_tpu.api.objects import Config, EncryptionKey, Node, Secret, Task
from swarmkit_tpu.api.serde import Message
from swarmkit_tpu.api.types import TaskStatus, WeightedPeer


@dataclass
class SessionMessage(Message):
    """Reference: api/dispatcher.proto SessionMessage."""

    session_id: str = ""
    node: Optional[Node] = None
    managers: list[WeightedPeer] = field(default_factory=list)
    network_bootstrap_keys: list[EncryptionKey] = field(default_factory=list)
    root_ca: bytes = b""


@dataclass
class HeartbeatResponse(Message):
    period: float = 0.0  # seconds until next expected heartbeat


class AssignmentsType(enum.IntEnum):
    """Reference: api/dispatcher.proto AssignmentsMessage.Type."""

    COMPLETE = 0
    INCREMENTAL = 1


class AssignmentAction(enum.IntEnum):
    """Reference: api/dispatcher.proto AssignmentChange.AssignmentAction."""

    UPDATE = 0
    REMOVE = 1


@dataclass
class Assignment(Message):
    """One of task / secret / config (reference: Assignment oneof)."""

    task: Optional[Task] = None
    secret: Optional[Secret] = None
    config: Optional[Config] = None

    @property
    def item(self) -> Any:
        return self.task if self.task is not None else (
            self.secret if self.secret is not None else self.config)


@dataclass
class AssignmentChange(Message):
    assignment: Assignment = field(default_factory=Assignment)
    action: AssignmentAction = AssignmentAction.UPDATE


@dataclass
class AssignmentsMessage(Message):
    type: AssignmentsType = AssignmentsType.COMPLETE
    applies_to: str = ""
    results_in: str = ""
    changes: list[AssignmentChange] = field(default_factory=list)


@dataclass
class UpdateTaskStatusRequest(Message):
    """Reference: api/dispatcher.proto UpdateTaskStatusRequest."""

    session_id: str = ""
    updates: list[tuple[str, TaskStatus]] = field(default_factory=list)

"""Event bus: the backbone of every control loop.

Reference: watch/watch.go (Queue: broadcaster + per-watcher filter) and
watch/queue/queue.go (LimitQueue: a watcher that is force-closed when its
buffer exceeds a limit instead of blocking the publisher — "drop vs close"
semantics).  Publishing never blocks; slow consumers are sacrificed.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Any, Callable, Iterable, Optional


class WatcherClosed(Exception):
    """Raised from get() when the watcher was closed (possibly by overflow)."""


class Watcher:
    def __init__(self, queue: "Queue", matchers: tuple[Callable[[Any], bool], ...],
                 limit: int = 0) -> None:
        self._queue = queue
        self._matchers = matchers
        self._limit = limit
        self._buf: deque = deque()
        self._closed = False
        self.overflowed = False
        self._wakeup: Optional[asyncio.Future] = None

    # -- publisher side -------------------------------------------------
    def _offer(self, event: Any) -> None:
        if self._closed:
            return
        if self._matchers and not any(m(event) for m in self._matchers):
            return
        self._buf.append(event)
        if self._limit and len(self._buf) > self._limit:
            # Reference watch/queue/queue.go:21 — close the watcher rather
            # than block or silently drop.
            self.overflowed = True
            self.close()
            return
        self._wake()

    def _wake(self) -> None:
        if self._wakeup is not None and not self._wakeup.done():
            self._wakeup.set_result(None)

    # -- consumer side --------------------------------------------------
    def __len__(self) -> int:
        return len(self._buf)

    def poll(self) -> list:
        """Drain everything buffered, non-blocking."""
        out = list(self._buf)
        self._buf.clear()
        return out

    def try_get(self):
        if self._buf:
            return self._buf.popleft()
        return None

    async def get(self) -> Any:
        while True:
            if self._buf:
                return self._buf.popleft()
            if self._closed:
                raise WatcherClosed(
                    "watcher closed" + (" (overflow)" if self.overflowed else ""))
            self._wakeup = asyncio.get_running_loop().create_future()
            try:
                await self._wakeup
            finally:
                self._wakeup = None

    def __aiter__(self) -> "Watcher":
        return self

    async def __anext__(self) -> Any:
        try:
            return await self.get()
        except WatcherClosed:
            raise StopAsyncIteration

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue._watchers.discard(self)
        self._wake()

    @property
    def closed(self) -> bool:
        return self._closed


class Queue:
    """Non-blocking broadcaster with filtered, bounded watchers."""

    def __init__(self, limit: int = 0) -> None:
        self._watchers: set[Watcher] = set()
        self._default_limit = limit

    def watch(self, *matchers: Callable[[Any], bool], limit: Optional[int] = None
              ) -> Watcher:
        w = Watcher(self, matchers,
                    self._default_limit if limit is None else limit)
        self._watchers.add(w)
        return w

    def publish(self, event: Any) -> None:
        for w in list(self._watchers):
            w._offer(event)

    def publish_all(self, events: Iterable[Any]) -> None:
        for ev in events:
            self.publish(ev)

    def close(self) -> None:
        for w in list(self._watchers):
            w.close()

    def __len__(self) -> int:
        return len(self._watchers)


async def watch_with_sweep(watcher: Watcher, clock, interval: float):
    """Yield events from ``watcher`` plus ``None`` sweep ticks every
    ``interval`` — the shape of every event-driven-with-periodic-reconcile
    control loop (role manager, member-record reconciler).  Terminates
    cleanly when the watcher closes; cancels its internal futures on exit
    (asyncio.wait does NOT cancel the futures it waited on), and closes the
    watcher so callers can't leak the subscription."""
    get_ev = timer = None
    try:
        while True:
            get_ev = asyncio.ensure_future(watcher.get())
            timer = asyncio.ensure_future(clock.sleep(interval))
            done, pending = await asyncio.wait(
                {get_ev, timer}, return_when=asyncio.FIRST_COMPLETED)
            for p in pending:
                p.cancel()
            if get_ev in done:
                try:
                    yield get_ev.result()
                except WatcherClosed:
                    return
            else:
                yield None
    finally:
        for t in (get_ev, timer):
            if t is not None and not t.done():
                t.cancel()
        watcher.close()

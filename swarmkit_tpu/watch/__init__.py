from swarmkit_tpu.watch.queue import Queue, Watcher, WatcherClosed

__all__ = ["Queue", "Watcher", "WatcherClosed"]

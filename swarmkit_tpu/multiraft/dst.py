"""DST adversary drive for the multi-raft group axis.

`dst.explore` broadcasts ONE init state over the schedule axis; the
serving plane instead owns a LIVE [G, N, ...] grouped state and wants to
drive it under a per-group `FaultSchedule` batch — group g gets schedule
slice g, exactly the mapping `FaultSchedule.slice` defines.  This module
reuses explore's per-lane tick (`_tick_one`: adversary verbs ->
effective_faults -> step -> invariant checkers), so every attack profile
and every invariant bit works unchanged per group, and the host gets the
same [G] violation bitmasks the DST pipeline already consumes
(postmortem, shrinking, artifact schema).

Fault isolation contract: each vmap lane reads only its own schedule
slice and its own group state, so faults injected into group g cannot
perturb any other group — pinned bit-for-bit by
tests/test_multiraft.py::test_group_isolation*.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from swarmkit_tpu.dst.explore import _tick_one
from swarmkit_tpu.dst.schedule import FaultSchedule
from swarmkit_tpu.raft.sim.state import SimConfig, SimState

I32 = jnp.int32


@partial(jax.jit, static_argnames=("cfg", "prop_count"))
def run_groups_under_schedule(gstate: SimState, cfg: SimConfig,
                              schedule: FaultSchedule,
                              prop_count: int = 0):
    """Advance the grouped state `schedule.ticks` ticks, group g under
    schedule slice g, checking invariants per group every tick.

    `schedule` is a [G, T, ...] batch (dst/schedule.py make_batch, or a
    hand-built FaultSchedule whose leading axis matches the group count).
    Returns (final, viol [G] uint32 bitmasks, first [G] first-violating
    tick or -1).
    """
    # scan consumes xs with a leading T axis; schedules batch as [G, T, ..]
    xs = jax.tree_util.tree_map(lambda a: jnp.moveaxis(a, 1, 0), schedule)

    def body(carry, sched_t):
        st, acc = carry
        new, bits = jax.vmap(
            lambda s, sch: _tick_one(s, cfg, sch, prop_count, None)
        )(st, sched_t)
        return (new, acc | bits), bits

    groups = schedule.target_leader.shape[0]
    init = (gstate, jnp.zeros((groups,), jnp.uint32))
    (final, viol), bits_by_tick = jax.lax.scan(body, init, xs)  # [T, G]
    any_t = bits_by_tick > 0
    first = jnp.where(jnp.any(any_t, axis=0),
                      jnp.argmax(any_t, axis=0).astype(I32), -1)
    return final, viol, first

"""Hot-group heat: the EWMA load score behind rebalance decisions.

ROADMAP item 2 asks for "router-level hot-group detection feeding
rebalance decisions from the ``router_keys{outcome='spilled'}`` signal".
This module is that detector's scoring core: one exponentially-weighted
moving average per group, fused from the two per-group load signals the
serving plane already produces —

- **router spills** (keys deferred past a flush by the group's
  ``max_props`` capacity): the saturation signal.  A spill means offered
  load exceeded what the group could even accept this tick, so spills are
  weighted ``SPILL_WEIGHT`` x heavier than served traffic.
- **commit rate** (entries committed through consensus per scrape): the
  utilization signal.  A group can be hot without spilling yet — a rising
  commit rate is the early warning the spill counter cannot give.

Heat is in "weighted events per scrape" units: raw_g = SPILL_WEIGHT *
spill_delta_g + commit_delta_g, folded as heat_g <- (1 - alpha) * heat_g
+ alpha * raw_g.  Inputs are CUMULATIVE counters (the shape the device
state and the Router both keep); the tracker deltas them internally and
re-baselines on decrease (new run), the same reset rule as
metrics/scrape.py.

``hottest_groups()`` is the designated input for the future rebalance
verb: it returns group indices ranked hottest-first, so "split / move the
top-k" is a one-liner for the layer above.  `MultiRaftObs` publishes the
scores as ``swarm_multiraft_group_heat``.
"""

from __future__ import annotations

import numpy as np

# Spilled keys count this many times a committed entry in the heat score:
# a spill is load the group already could not absorb, a commit is load it
# handled — saturation must outrank throughput when ranking candidates
# for rebalancing.
SPILL_WEIGHT = 4.0


class HeatTracker:
    """Per-group EWMA heat over (spill, commit) cumulative counters."""

    def __init__(self, groups: int, alpha: float = 0.5) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.groups = groups
        self.alpha = alpha
        self.heat = np.zeros((groups,), np.float64)
        self._prev_commit: np.ndarray | None = None
        self._prev_spill: np.ndarray | None = None

    @staticmethod
    def _delta(prev: np.ndarray | None, cur: np.ndarray) -> np.ndarray:
        """Cumulative -> per-scrape delta, re-baselining on decrease
        (fresh state / new run: count the full reading, as scrape.py
        CounterDeltas does)."""
        if prev is None:
            return np.zeros_like(cur)      # first scrape is the baseline
        d = cur - prev
        return np.where(d >= 0, d, cur)

    def update(self, commit_by_group, spill_by_group=None) -> np.ndarray:
        """Fold one scrape's cumulative readings; returns the new heat.

        commit_by_group: [G] cumulative committed entries per group
        (``max(commit, axis=-1)`` of the grouped state).  spill_by_group:
        [G] cumulative spilled keys per group (``Router.spilled_by_group``;
        None when no router fronts the plane — heat is then pure commit
        rate).
        """
        commit = np.asarray(commit_by_group, np.float64)
        if commit.shape != (self.groups,):
            raise ValueError(f"expected [{self.groups}] commit readings, "
                             f"got shape {commit.shape}")
        raw = self._delta(self._prev_commit, commit)
        self._prev_commit = commit
        if spill_by_group is not None:
            spill = np.asarray(spill_by_group, np.float64)
            raw = raw + SPILL_WEIGHT * self._delta(self._prev_spill, spill)
            self._prev_spill = spill
        self.heat = (1.0 - self.alpha) * self.heat + self.alpha * raw
        return self.heat

    def hottest_groups(self, k: int | None = None) -> list[int]:
        """Group indices ranked hottest-first (ties: lower index first —
        deterministic for the rebalance verb).  `k` caps the list."""
        order = np.argsort(-self.heat, kind="stable")
        out = [int(g) for g in order]
        return out if k is None else out[:k]

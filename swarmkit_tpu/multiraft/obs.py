"""swarm_multiraft_* metric names + the serving-plane publisher.

``METRIC_NAMES`` is the scrape-side schema for the multi-raft serving
plane; ``tools/metrics_lint.py`` check #11 pins it to the catalog in both
directions (every constant has a spec with exactly these labels, every
swarm_multiraft_* spec has a constant), the same lockstep discipline the
flight recorder (#5), telemetry plane (#6), and model checker (#7) get.

`MultiRaftObs` mirrors `KernelObs` (raft/sim/run.py) for the group axis:
pull the tiny aggregate quantities off device once per publish, fold the
cumulative ones through the shared per-registry delta seam
(metrics/scrape.py) so repeated publishes of the same state add nothing,
and gauge the point-in-time ones.
"""

from __future__ import annotations

import jax
import numpy as np

from swarmkit_tpu.multiraft.group import (
    aggregate_committed, aggregate_reads_served, group_leaders, groups_of,
)
from swarmkit_tpu.raft.sim.state import SimState

METRIC_GROUPS = "swarm_multiraft_groups"
METRIC_GROUPS_WITH_LEADER = "swarm_multiraft_groups_with_leader"
METRIC_ROUTER_KEYS = "swarm_multiraft_router_keys_total"
METRIC_LEADER_CHANGES = "swarm_multiraft_leader_changes_total"
METRIC_COMMITTED = "swarm_multiraft_committed_entries_total"
METRIC_READS = "swarm_multiraft_reads_served_total"

# name -> required label names, exactly as the catalog must declare them
METRIC_NAMES = {
    METRIC_GROUPS: (),
    METRIC_GROUPS_WITH_LEADER: (),
    METRIC_ROUTER_KEYS: ("outcome",),      # routed | spilled
    METRIC_LEADER_CHANGES: (),
    METRIC_COMMITTED: (),
    METRIC_READS: (),
}

# one valid value per label, for the lint's publishability probe
SAMPLE_LABELS = {
    "outcome": "routed",
}


class MultiRaftObs:
    """Host-side observability for a [G, N, ...] grouped state.

    ``publish(gstate)`` folds the aggregate serving quantities into the
    swarm_multiraft_* families and returns them as a dict.  Per-group
    leader changes are detected host-side by diffing each group's leader
    row against the previous publish: a group whose CURRENT leader is a
    different concrete row than last time counts one change (the first
    publish only establishes the baseline; a group that merely lost its
    leader counts when the replacement appears).  Router outcomes are
    pushed by the Router through ``router_keys``.
    """

    def __init__(self, registry=None) -> None:
        from swarmkit_tpu.metrics import catalog as obs_catalog
        from swarmkit_tpu.metrics import registry as obs_registry
        from swarmkit_tpu.metrics import scrape as obs_scrape

        self.obs = registry or obs_registry.DEFAULT
        self._m = {name: obs_catalog.get(self.obs, name)
                   for name in METRIC_NAMES}
        self._deltas = obs_scrape.deltas_for(self.obs)
        self._last_leaders: np.ndarray | None = None

    def router_keys(self, outcome: str, n: int = 1) -> None:
        self._m[METRIC_ROUTER_KEYS].labels(outcome=outcome).inc(n)

    def publish(self, gstate: SimState) -> dict:
        g = groups_of(gstate)
        leaders = np.asarray(jax.device_get(group_leaders(gstate)))
        with_leader = int((leaders >= 0).sum())
        self._m[METRIC_GROUPS].set(g)
        self._m[METRIC_GROUPS_WITH_LEADER].set(with_leader)

        changes = 0
        if self._last_leaders is not None:
            changes = int(((leaders >= 0)
                           & (leaders != self._last_leaders)).sum())
            if changes:
                self._m[METRIC_LEADER_CHANGES].inc(changes)
        self._last_leaders = leaders

        out = {"groups": g, "groups_with_leader": with_leader,
               "leader_changes": changes}
        committed = int(jax.device_get(aggregate_committed(gstate)))
        d = self._deltas.advance((METRIC_COMMITTED,), committed)
        if d:
            self._m[METRIC_COMMITTED].inc(d)
        out["committed_entries"] = committed
        if gstate.read_srv is not None:
            reads = int(jax.device_get(aggregate_reads_served(gstate)))
            d = self._deltas.advance((METRIC_READS,), reads)
            if d:
                self._m[METRIC_READS].inc(d)
            out["reads_served"] = reads
        return out

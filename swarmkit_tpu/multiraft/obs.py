"""swarm_multiraft_* metric names + the serving-plane publisher.

``METRIC_NAMES`` is the scrape-side schema for the multi-raft serving
plane; ``tools/metrics_lint.py`` check #11 pins it to the catalog in both
directions (every constant has a spec with exactly these labels, every
swarm_multiraft_* spec has a constant), the same lockstep discipline the
flight recorder (#5), telemetry plane (#6), and model checker (#7) get.

`MultiRaftObs` mirrors `KernelObs` (raft/sim/run.py) for the group axis:
pull the tiny aggregate quantities off device once per publish, fold the
cumulative ones through the shared per-registry delta seam
(metrics/scrape.py) so repeated publishes of the same state add nothing,
and gauge the point-in-time ones.

The fleet-health extension (ISSUE 20) adds the PER-GROUP families:
commit-latency p50/p99 read off each group's on-device telemetry
histogram, a per-group leader-changes counter (the churn-rate input for
the SLO engine), and the ``swarm_multiraft_group_heat`` EWMA score fused
from router spills + commit rate (multiraft/heat.py).  Per-group label
sets are bounded: the registry caps a family at MAX_LABEL_SETS children,
so fleets beyond ``GROUP_LABEL_CAP`` groups publish heat for the top
``HEAT_TOP_K`` hottest groups only and skip the other per-group families
— the aggregates always publish, whatever G is.
"""

from __future__ import annotations

import jax
import numpy as np

from swarmkit_tpu.multiraft.group import (
    aggregate_reads_served, group_leaders, groups_of,
)
from swarmkit_tpu.multiraft.heat import HeatTracker
from swarmkit_tpu.raft.sim.state import SimState

METRIC_GROUPS = "swarm_multiraft_groups"
METRIC_GROUPS_WITH_LEADER = "swarm_multiraft_groups_with_leader"
METRIC_ROUTER_KEYS = "swarm_multiraft_router_keys_total"
METRIC_LEADER_CHANGES = "swarm_multiraft_leader_changes_total"
METRIC_COMMITTED = "swarm_multiraft_committed_entries_total"
METRIC_READS = "swarm_multiraft_reads_served_total"
METRIC_GROUP_COMMIT_LATENCY = "swarm_multiraft_group_commit_latency_ticks"
METRIC_GROUP_LEADER_CHANGES = "swarm_multiraft_group_leader_changes_total"
METRIC_GROUP_HEAT = "swarm_multiraft_group_heat"

# Per-group families label by group index; a registry family holds at
# most MAX_LABEL_SETS children, so per-group publishing is gated on G.
GROUP_LABEL_CAP = 64
HEAT_TOP_K = 8

# name -> required label names, exactly as the catalog must declare them
METRIC_NAMES = {
    METRIC_GROUPS: (),
    METRIC_GROUPS_WITH_LEADER: (),
    METRIC_ROUTER_KEYS: ("outcome",),      # routed | spilled
    METRIC_LEADER_CHANGES: (),
    METRIC_COMMITTED: (),
    METRIC_READS: (),
    METRIC_GROUP_COMMIT_LATENCY: ("group", "quantile"),   # p50 | p99
    METRIC_GROUP_LEADER_CHANGES: ("group",),
    METRIC_GROUP_HEAT: ("group",),
}

# one valid value per label, for the lint's publishability probe
SAMPLE_LABELS = {
    "outcome": "routed",
    "group": "0",
    "quantile": "p99",
}


class MultiRaftObs:
    """Host-side observability for a [G, N, ...] grouped state.

    ``publish(gstate)`` folds the aggregate serving quantities into the
    swarm_multiraft_* families and returns them as a dict.  Per-group
    leader changes are detected host-side by diffing each group's leader
    row against the previous publish: a group whose CURRENT leader is a
    different concrete row than last time counts one change (the first
    publish only establishes the baseline; a group that merely lost its
    leader counts when the replacement appears).  Router outcomes are
    pushed by the Router through ``router_keys``.

    Pass the fronting ``Router`` to ``publish(gstate, router=r)`` and the
    heat score fuses its per-group spill counters; without one, heat is
    pure per-group commit rate.  ``hottest_groups()`` exposes the
    resulting ranking — the designated input for the rebalance verb.
    """

    def __init__(self, registry=None, heat_alpha: float = 0.5) -> None:
        from swarmkit_tpu.metrics import catalog as obs_catalog
        from swarmkit_tpu.metrics import registry as obs_registry
        from swarmkit_tpu.metrics import scrape as obs_scrape

        self.obs = registry or obs_registry.DEFAULT
        self._m = {name: obs_catalog.get(self.obs, name)
                   for name in METRIC_NAMES}
        self._deltas = obs_scrape.deltas_for(self.obs)
        self._last_leaders: np.ndarray | None = None
        self._heat_alpha = heat_alpha
        self.heat: HeatTracker | None = None    # sized at first publish

    def router_keys(self, outcome: str, n: int = 1) -> None:
        self._m[METRIC_ROUTER_KEYS].labels(outcome=outcome).inc(n)

    def hottest_groups(self, k: int | None = None) -> list[int]:
        """Hottest-first group ranking (empty before the first publish)."""
        return [] if self.heat is None else self.heat.hottest_groups(k)

    def _publish_group_latency(self, gstate: SimState, g: int) -> None:
        from swarmkit_tpu.telemetry.obs import percentile_edge

        hist = np.asarray(jax.device_get(gstate.tel_commit_hist))
        fam = self._m[METRIC_GROUP_COMMIT_LATENCY]
        for gi in range(g):
            counts = hist[gi]
            for q in (50, 99):
                edge = percentile_edge(counts, q)
                if edge is not None:
                    fam.labels(group=str(gi), quantile=f"p{q}").set(edge)

    def publish(self, gstate: SimState, router=None) -> dict:
        g = groups_of(gstate)
        leaders = np.asarray(jax.device_get(group_leaders(gstate)))
        with_leader = int((leaders >= 0).sum())
        self._m[METRIC_GROUPS].set(g)
        self._m[METRIC_GROUPS_WITH_LEADER].set(with_leader)

        changes = 0
        per_group_ok = g <= GROUP_LABEL_CAP
        if self._last_leaders is not None:
            changed = (leaders >= 0) & (leaders != self._last_leaders)
            changes = int(changed.sum())
            if changes:
                self._m[METRIC_LEADER_CHANGES].inc(changes)
            if per_group_ok:
                fam = self._m[METRIC_GROUP_LEADER_CHANGES]
                for gi in np.nonzero(changed)[0]:
                    fam.labels(group=str(int(gi))).inc()
        self._last_leaders = leaders

        out = {"groups": g, "groups_with_leader": with_leader,
               "leader_changes": changes}
        commit_by_group = np.asarray(
            jax.device_get(jax.numpy.max(gstate.commit, axis=-1)))
        committed = int(commit_by_group.sum())
        d = self._deltas.advance((METRIC_COMMITTED,), committed)
        if d:
            self._m[METRIC_COMMITTED].inc(d)
        out["committed_entries"] = committed
        if gstate.read_srv is not None:
            reads = int(jax.device_get(aggregate_reads_served(gstate)))
            d = self._deltas.advance((METRIC_READS,), reads)
            if d:
                self._m[METRIC_READS].inc(d)
            out["reads_served"] = reads

        # per-group commit latency off the grouped telemetry histograms
        if per_group_ok and gstate.tel_commit_hist is not None:
            self._publish_group_latency(gstate, g)

        # hot-group heat: EWMA over router spills + per-group commit rate
        if self.heat is None or self.heat.groups != g:
            self.heat = HeatTracker(g, alpha=self._heat_alpha)
        spills = None if router is None else router.spilled_by_group
        heat = self.heat.update(commit_by_group, spills)
        fam = self._m[METRIC_GROUP_HEAT]
        hot = self.heat.hottest_groups(HEAT_TOP_K)
        for gi in (range(g) if per_group_ok else hot):
            fam.labels(group=str(int(gi))).set(float(heat[int(gi)]))
        out["hottest_groups"] = hot
        return out

"""Host-side key -> group router for the multi-raft serving plane.

Clients address KEYS; the serving plane holds G raft groups.  The router
maps each key to its owning group by stable hashing (blake2b keyed by the
router seed — deterministic across processes and Python hash
randomization, unlike ``hash()``), buckets offered writes/reads into
per-group batches, and feeds one tick's worth of batches through the
vmapped kernel (`propose_groups` + `submit_reads_groups` + `step_groups`)
per `flush`.

A group's per-tick proposal capacity is ``cfg.max_props``; keys offered
beyond that SPILL — they stay queued for the next flush rather than being
dropped, and the spill is surfaced through
``swarm_multiraft_router_keys_total{outcome="spilled"}`` so a hot group
shows up on the scrape page instead of as silent tail latency.
"""

from __future__ import annotations

import hashlib

import numpy as np

from swarmkit_tpu.multiraft.group import (
    propose_groups, step_groups, submit_reads_groups,
)
from swarmkit_tpu.raft.sim.state import SimConfig, SimState


def group_of_key(key, groups: int, seed: int = 0) -> int:
    """Owning group of `key` (str / bytes / int): stable across processes,
    uniform over [0, groups)."""
    if isinstance(key, int):
        key = key.to_bytes(8, "little", signed=True)
    elif isinstance(key, str):
        key = key.encode("utf-8")
    h = hashlib.blake2b(key, digest_size=8,
                        key=seed.to_bytes(8, "little", signed=True))
    return int.from_bytes(h.digest(), "little") % groups


class Router:
    """Per-group write/read batching front end.

    >>> r = Router(cfg, groups=64)
    >>> r.offer(b"user/123", payload=0xBEEF)   # returns the owning group
    >>> r.offer_read(b"user/123")
    >>> gstate = r.flush(gstate)               # one tick, batches applied

    `flush` is one serving tick: drain up to cfg.max_props queued payloads
    per group into a vmapped `propose`, submit queued read counts, then
    `step_groups`.  Queues keep their overflow for the next flush.
    """

    def __init__(self, cfg: SimConfig, groups: int, seed: int = 0,
                 obs=None) -> None:
        self.cfg = cfg
        self.groups = groups
        self.seed = seed
        self.obs = obs                      # optional MultiRaftObs
        self._writes: list[list[int]] = [[] for _ in range(groups)]
        self._reads = np.zeros((groups,), np.int64)
        self.routed = 0                     # keys accepted into queues
        self.spilled = 0                    # flushes deferred by capacity
        # cumulative per-group flow, the heat detector's inputs
        # (multiraft/heat.py): offered keys and capacity spills by group
        self.routed_by_group = np.zeros((groups,), np.int64)
        self.spilled_by_group = np.zeros((groups,), np.int64)

    def group_of(self, key) -> int:
        return group_of_key(key, self.groups, self.seed)

    def offer(self, key, payload: int) -> int:
        """Queue one write of `payload` (uint32; bit 31 reserved for conf
        entries) under `key`; returns the owning group."""
        g = self.group_of(key)
        self._writes[g].append(int(payload) & 0x7FFFFFFF)
        self.routed += 1
        self.routed_by_group[g] += 1
        if self.obs is not None:
            self.obs.router_keys("routed")
        return g

    def offer_read(self, key, count: int = 1) -> int:
        """Queue `count` linearizable read ops under `key`; returns the
        owning group (cfg.read_batch > 0 required at flush time)."""
        g = self.group_of(key)
        self._reads[g] += count
        self.routed += count
        self.routed_by_group[g] += count
        if self.obs is not None:
            self.obs.router_keys("routed", count)
        return g

    def pending(self) -> tuple[int, int]:
        """(queued writes, queued read ops) across all groups."""
        return (sum(len(q) for q in self._writes), int(self._reads.sum()))

    def flush(self, gstate: SimState) -> SimState:
        """Apply one tick's batches and advance every group one tick."""
        cap = self.cfg.max_props
        payloads = np.zeros((self.groups, cap), np.uint32)
        counts = np.zeros((self.groups,), np.int32)
        spilled = 0
        for g, q in enumerate(self._writes):
            take = min(len(q), cap)
            over = len(q) - take
            spilled += over
            self.spilled_by_group[g] += over
            if take:
                payloads[g, :take] = q[:take]
                counts[g] = take
                self._writes[g] = q[take:]
        if spilled:
            self.spilled += spilled
            if self.obs is not None:
                self.obs.router_keys("spilled", spilled)
        if counts.any():
            gstate = propose_groups(gstate, self.cfg, payloads, counts)
        if self._reads.any():
            rc = np.minimum(self._reads, np.iinfo(np.int32).max)
            gstate = submit_reads_groups(gstate, self.cfg,
                                         rc.astype(np.int32))
            self._reads[:] = 0
        return step_groups(gstate, self.cfg)

"""[G, N, ...] multi-group serving plane over the single-group tick kernel.

Production stores shard the keyspace over many small raft groups rather
than one giant quorum (CockroachDB/TiKV ranges; arXiv:2004.05074 frames
per-group consensus as the composable unit).  The DST layer already vmaps
S independent clusters over a leading schedule axis (dst/explore.py);
this module promotes that batch axis into a first-class SERVING mode: a
[G, N, ...] state holding G independent groups, advanced one tick at a
time by `jax.vmap` over the unmodified `kernel.step` — so every
`SimConfig` lever (tiled log, banded peer reductions, role-sparse
progress, leases, mailbox wires, storage model) stays live per group,
and per-group optimizations port mechanically (arXiv:1905.10786).

Bit-identity contract: `step_groups` is PYTHON-GATED on the group count.
At G == 1 it bypasses vmap entirely and runs the plain single-group
`step` on the squeezed state, so the compiled program — not just its
values — is literally today's kernel (pinned by
tests/test_multiraft.py::test_g1_bit_identity).

Grouped telemetry (ISSUE 20) rides the same two gates and adds none of
its own: with ``cfg.collect_telemetry`` on, `init_state` carries the
telemetry leaves (histograms, [NUM_SERIES, window] ring, propose-batch
stamps), `init_groups` broadcasts them to [G, ...] like every other
leaf, and the vmapped kernel's Python-gated end-of-tick telemetry block
folds each group's lane independently — so every group carries its own
latency histograms and [G, NUM_SERIES, window] series rings with zero
kernel changes.  Telemetry OFF keeps the leaves ``None`` (never traced,
bit-identical program), and the G == 1 short-circuit covers the
telemetry leaves exactly like the rest of the state; both pins live in
tests/test_multiraft.py::TestGroupedTelemetry.  `slice_group` extracts
one group's plain SimState for the single-group summarize/publish path.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from swarmkit_tpu.raft.sim.kernel import propose, step
from swarmkit_tpu.raft.sim.run import (
    _payload_at, leader_mask, submit_reads,
)
from swarmkit_tpu.raft.sim.state import (
    SimConfig, SimState, init_state, rand_timeout,
)

I32 = jnp.int32


def groups_of(gstate: SimState) -> int:
    """Static group count G of a grouped state (leading-axis length)."""
    return gstate.tick.shape[0]


def slice_group(gstate: SimState, g: int) -> SimState:
    """One group's plain (ungrouped) SimState — every leaf indexed at g.

    The seam between the [G, ...] plane and the single-group host
    tooling: the sliced state is exactly what `telemetry.obs
    .summarize_state` / `flightrec.decode_state` / `KernelObs.publish`
    consume."""
    return jax.tree_util.tree_map(lambda a: a[g], gstate)


def init_groups(cfg: SimConfig, groups: int,
                stagger: bool = True) -> SimState:
    """Stack `groups` fresh independent clusters on a new leading [G] axis.

    Group 0 is bit-identical to ``init_state(cfg)`` — the G=1 serving
    plane IS the single-group deployment.  With `stagger` (default),
    groups g > 0 re-randomize their initial election timeouts with g
    folded into the ``rand_timeout`` term argument (still inside
    [T, 2T), still deterministic per (node, g, seed)), so a fresh fleet
    does not campaign in lock-step across groups.
    """
    base = init_state(cfg)
    gstate = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a, (groups,) + a.shape), base)
    if stagger and groups > 1:
        node = jnp.arange(cfg.n, dtype=I32)
        gid = jnp.arange(groups, dtype=I32)
        tmo = jax.vmap(
            lambda g: rand_timeout(cfg, node, jnp.full((cfg.n,), g, I32))
        )(gid)
        gstate = dataclasses.replace(gstate, timeout=tmo)
    return gstate


@partial(jax.jit, static_argnames=("cfg", "payload_fn"))
def step_groups(gstate: SimState, cfg: SimConfig, alive=None, drop=None,
                prop_count=None, payload_fn=None) -> SimState:
    """Advance every group one tick (vmapped `kernel.step`, jit-cached
    per (G, cfg) so host drivers like `Router.flush` pay one trace).

    alive: [G, N] bool, drop: [G, N, N] bool — per-group fault inputs
    (None = fault-free everywhere).  prop_count is the fused-propose
    batch size: a scalar applies to all groups, a [G] array gives each
    group its own count (the router's flush path) — pair it with
    `payload_fn` exactly as in the single-group drivers.

    G == 1 short-circuits to the plain single-group `step` (module
    docstring: the bit-identity gate).
    """
    if groups_of(gstate) == 1:
        one = jax.tree_util.tree_map(lambda a: a[0], gstate)
        pc = None
        if prop_count is not None:
            pc = jnp.asarray(prop_count, I32).reshape(-1)[0] \
                if jnp.ndim(prop_count) else jnp.asarray(prop_count, I32)
        out = step(one, cfg,
                   alive=None if alive is None else alive[0],
                   drop=None if drop is None else drop[0],
                   prop_count=pc, payload_fn=payload_fn)
        return jax.tree_util.tree_map(lambda a: a[None], out)

    pc = None if prop_count is None else jnp.asarray(prop_count, I32)
    pc_axis = 0 if (pc is not None and pc.ndim == 1) else None

    def one(st, alive_g, drop_g, pc_g):
        return step(st, cfg, alive=alive_g, drop=drop_g,
                    prop_count=pc_g, payload_fn=payload_fn)

    return jax.vmap(
        one,
        in_axes=(0, None if alive is None else 0,
                 None if drop is None else 0, pc_axis)
    )(gstate, alive, drop, pc)


@partial(jax.jit, static_argnames=("cfg",))
def propose_groups(gstate: SimState, cfg: SimConfig, payloads,
                   counts) -> SimState:
    """Vmapped host `propose`: payloads [G, max_props] uint32, counts [G].

    Appends each group's batch to whatever row currently claims that
    group's leadership (same acceptance rules as the single-group API).
    Outside scans only — scan drivers must use the fused
    ``step_groups(prop_count=, payload_fn=)`` path to keep the [G, N, L]
    log buffers in place (kernel.step docstring).
    """
    return jax.vmap(
        lambda st, pl, c: propose(st, cfg, pl, c)
    )(gstate, jnp.asarray(payloads, jnp.uint32), jnp.asarray(counts, I32))


@partial(jax.jit, static_argnames=("cfg",))
def submit_reads_groups(gstate: SimState, cfg: SimConfig,
                        counts) -> SimState:
    """Vmapped `submit_reads`: counts [G] linearizable read ops offered to
    every row of each group (cfg.read_batch > 0)."""
    return jax.vmap(
        lambda st, c: submit_reads(st, cfg, c)
    )(gstate, jnp.asarray(counts, I32))


@partial(jax.jit, static_argnames=("cfg", "n_ticks", "prop_count"))
def run_group_ticks(gstate: SimState, cfg: SimConfig, n_ticks: int,
                    prop_count: int = 0):
    """Advance all groups `n_ticks` as one scan-compiled program.

    Per tick: optionally fused-propose `prop_count` entries to each
    group's leader (deterministic `_payload_at` payloads, as in
    run_ticks).  Linearizable reads need no explicit driver — with
    cfg.read_batch > 0 every group's kernel runs its own closed-loop
    refill (Phase R0), so `aggregate_reads_served` advances on its own.

    Returns (final, trace) with per-tick trace rows
    [groups_with_leader, aggregate_commit].
    """

    def body(st, _):
        if prop_count:
            st = step_groups(st, cfg,
                             prop_count=jnp.asarray(prop_count, I32),
                             payload_fn=_payload_at)
        else:
            st = step_groups(st, cfg)
        row = jnp.stack([groups_with_leader(st), aggregate_committed(st)])
        return st, row

    return jax.lax.scan(body, gstate, None, length=n_ticks)


# --- aggregate observables (the serving plane's headline quantities) -----

def group_leader_mask(gstate: SimState) -> jax.Array:
    """[G, N] bool: rows currently acting as their group's leader."""
    return jax.vmap(leader_mask)(gstate)


def group_leaders(gstate: SimState) -> jax.Array:
    """[G] int32: leader row per group, -1 while a group has none."""
    lm = group_leader_mask(gstate)
    return jnp.where(jnp.any(lm, axis=-1),
                     jnp.argmax(lm, axis=-1).astype(I32), -1)


def groups_with_leader(gstate: SimState) -> jax.Array:
    """Scalar: number of groups that currently have an acting leader."""
    return jnp.sum(jnp.any(group_leader_mask(gstate), axis=-1)
                   .astype(I32))


def aggregate_committed(gstate: SimState) -> jax.Array:
    """Total entries committed through consensus, summed over groups
    (per group: max commit across rows, as `committed_entries`)."""
    return jnp.sum(jnp.max(gstate.commit, axis=-1))


def aggregate_reads_served(gstate: SimState) -> jax.Array:
    """Total linearizable read ops served across all groups and rows
    (0 when the read path is off)."""
    if gstate.read_srv is None:
        return jnp.asarray(0, I32)
    return jnp.sum(gstate.read_srv)


def aggregate_reads_blocked(gstate: SimState) -> jax.Array:
    """Total read ops refused (deposal / lease expiry) across groups."""
    if gstate.read_block is None:
        return jnp.asarray(0, I32)
    return jnp.sum(gstate.read_block)

"""Multi-raft serving plane: G independent raft groups as one program.

Promotes the DST-only batch axis into a first-class serving mode: a
[G, N, ...] grouped `SimState` advanced by the unmodified tick kernel
under `jax.vmap`, a host-side key->group `Router`, group->device
placement over `parallel.group_mesh` / `shard_rows`, and
`swarm_multiraft_*` observability.  See group.py for the G=1
bit-identity contract and dst.py for adversary drivability.
"""

from swarmkit_tpu.multiraft.dst import run_groups_under_schedule
from swarmkit_tpu.multiraft.group import (
    aggregate_committed, aggregate_reads_blocked, aggregate_reads_served,
    group_leader_mask, group_leaders, groups_of, groups_with_leader,
    init_groups, propose_groups, run_group_ticks, slice_group,
    step_groups, submit_reads_groups,
)
from swarmkit_tpu.multiraft.heat import SPILL_WEIGHT, HeatTracker
from swarmkit_tpu.multiraft.obs import METRIC_NAMES, MultiRaftObs
from swarmkit_tpu.multiraft.router import Router, group_of_key

__all__ = [
    "METRIC_NAMES", "MultiRaftObs", "Router",
    "HeatTracker", "SPILL_WEIGHT",
    "aggregate_committed", "aggregate_reads_blocked",
    "aggregate_reads_served", "group_leader_mask", "group_leaders",
    "group_of_key", "groups_of", "groups_with_leader", "init_groups",
    "propose_groups", "run_group_ticks", "run_groups_under_schedule",
    "slice_group", "step_groups", "submit_reads_groups",
]

"""Hand-tiled Pallas TPU kernels for executor task programs.

The TPU executor's built-in programs (`agent/tpu.py`) are the framework's
workload analog of the reference's container images (the Docker executor
runs whatever the image says, agent/exec/dockerapi/controller.go); here the
runtime is XLA, and the hottest workload class is dense matmul chains on
the MXU.  XLA already tiles a plain `jnp.dot` well, but a task program that
owns its schedule — tile sizes matched to the 128x128 systolic array, f32
accumulation in VMEM scratch, K-innermost grid so each output tile is
revisited without leaving VMEM — is the TPU-native equivalent of a
hand-optimized container workload, and exercises the Pallas path the rest
of the framework reserves for futures profiling shows need it.

Kernels run `interpret=True` off-TPU (and under
`xla_force_host_platform_device_count` CPU meshes), so the same task image
(`tpu://pallas_matmul`) is schedulable on any node, exactly like the
builtins.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# MXU is 128x128; bf16 min tile is (16, 128).  128-multiples keep every
# block MXU-shaped and lane-aligned for both dtypes we accept.
_LANE = 128

# jax renamed TPUCompilerParams -> CompilerParams across 0.4.x/0.5.x;
# accept either so the kernels run on whichever jax the image bakes in.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams", None)


def _compiler_params(dimension_semantics):
    if _CompilerParams is None:
        return None
    return _CompilerParams(dimension_semantics=dimension_semantics)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _mm_kernel(a_ref, b_ref, o_ref, acc_ref):
    """One (i, j, k) grid step: acc += A[i,k] @ B[k,j]; flush on last k.

    The grid iterates K innermost, so `acc_ref` (VMEM scratch, f32) carries
    the partial sum for output tile (i, j) across the K sweep — the MXU
    consumes bf16/f32 operands but accumulation stays f32 until the final
    cast, which is the standard mixed-precision contraction discipline.
    """
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                          preferred_element_type=jnp.float32)

    @pl.when(k == pl.num_programs(2) - 1)
    def _flush():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("tile_m", "tile_n", "tile_k",
                                             "interpret"))
def matmul(a: jax.Array, b: jax.Array, *, tile_m: int = 256,
           tile_n: int = 256, tile_k: int = 256,
           interpret: bool | None = None) -> jax.Array:
    """Tiled Pallas matmul: [M, K] @ [K, N] -> [M, N] in `a.dtype`.

    Shapes must divide the tile sizes (task programs pick aligned shapes;
    this is a kernel, not a frontend).  `interpret=None` auto-selects the
    interpreter off-TPU.
    """
    m, ka = a.shape
    kb, n = b.shape
    if ka != kb:
        raise ValueError(f"contraction mismatch: {a.shape} @ {b.shape}")
    tile_m, tile_n, tile_k = (min(tile_m, m), min(tile_n, n), min(tile_k, ka))
    if m % tile_m or n % tile_n or ka % tile_k:
        raise ValueError(
            f"shapes ({m},{ka})@({kb},{n}) must divide tiles "
            f"({tile_m},{tile_n},{tile_k})")
    if interpret is None:
        interpret = not _on_tpu()
    if not interpret and (tile_n % _LANE or tile_k % _LANE):
        # Mosaic requires the last block dim be a lane multiple; fail with
        # a readable message instead of a lowering error
        raise ValueError(
            f"compiled TPU path needs lane-aligned tiles (multiples of "
            f"{_LANE}): got tile_k={tile_k}, tile_n={tile_n}")

    grid = (m // tile_m, n // tile_n, ka // tile_k)
    return pl.pallas_call(
        _mm_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_m, tile_k), lambda i, j, k: (i, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_k, tile_n), lambda i, j, k: (k, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile_m, tile_n), lambda i, j, k: (i, j),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((tile_m, tile_n), jnp.float32)],
        compiler_params=_compiler_params(
            ("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a, b)


def _rms_kernel(x_ref, o_ref, acc_ref):
    """Row-tiled sum of squares: one grid step accumulates its tile's
    f32 square-sum into SMEM; the last step writes the total."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _zero():
        acc_ref[0] = jnp.float32(0.0)

    x = x_ref[:].astype(jnp.float32)
    acc_ref[0] += jnp.sum(x * x)

    @pl.when(i == pl.num_programs(0) - 1)
    def _flush():
        o_ref[0, 0] = acc_ref[0]


@functools.partial(jax.jit, static_argnames=("tile_m", "interpret"))
def sumsq(x: jax.Array, *, tile_m: int = 256,
          interpret: bool | None = None) -> jax.Array:
    """Sum of squares of a [M, N] array as f32 scalar (pallas-reduced)."""
    m, n = x.shape
    tile_m = min(tile_m, m)
    if m % tile_m:
        raise ValueError(f"rows {m} must divide tile {tile_m}")
    if interpret is None:
        interpret = not _on_tpu()
    if not interpret and n % _LANE:
        raise ValueError(
            f"compiled TPU path needs a lane-aligned last dim (multiple "
            f"of {_LANE}): got {n}")
    out = pl.pallas_call(
        _rms_kernel,
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        grid=(m // tile_m,),
        in_specs=[pl.BlockSpec((tile_m, n), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
        scratch_shapes=[pltpu.SMEM((1,), jnp.float32)],
        compiler_params=_compiler_params(("arbitrary",)),
        interpret=interpret,
    )(x)
    return out[0, 0]


def _band_copy_kernel(d_ref, s_ref, m_ref, o_ref):
    """One row-tile of the banded append copy: a pure VPU select."""
    o_ref[:] = jnp.where(m_ref[:], s_ref[:], d_ref[:])


@functools.partial(jax.jit, static_argnames=("tile_m", "interpret"))
def append_band_copy(dst: jax.Array, src: jax.Array, write: jax.Array, *,
                     tile_m: int = 8, interpret: bool | None = None
                     ) -> jax.Array:
    """Fused masked copy for one [N, C] log band chunk:
    ``out[i, s] = src[i, s] if write[i, s] else dst[i, s]``.

    The raft tick kernel's banded append pass (raft/sim/kernel.py, behind
    SWARMKIT_PALLAS_BAND=1) routes its per-chunk write-back through this
    kernel so the whole chunk streams once through VMEM on TPU; off-TPU it
    runs in interpret mode and is value-identical to the jnp.where it
    replaces (C is a cfg.log_chunk, i.e. a 128-multiple, so the compiled
    path is always lane-aligned)."""
    if dst.shape != src.shape or dst.shape != write.shape:
        raise ValueError(
            f"shape mismatch: dst {dst.shape}, src {src.shape}, "
            f"write {write.shape}")
    m, c = dst.shape
    tile_m = min(tile_m, m)
    while m % tile_m:
        tile_m -= 1
    if interpret is None:
        interpret = not _on_tpu()
    if not interpret and c % _LANE:
        raise ValueError(
            f"compiled TPU path needs a lane-aligned chunk width (multiple "
            f"of {_LANE}): got {c}")
    spec = pl.BlockSpec((tile_m, c), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _band_copy_kernel,
        out_shape=jax.ShapeDtypeStruct((m, c), dst.dtype),
        grid=(m // tile_m,),
        in_specs=[spec, spec, spec],
        out_specs=spec,
        compiler_params=_compiler_params(("parallel",)),
        interpret=interpret,
    )(dst, src, write)


def matmul_chain(x: jax.Array, a: jax.Array, steps: int, *,
                 tile: int = 256, interpret: bool | None = None) -> jax.Array:
    """`steps` rounds of x <- normalize(x @ a), all through the Pallas
    kernels — the pallas twin of the executor's builtin matmul chain."""
    def body(carry, _):
        y = matmul(carry, a, tile_m=tile, tile_n=tile, tile_k=tile,
                   interpret=interpret)
        ss = sumsq(y, tile_m=tile, interpret=interpret)
        denom = jnp.maximum(jnp.sqrt(ss / y.size), 1e-6)
        y = (y.astype(jnp.float32) / denom).astype(y.dtype)
        return y, ()

    out, _ = jax.lax.scan(body, x, None, length=steps)
    return out

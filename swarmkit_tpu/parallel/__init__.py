"""Mesh + sharding helpers for the device-resident subsystems.

The framework's device programs are all SPMD over a 1-D mesh along the
simulated-manager (row) axis: per-node scalars are [N] sharded on the axis,
pairwise progress/mailboxes are [N, N, ...] sharded on the first (row) axis,
and log rings are [N, L] sharded on rows. These helpers centralize the mesh
construction and the pytree→sharding mapping used by the sim kernel, the
device-mesh transport and the multichip dry-run (previously inlined in
__graft_entry__.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MANAGER_AXIS = "managers"


def row_mesh(rows: int, devices: Optional[Sequence] = None,
             axis: str = MANAGER_AXIS) -> Mesh:
    """1-D mesh over the largest device prefix that divides `rows`.

    rows=4096 on 8 devices -> all 8; rows=6 on 8 devices -> 6's largest
    divisor <= 8 is 6... devices don't subdivide, so we take the largest
    d <= len(devices) with rows % d == 0 (worst case d=1: still a valid
    mesh, just unsharded).
    """
    devices = list(devices if devices is not None else jax.devices())
    d = len(devices)
    while d > 1 and rows % d != 0:
        d -= 1
    return Mesh(devices[:d], axis_names=(axis,))


SCHEDULE_AXIS = "schedules"


def schedule_mesh(schedules: int, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the DST schedule axis (dst/explore.py).

    Schedule exploration is embarrassingly data-parallel — each of the S
    vmapped clusters is independent — so the leading S axis shards exactly
    like the manager row axis, just under its own mesh-axis name so a
    future two-level layout (schedules over hosts, rows over chips) can
    compose with `host_row_mesh` without a rename.
    """
    return row_mesh(schedules, devices, axis=SCHEDULE_AXIS)


GROUP_AXIS = "groups"


def group_mesh(groups: int, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the multiraft group axis (multiraft/).

    The serving plane's [G, N, ...] state is embarrassingly data-parallel
    over G independent raft groups — the leading G axis shards exactly
    like the DST schedule axis, under its own mesh-axis name so a future
    two-level layout (groups over hosts, rows over chips) composes with
    `host_row_mesh` without a rename.
    """
    return row_mesh(groups, devices, axis=GROUP_AXIS)


DCN_AXIS = "hosts"    # outer: crosses the data-center network
ICI_AXIS = "chips"    # inner: rides the on-pod interconnect


def host_row_mesh(rows: int, hosts: int = 2,
                  devices: Optional[Sequence] = None) -> Mesh:
    """2-D hosts x chips mesh for multi-host deployments.

    The row (manager) axis shards over BOTH mesh axes with hosts
    OUTERMOST: rows living on the same host are contiguous, so the
    kernel's sender-axis reductions decompose into an ICI-local phase plus
    one small cross-host (DCN) combine — the standard outer-DCN /
    inner-ICI layout (reference analog: swarmkit's managers span machines
    over gRPC; here the placement hierarchy is explicit in the mesh).
    Degrades gracefully: among shapes with hosts <= the request and
    hosts*chips dividing the row count, the one using the MOST devices
    wins (ties keep more hosts; worst case 1x1).  On a real multi-process
    topology the hosts axis follows `device.process_index` and the chips
    axis never crosses a host boundary; in a single process (CPU
    simulation) the partition is simulated over a device prefix.
    """
    import numpy as _np

    devices = list(devices if devices is not None else jax.devices())
    groups: dict[int, list] = {}
    for dev in devices:
        groups.setdefault(getattr(dev, "process_index", 0), []).append(dev)
    if len(groups) > 1:
        # REAL multi-host topology: the hosts axis follows physical
        # processes and the chips axis never crosses a host boundary —
        # otherwise "ICI-local" phases would silently ride the DCN.
        # Hosts are considered LARGEST-first so an uneven small host
        # cannot cap the whole mesh (a 1+4 topology must be able to pick
        # the 4-chip host alone).
        order = sorted(groups, key=lambda p: (-len(groups[p]), p))
        h, c = pick_host_shape(rows, min(hosts, len(order)),
                               [len(groups[p]) for p in order])
        arr = _np.array([groups[p][:c] for p in order[:h]])
    else:
        # single process (CPU simulation, or one host): every device is
        # equidistant, so any prefix reshape is placement-correct and the
        # hosts axis is a SIMULATED partition
        h, c = pick_host_shape(rows, min(hosts, len(devices)),
                               None, total=len(devices))
        arr = _np.array(devices[:h * c]).reshape(h, c)
    return Mesh(arr, axis_names=(DCN_AXIS, ICI_AXIS))


def pick_host_shape(rows: int, max_hosts: int,
                    group_sizes: Optional[list] = None,
                    total: int = 0) -> tuple:
    """(hosts, chips) maximizing devices used, s.t. hosts*chips | rows.

    With `group_sizes` (real multi-host, pre-sorted LARGEST-first by the
    caller), a shape of h hosts uses the h largest hosts and chips is
    bounded by the smallest of those, keeping the mesh rectangular
    without crossing host boundaries; without it, any (h, c) with
    h*c <= total works.  Ties prefer more hosts (h scans downward,
    strict improvement wins).
    """
    best_h, best_c = 1, 1
    for h in range(max(1, max_hosts), 0, -1):
        c = min(g for g in group_sizes[:h]) if group_sizes else total // h
        while c > 1 and rows % (h * c):
            c -= 1
        if rows % (h * c) == 0 and h * c > best_h * best_c:
            best_h, best_c = h, c
    return best_h, best_c


HOST_ROW_AXES = (DCN_AXIS, ICI_AXIS)


def row_spec(ndim: int, axis=MANAGER_AXIS) -> P:
    """PartitionSpec sharding the leading (row) axis, replicating the rest.

    `axis` may be one mesh axis name or a tuple of names (e.g.
    HOST_ROW_AXES) — a tuple shards the single row dimension across the
    flattened product of those mesh axes, hosts-major.
    """
    if ndim == 0:
        return P()
    return P(axis, *([None] * (ndim - 1)))


def state_shardings(mesh: Mesh, tree, axis=MANAGER_AXIS, leading=None):
    """Per-leaf NamedSharding tree: leading axis on the mesh axis (or axes).

    Leaves whose leading dimension the mesh does not divide are
    replicated instead of sharded: row-axis state always divides (the
    mesh is built from a divisor of n), so a non-divisible leaf is
    per-cluster bookkeeping like the [4] stats vector, not row state.

    `leading` pins the rule to one axis length: only leaves whose dim 0
    EQUALS it are sharded (divisibility still required), everything else
    replicates.  The multiraft serving plane uses this for its [G, ...]
    group axis — a grouped tree can carry group-shared leaves (router
    tables, bootstrap configs) whose dim 0 is some multiple of the mesh
    size by coincidence, and sharding those on the group axis would hand
    each device the wrong slice of a shared table."""
    names = axis if isinstance(axis, tuple) else (axis,)
    size = 1
    for a in names:
        size *= mesh.shape[a]

    def _spec(leaf):
        if leading is not None and (not leaf.ndim
                                    or leaf.shape[0] != leading):
            return P()
        if leaf.ndim and leaf.shape[0] % size == 0:
            return row_spec(leaf.ndim, axis)
        return P()
    return jax.tree.map(
        lambda leaf: NamedSharding(mesh, _spec(leaf)), tree)


def shard_rows(tree, mesh: Mesh, axis=MANAGER_AXIS, leading=None):
    """device_put a pytree with row-major sharding over the mesh."""
    return jax.device_put(tree, state_shardings(mesh, tree, axis, leading))

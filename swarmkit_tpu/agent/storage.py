"""Worker-local task persistence.

Reference: agent/storage.go — a boltdb file with per-task buckets holding the
task data, its latest status, and an "assigned" flag, so a restarted worker
can reconcile running work against fresh assignments.  Re-expressed over
sqlite3 (in this image; boltdb is Go-only): one table, same three facts.
"""

from __future__ import annotations

import json
import sqlite3
from typing import Iterable, Optional

from swarmkit_tpu.api import Task, TaskStatus


class TaskDB:
    def __init__(self, path: str = ":memory:") -> None:
        if path != ":memory:":
            import os

            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._db = sqlite3.connect(path)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS tasks ("
            " id TEXT PRIMARY KEY,"
            " data TEXT NOT NULL,"
            " status TEXT,"
            " assigned INTEGER NOT NULL DEFAULT 0)")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS meta ("
            " key TEXT PRIMARY KEY, value TEXT NOT NULL)")
        self._db.commit()

    def close(self) -> None:
        self._db.close()

    # ------------------------------------------------------------------
    def put_task(self, task) -> None:
        """reference: PutTask storage.go — stores spec-side task data."""
        self._db.execute(
            "INSERT INTO tasks (id, data, assigned) VALUES (?, ?, 0)"
            " ON CONFLICT(id) DO UPDATE SET data = excluded.data",
            (task.id, json.dumps(task.to_dict())))
        self._db.commit()

    def get_task(self, task_id: str) -> Optional[Task]:
        row = self._db.execute(
            "SELECT data FROM tasks WHERE id = ?", (task_id,)).fetchone()
        if row is None:
            return None
        return Task.from_dict(json.loads(row[0]))

    def delete_task(self, task_id: str) -> None:
        self._db.execute("DELETE FROM tasks WHERE id = ?", (task_id,))
        self._db.commit()

    def put_task_status(self, task_id: str, status: TaskStatus) -> None:
        self._db.execute(
            "UPDATE tasks SET status = ? WHERE id = ?",
            (json.dumps(status.to_dict()), task_id))
        self._db.commit()

    def get_task_status(self, task_id: str) -> Optional[TaskStatus]:
        row = self._db.execute(
            "SELECT status FROM tasks WHERE id = ?", (task_id,)).fetchone()
        if row is None or row[0] is None:
            return None
        return TaskStatus.from_dict(json.loads(row[0]))

    def set_task_assignment(self, task_id: str, assigned: bool) -> None:
        self._db.execute(
            "UPDATE tasks SET assigned = ? WHERE id = ?",
            (1 if assigned else 0, task_id))
        self._db.commit()

    def task_assigned(self, task_id: str) -> bool:
        row = self._db.execute(
            "SELECT assigned FROM tasks WHERE id = ?", (task_id,)).fetchone()
        return bool(row and row[0])

    def put_node(self, node) -> None:
        """Persist the last-known node object so a restarted worker can
        expand task templates before the first session message arrives."""
        self._db.execute(
            "INSERT INTO meta (key, value) VALUES ('node', ?)"
            " ON CONFLICT(key) DO UPDATE SET value = excluded.value",
            (json.dumps(node.to_dict()),))
        self._db.commit()

    def get_node(self):
        from swarmkit_tpu.api import Node

        row = self._db.execute(
            "SELECT value FROM meta WHERE key = 'node'").fetchone()
        if row is None:
            return None
        return Node.from_dict(json.loads(row[0]))

    def walk(self) -> Iterable[tuple[Task, Optional[TaskStatus], bool]]:
        for tid, data, status, assigned in self._db.execute(
                "SELECT id, data, status, assigned FROM tasks ORDER BY id"):
            t = Task.from_dict(json.loads(data))
            st = TaskStatus.from_dict(json.loads(status)) if status else None
            yield t, st, bool(assigned)

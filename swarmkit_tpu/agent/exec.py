"""The pluggable runtime seam: Executor / Controller interfaces and the
task-state advancer.

Reference: agent/exec/executor.go:9-23 (Executor: Describe/Configure/
Controller/SetNetworkBootstrapKeys) and agent/exec/controller.go:17-46
(Controller FSM: Update/Prepare/Start/Wait/Shutdown/Terminate/Remove/Close)
plus the ``Do`` state-advancer in controller.go — one observable transition
per call so every step is reported to the dispatcher in order.
"""

from __future__ import annotations

from typing import Optional

from swarmkit_tpu.api import TaskState, TaskStatus
from swarmkit_tpu.api.types import NodeDescription


class TaskError(Exception):
    """Controller operation failed.  The terminal state is chosen by
    WHERE the failure occurred, not by the exception type (reference
    fatal() switch controller.go:210-221): before STARTING the task is
    REJECTED, from STARTING on it is FAILED."""


class TaskRejected(TaskError):
    """Semantic marker: the node cannot run this task at all.  Raised
    from update()/prepare() it lands as REJECTED via the same
    where-it-failed rule above (an escape from start()/wait() would be
    FAILED like any other error there)."""


class Controller:
    """Drives one task through its lifecycle (agent/exec/controller.go:17)."""

    async def update(self, task) -> None:
        """Absorb a changed task spec (most runtimes reject real changes)."""

    async def prepare(self) -> None:
        """Allocate runtime resources (pull image, create container…)."""

    async def start(self) -> None:
        """Start the workload."""

    async def wait(self) -> None:
        """Block until the workload exits; raise TaskError on failure."""

    async def shutdown(self) -> None:
        """Gracefully stop."""

    async def terminate(self) -> None:
        """Forcefully stop."""

    async def remove(self) -> None:
        """Remove all resources."""

    async def close(self) -> None:
        """Release the controller itself."""


class Executor:
    """Factory + node description provider (agent/exec/executor.go:9)."""

    async def describe(self) -> NodeDescription:
        raise NotImplementedError

    async def configure(self, node) -> None:
        """Absorb node object changes (labels, certificates...)."""

    async def controller(self, task) -> Controller:
        raise NotImplementedError

    async def set_network_bootstrap_keys(self, keys) -> None:
        pass


def _status(task, state: TaskState, message: str, now: float,
            err: Optional[Exception] = None) -> TaskStatus:
    st = task.status.copy()
    st.state = state
    st.message = message
    st.timestamp = now
    if err is not None:
        st.err = str(err)
    return st


async def do_task_state(task, controller: Controller, now: float
                        ) -> Optional[TaskStatus]:
    """Advance the task one observable state (reference: exec.Do
    controller.go).  Returns the new status, or None when terminal.

    The switch mirrors the reference exactly: ASSIGNED→ACCEPTED→PREPARING→
    (Prepare)→READY→STARTING→(Start)→RUNNING→(Wait)→COMPLETE/FAILED, with
    desired_state >= SHUTDOWN short-circuiting to Shutdown at any point.
    """
    state = task.status.state
    if state >= TaskState.COMPLETE:
        return None  # terminal; nothing to do

    if task.desired_state in (TaskState.SHUTDOWN, TaskState.REMOVE):
        try:
            await controller.shutdown()
        except Exception:
            pass
        return _status(task, TaskState.SHUTDOWN, "shutdown", now)

    try:
        if state <= TaskState.ASSIGNED:
            return _status(task, TaskState.ACCEPTED, "accepted", now)
        if state == TaskState.ACCEPTED:
            return _status(task, TaskState.PREPARING, "preparing", now)
        if state == TaskState.PREPARING:
            await controller.prepare()
            return _status(task, TaskState.READY, "prepared", now)
        if state == TaskState.READY:
            # park here while desired_state <= READY: stop-first rolling
            # updates create replacements at desired READY and only promote
            # them to RUNNING once the old task is down (reference: exec.Do
            # gates on desired state; update.py:166-184 relies on it)
            if task.desired_state <= TaskState.READY:
                return None
            return _status(task, TaskState.STARTING, "starting", now)
        if state == TaskState.STARTING:
            await controller.start()
            return _status(task, TaskState.RUNNING, "started", now)
        if state == TaskState.RUNNING:
            await controller.wait()
            return _status(task, TaskState.COMPLETE, "finished", now)
    except Exception as e:
        # The reference's fatal() switch (controller.go:210-221) picks the
        # terminal state by WHERE the failure was encountered: before
        # STARTING the node never ran the workload, so the task is
        # REJECTED; from STARTING on it FAILED.  (Tasks.tla's agent table
        # encodes the same shape: rejected from assigned..starting, failed
        # from running.)
        if state < TaskState.STARTING:
            return _status(task, TaskState.REJECTED, "rejected", now, e)
        return _status(task, TaskState.FAILED, "failed", now, e)
    return None

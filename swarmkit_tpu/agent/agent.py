"""The agent event loop: owns one dispatcher session at a time, feeds the
worker, reports statuses, rebuilds the session with backoff on failure.

Reference: agent/agent.go — ``run`` (:179) is the select loop over session
messages / assignment sets / errors; handleSessionMessage (:393) absorbs
node updates, manager lists and bootstrap keys; session rebuild backoff at
agent.go:338-341 (max 8 s).
"""

from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass, field
from typing import Callable, Optional

from swarmkit_tpu.agent.exec import Executor
from swarmkit_tpu.agent.reporter import StatusReporter
from swarmkit_tpu.agent.session import Session
from swarmkit_tpu.agent.storage import TaskDB
from swarmkit_tpu.agent.worker import Worker
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.agent")

MAX_SESSION_BACKOFF = 8.0   # reference: agent.go:338-341


@dataclass
class AgentConfig:
    node_id: str
    executor: Executor
    # the connection-broker seam: returns a Dispatcher-shaped client
    # (reference: agent/config.go ConnBroker)
    connect: Callable[[], object] = None
    # LogBroker-shaped client factory (listen_subscriptions/publish_logs);
    # None disables the agent-side log pipeline (reference:
    # agent/session.go:249 logSubscriptions over the same connection)
    connect_logs: Callable[[], object] = None
    addr: str = ""
    db_path: str = ":memory:"
    clock: Optional[Clock] = None
    # notification hooks (reference: Agent node/manager update channels)
    on_node_change: Optional[Callable[[object], None]] = None
    on_managers_change: Optional[Callable[[list], None]] = None


class Agent:
    def __init__(self, config: AgentConfig) -> None:
        self.config = config
        self.clock = config.clock or SystemClock()
        self.worker = Worker(config.executor, TaskDB(config.db_path),
                             clock=self.clock)
        self.reporter: Optional[StatusReporter] = None
        self.session: Optional[Session] = None
        self.managers: list = []
        self._runner: Optional[asyncio.Task] = None
        self._running = False
        self._established = False
        self._ready = asyncio.Event()
        self._rng = random.Random()

    # ------------------------------------------------------------------
    async def start(self) -> None:
        await self.worker.init()
        self._running = True
        self._runner = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._running = False
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except (asyncio.CancelledError, Exception):
                pass
            self._runner = None
        await self._teardown_session()
        await self.worker.close()

    async def ready(self, timeout: float = 10.0) -> None:
        await asyncio.wait_for(self._ready.wait(), timeout)

    # ------------------------------------------------------------------
    async def _run(self) -> None:
        backoff = 0.0
        while self._running:
            self._established = False
            try:
                await self._run_session()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.info("agent %s: session failed: %s",
                         self.config.node_id, e)
            finally:
                await self._teardown_session()
            if not self._running:
                return
            self._ready.clear()
            if self._established:
                # a session that registered successfully resets the backoff
                # (reference: agent.go — registered resets the timer)
                backoff = 0.0
            if backoff:
                await self.clock.sleep(backoff * self._rng.uniform(0.5, 1.0))
            backoff = min(MAX_SESSION_BACKOFF, (backoff * 2) or 0.05)

    async def _run_session(self) -> None:
        description = await self.config.executor.describe()
        client = self.config.connect()
        session = Session(client, self.config.node_id, description,
                          self.config.addr, self.clock)
        await session.start()
        self.session = session

        reporter = StatusReporter(session.send_task_statuses,
                                  clock=self.clock)
        reporter.start()
        self.reporter = reporter
        self.worker.set_reporter(reporter.update_status)
        self._established = True
        self._ready.set()

        # agent side of `service logs`: subscription intake + publishers,
        # tied to the session lifetime (reference: session.go:249-273)
        self.log_loop = None
        log_buffer = getattr(self.config.executor, "logs", None)
        if self.config.connect_logs is not None and log_buffer is not None:
            from swarmkit_tpu.agent.logs import LogSubscriptionLoop

            try:
                self.log_loop = LogSubscriptionLoop(
                    self.config.connect_logs(), self.worker, log_buffer,
                    self.config.node_id)
                self.log_loop.start()
            except Exception:
                log.exception("log subscription loop failed to start")

        # absorb the registration message (node object = template context)
        # BEFORE any assignment can race it
        if not session.session_msgs.empty():
            await self._handle_session_message(
                session.session_msgs.get_nowait())

        smsg = asyncio.ensure_future(session.session_msgs.get())
        amsg = asyncio.ensure_future(session.assignments.get())
        emsg = asyncio.ensure_future(session.errs.get())
        try:
            while self._running:
                done, _ = await asyncio.wait(
                    {smsg, amsg, emsg}, return_when=asyncio.FIRST_COMPLETED)
                if emsg in done:
                    raise emsg.result()
                if smsg in done:
                    await self._handle_session_message(smsg.result())
                    smsg = asyncio.ensure_future(session.session_msgs.get())
                if amsg in done:
                    await self.worker.assign(amsg.result())
                    amsg = asyncio.ensure_future(session.assignments.get())
        finally:
            for f in (smsg, amsg, emsg):
                f.cancel()

    async def _handle_session_message(self, msg) -> None:
        """reference: handleSessionMessage agent.go:393."""
        if msg.node is not None:
            self.worker.set_node(msg.node)   # template-expansion context
            try:
                await self.config.executor.configure(msg.node)
            except Exception:
                log.exception("executor.configure failed")
            if self.config.on_node_change is not None:
                self.config.on_node_change(msg.node)
        if msg.managers != self.managers:
            self.managers = list(msg.managers)
            if self.config.on_managers_change is not None:
                self.config.on_managers_change(self.managers)
        if msg.network_bootstrap_keys:
            try:
                await self.config.executor.set_network_bootstrap_keys(
                    msg.network_bootstrap_keys)
            except Exception:
                log.exception("setting network bootstrap keys failed")

    async def _teardown_session(self) -> None:
        if getattr(self, "log_loop", None) is not None:
            await self.log_loop.stop()
            self.log_loop = None
        self.worker.set_reporter(None)
        if self.reporter is not None:
            await self.reporter.close()
            self.reporter = None
        if self.session is not None:
            await self.session.close()
            self.session = None

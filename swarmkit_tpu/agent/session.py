"""One dispatcher session: registration stream, heartbeats, assignments,
status updates.

Reference: agent/session.go — ``session`` (:31) opens the Session stream
(start :120), then runs heartbeat (:176), watch/assignments (:282) and
status-update (:393) machinery against one manager connection; any error
closes the whole session and the agent rebuilds it with backoff.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import TaskStatus
from swarmkit_tpu.utils.clock import Clock

log = logging.getLogger("swarmkit_tpu.agent.session")


class SessionError(Exception):
    pass


class Session:
    def __init__(self, client, node_id: str, description, addr: str,
                 clock: Clock) -> None:
        self.client = client          # Dispatcher-shaped (local or remote)
        self.node_id = node_id
        self.description = description
        self.addr = addr
        self.clock = clock
        self.session_id: str = ""
        self.session_msgs: asyncio.Queue = asyncio.Queue()
        self.assignments: asyncio.Queue = asyncio.Queue()
        self.errs: asyncio.Queue = asyncio.Queue()
        self._tasks: list[asyncio.Task] = []
        self._closed = False

    async def start(self) -> None:
        """Open the Session stream and wait for the first message (which
        carries the session id), then start heartbeat + assignments."""
        self._stream = self.client.session(
            self.node_id, self.description, addr=self.addr)
        first = await self._stream.__anext__()
        self.session_id = first.session_id
        await self.session_msgs.put(first)
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._consume_session()),
            loop.create_task(self._heartbeat()),
            loop.create_task(self._consume_assignments()),
        ]

    async def close(self) -> None:
        self._closed = True
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks = []

    def _fail(self, err: Exception) -> None:
        if not self._closed:
            self.errs.put_nowait(err)

    # ------------------------------------------------------------------
    async def _consume_session(self) -> None:
        try:
            async for msg in self._stream:
                await self.session_msgs.put(msg)
            self._fail(SessionError("session stream closed"))
        except asyncio.CancelledError:
            pass
        except Exception as e:
            self._fail(e)

    async def _heartbeat(self) -> None:
        period = 1.0
        try:
            while not self._closed:
                await self.clock.sleep(period)
                resp = await self.client.heartbeat(self.node_id,
                                                   self.session_id)
                period = resp.period
        except asyncio.CancelledError:
            pass
        except Exception as e:
            self._fail(e)

    async def _consume_assignments(self) -> None:
        try:
            async for msg in self.client.assignments(self.node_id,
                                                     self.session_id):
                await self.assignments.put(msg)
            self._fail(SessionError("assignments stream closed"))
        except asyncio.CancelledError:
            pass
        except Exception as e:
            self._fail(e)

    # ------------------------------------------------------------------
    async def send_task_statuses(self, updates: list[tuple[str, TaskStatus]]
                                 ) -> None:
        await self.client.update_task_status(self.node_id, self.session_id,
                                             updates)

"""Fake runtime for tests and the in-process integration harness.

Reference: agent/testutils/fakes.go — TestExecutor (:24) instantly "runs"
tasks; its controllers succeed at every step and block in Wait until shut
down, so orchestration logic can be exercised with no real containers.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from swarmkit_tpu.agent.exec import Controller, Executor, TaskError
from swarmkit_tpu.api.types import NodeDescription, NodeResources, Platform


class TestController(Controller):
    def __init__(self, task, executor: "TestExecutor") -> None:
        self.task = task
        self.executor = executor
        self.exit_evt = asyncio.Event()
        self.fail_msg: Optional[str] = None

    def write_log(self, line: str) -> None:
        """Test hook: emit a task output line into the executor's buffer."""
        import time

        from swarmkit_tpu.manager.logbroker import LogStream

        self.executor.logs.publish(
            self.task.id, LogStream.STDOUT, line.encode(),
            service_id=self.task.service_id, node_id=self.task.node_id,
            timestamp=time.time())

    async def prepare(self) -> None:
        if self.executor.fail_prepare:
            raise TaskError("prepare failed (test)")
        # resolve referenced secrets/configs through the per-task templated
        # view (template/getter.go) so tests can assert expanded payloads
        deps = getattr(self.executor, "dependencies", None)
        self.resolved_secrets: dict[str, bytes] = {}
        self.resolved_configs: dict[str, bytes] = {}
        if deps is not None and self.task.spec.container is not None:
            view = deps.templated(self.task,
                                  (self.executor.configured_nodes or
                                   [None])[-1])
            for ref in self.task.spec.container.secrets:
                item = view.secrets.get(ref.secret_id)
                if item is not None:
                    self.resolved_secrets[ref.secret_name] = item.spec.data
            for ref in self.task.spec.container.configs:
                item = view.configs.get(ref.config_id)
                if item is not None:
                    self.resolved_configs[ref.config_name] = item.spec.data

    async def start(self) -> None:
        if self.executor.fail_start:
            raise TaskError("start failed (test)")
        self.write_log("started")

    async def wait(self) -> None:
        await self.exit_evt.wait()
        if self.fail_msg:
            raise TaskError(self.fail_msg)

    async def shutdown(self) -> None:
        self.exit_evt.set()

    async def terminate(self) -> None:
        self.exit_evt.set()

    # test hooks ---------------------------------------------------------
    def exit(self, fail: Optional[str] = None) -> None:
        """Make the fake workload exit (cleanly or with an error)."""
        self.fail_msg = fail
        self.exit_evt.set()


class TestExecutor(Executor):
    __test__ = False  # not a pytest class despite the name

    def __init__(self, hostname: str = "testhost",
                 cpus: int = 4_000_000_000, memory: int = 8 << 30) -> None:
        self.hostname = hostname
        self.cpus = cpus
        self.memory = memory
        from swarmkit_tpu.agent.logs import TaskLogBuffer

        self.controllers: dict[str, TestController] = {}
        self.logs = TaskLogBuffer()
        self.fail_prepare = False
        self.fail_start = False
        self.configured_nodes: list = []
        self.bootstrap_keys: list = []

    async def describe(self) -> NodeDescription:
        return NodeDescription(
            hostname=self.hostname,
            platform=Platform(architecture="x86_64", os="linux"),
            resources=NodeResources(nano_cpus=self.cpus,
                                    memory_bytes=self.memory))

    async def configure(self, node) -> None:
        self.configured_nodes.append(node)

    async def controller(self, task) -> Controller:
        c = TestController(task, self)
        self.controllers[task.id] = c
        return c

    async def set_network_bootstrap_keys(self, keys) -> None:
        self.bootstrap_keys = list(keys)

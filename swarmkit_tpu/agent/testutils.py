"""Fake runtime for tests and the in-process integration harness.

Reference: agent/testutils/fakes.go — TestExecutor (:24) instantly "runs"
tasks; its controllers succeed at every step and block in Wait until shut
down, so orchestration logic can be exercised with no real containers.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from swarmkit_tpu.agent.exec import Controller, Executor, TaskError
from swarmkit_tpu.api.types import NodeDescription, NodeResources, Platform


class TestController(Controller):
    def __init__(self, task, executor: "TestExecutor") -> None:
        self.task = task
        self.executor = executor
        self.exit_evt = asyncio.Event()
        self.fail_msg: Optional[str] = None

    async def prepare(self) -> None:
        if self.executor.fail_prepare:
            raise TaskError("prepare failed (test)")

    async def start(self) -> None:
        if self.executor.fail_start:
            raise TaskError("start failed (test)")

    async def wait(self) -> None:
        await self.exit_evt.wait()
        if self.fail_msg:
            raise TaskError(self.fail_msg)

    async def shutdown(self) -> None:
        self.exit_evt.set()

    async def terminate(self) -> None:
        self.exit_evt.set()

    # test hooks ---------------------------------------------------------
    def exit(self, fail: Optional[str] = None) -> None:
        """Make the fake workload exit (cleanly or with an error)."""
        self.fail_msg = fail
        self.exit_evt.set()


class TestExecutor(Executor):
    __test__ = False  # not a pytest class despite the name

    def __init__(self, hostname: str = "testhost",
                 cpus: int = 4_000_000_000, memory: int = 8 << 30) -> None:
        self.hostname = hostname
        self.cpus = cpus
        self.memory = memory
        self.controllers: dict[str, TestController] = {}
        self.fail_prepare = False
        self.fail_start = False
        self.configured_nodes: list = []
        self.bootstrap_keys: list = []

    async def describe(self) -> NodeDescription:
        return NodeDescription(
            hostname=self.hostname,
            platform=Platform(architecture="x86_64", os="linux"),
            resources=NodeResources(nano_cpus=self.cpus,
                                    memory_bytes=self.memory))

    async def configure(self, node) -> None:
        self.configured_nodes.append(node)

    async def controller(self, task) -> Controller:
        c = TestController(task, self)
        self.controllers[task.id] = c
        return c

    async def set_network_bootstrap_keys(self, keys) -> None:
        self.bootstrap_keys = list(keys)

"""Status-report queue: dedup + retry of task status updates to the manager.

Reference: agent/reporter.go — statusReporter keeps the freshest status per
task id and a single goroutine drains the map via UpdateTaskStatus, putting
statuses back on failure so they retry on the next wakeup.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Optional

from swarmkit_tpu.api import TaskStatus
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.agent.reporter")


class StatusReporter:
    def __init__(self,
                 send: Callable[[list[tuple[str, TaskStatus]]], Awaitable[None]],
                 retry_delay: float = 0.1,
                 clock: Optional[Clock] = None) -> None:
        self._send = send
        self._retry_delay = retry_delay
        self._clock = clock or SystemClock()
        self._statuses: dict[str, TaskStatus] = {}
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._closed = False

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def close(self) -> None:
        self._closed = True
        self._wake.set()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    def update_status(self, task_id: str, status: TaskStatus) -> None:
        """Keep only the freshest status per task (reporter.go dedup)."""
        old = self._statuses.get(task_id)
        if old is not None and old.state > status.state:
            return
        self._statuses[task_id] = status
        self._wake.set()

    async def _run(self) -> None:
        try:
            while not self._closed:
                await self._wake.wait()
                self._wake.clear()
                while self._statuses and not self._closed:
                    batch, self._statuses = self._statuses, {}
                    try:
                        await self._send(list(batch.items()))
                    except Exception as e:
                        log.debug("status report failed, will retry: %s", e)
                        # put back anything not overwritten meanwhile
                        for tid, st in batch.items():
                            cur = self._statuses.get(tid)
                            if cur is None or cur.state < st.state:
                                self._statuses[tid] = st
                        await self._clock.sleep(self._retry_delay)
        except asyncio.CancelledError:
            pass

"""Per-task driver: one asyncio task advancing one Controller through the
FSM with ordered status reporting.

Reference: agent/task.go taskManager (:16, run :77) — a goroutine per task
calling exec.Do in a loop, absorbing task updates (desired-state flips) via
``update``, and pushing every observed status to the reporter.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Optional

from swarmkit_tpu.agent.exec import Controller, do_task_state
from swarmkit_tpu.api import TaskState
from swarmkit_tpu.utils.clock import Clock

log = logging.getLogger("swarmkit_tpu.agent.task")


class TaskManager:
    def __init__(self, task, controller: Controller,
                 report: Callable[[str, object], Awaitable[None]],
                 clock: Clock) -> None:
        self.task = task.copy()
        self.controller = controller
        self.report = report
        self.clock = clock
        self._update_evt = asyncio.Event()
        self._runner: Optional[asyncio.Task] = None
        self._closed = False

    def start(self) -> None:
        self._runner = asyncio.get_running_loop().create_task(self._run())

    async def update(self, task) -> None:
        """Absorb a task update (reference: taskManager.Update task.go:38)."""
        self.task = task.copy()
        try:
            await self.controller.update(task)
        except Exception:
            pass
        self._update_evt.set()

    async def close(self) -> None:
        """Stop driving; does NOT shut the workload down (the worker decides
        whether that's wanted via desired_state)."""
        self._closed = True
        self._update_evt.set()
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except (asyncio.CancelledError, Exception):
                pass
            self._runner = None
        try:
            await self.controller.close()
        except Exception:
            pass

    @property
    def done(self) -> bool:
        return self.task.status.state >= TaskState.COMPLETE

    async def _run(self) -> None:
        try:
            while not self._closed:
                # race the FSM step against task updates so a desired-state
                # flip interrupts a blocked Wait (reference: task.go cancels
                # the in-flight Do when an update arrives)
                step = asyncio.ensure_future(do_task_state(
                    self.task, self.controller, self.clock.now()))
                upd = asyncio.ensure_future(self._update_evt.wait())
                try:
                    done, _ = await asyncio.wait(
                        {step, upd}, return_when=asyncio.FIRST_COMPLETED)
                except asyncio.CancelledError:
                    # close() cancelled the runner mid-wait: reap the
                    # in-flight FSM step too or it leaks (a blocked
                    # controller.wait() outlives the loop otherwise) —
                    # and AWAIT it so its unwind finishes before close()
                    # proceeds to controller.close()
                    step.cancel()
                    upd.cancel()
                    try:
                        await step
                    except (asyncio.CancelledError, Exception):
                        pass
                    raise
                if step in done:
                    upd.cancel()
                    status = step.result()
                    if status is None:
                        # terminal: park until an update changes the picture
                        await self._update_evt.wait()
                        self._update_evt.clear()
                        continue
                    self.task.status = status
                    await self.report(self.task.id, status)
                else:
                    step.cancel()
                    try:
                        await step
                    except (asyncio.CancelledError, Exception):
                        pass
                    self._update_evt.clear()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("task %s manager crashed", self.task.id)

"""TPU executor: tasks are real JAX programs compiled and executed on the
local device(s).

This is the framework's native analog of the reference's Docker executor
(agent/exec/dockerapi/controller.go:1-687 — Prepare pulls the image and
creates the container, Start runs it, Wait blocks on exit). Here the
runtime is XLA: Prepare RESOLVES the named program and AOT-compiles it
(compile errors fail the task at PREPARING, like a bad image pull), Start
launches the compiled executable, Wait completes when the device result is
ready. Shutdown/Terminate cancel the host-side wait (a dispatched XLA
program itself is not preemptible, matching a container runtime's kill
granularity at best).

Task programs are named in the container image field with a ``tpu://``
scheme: ``tpu://matmul`` with parameters from ContainerSpec.args (``k=v``)
and env (``K=V``), e.g.::

    ContainerSpec(image="tpu://matmul", args=["n=512", "steps=8"])

The registry ships MXU-friendly builtins (bf16 matmul chains, elementwise
axpy, scan spins) and accepts registrations from embedding applications.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import Callable, Optional

from swarmkit_tpu.agent.exec import (
    Controller, Executor, TaskError, TaskRejected,
)
from swarmkit_tpu.api.types import (
    EngineDescription, NodeDescription, NodeResources, Platform,
)

log = logging.getLogger("swarmkit_tpu.agent.tpu")

SCHEME = "tpu://"

_backend_checked = False
_backend_lock = threading.Lock()


def ensure_jax_backend() -> None:
    """Fall back to the CPU backend when the configured platform cannot
    initialize (e.g. JAX_PLATFORMS names a TPU plugin that is not on
    PYTHONPATH in this process).  Without this every task the executor
    touches fails at PREPARING even though a working CPU backend exists.
    Serialized: callers run on executor threads, and concurrent first-time
    backend init + config mutation is not thread-safe in jax."""
    global _backend_checked
    with _backend_lock:
        if _backend_checked:
            return
        import jax

        try:
            jax.devices()
        except Exception as e:
            log.warning("jax platform init failed (%s); falling back to cpu",
                        e)
            try:
                jax.config.update("jax_platforms", "cpu")
                jax.devices()
            except Exception:
                log.exception("cpu fallback failed too; tasks will fail")
        _backend_checked = True

# name -> builder(params: dict[str, str]) -> (fn, example_args)
PROGRAMS: dict[str, Callable] = {}


def register_program(name: str, builder: Callable) -> None:
    PROGRAMS[name] = builder


def _builtin_matmul(params: dict):
    """bf16 matmul chain — keeps the MXU busy for `steps` iterations."""
    import jax
    import jax.numpy as jnp

    n = int(params.get("n", 256))
    steps = int(params.get("steps", 4))
    key = jax.random.PRNGKey(int(params.get("seed", 0)))
    a = jax.random.normal(key, (n, n), dtype=jnp.bfloat16)

    def fn(x):
        def body(carry, _):
            y = (carry @ a).astype(jnp.bfloat16)
            # renormalize so the chain neither explodes nor vanishes
            y = y / jnp.maximum(
                jnp.sqrt(jnp.mean(jnp.square(y.astype(jnp.float32)))),
                1e-6).astype(jnp.bfloat16)
            return y, ()
        out, _ = jax.lax.scan(body, x, None, length=steps)
        return jnp.sum(out.astype(jnp.float32))

    return fn, (a,)


def _builtin_axpy(params: dict):
    import jax.numpy as jnp

    n = int(params.get("n", 1 << 16))
    alpha = float(params.get("alpha", 2.0))

    def fn(x, y):
        return jnp.sum(alpha * x + y)

    x = jnp.arange(n, dtype=jnp.float32)
    return fn, (x, x * 0.5)


def _builtin_pmatmul(params: dict):
    """Sharded bf16 matmul chain over ALL local devices: the batch axis is
    sharded on a 1-D mesh, each step does a local matmul on the MXU plus a
    cross-device `psum` of activation norms over ICI (shard_map + jax.lax
    collectives — the multi-chip execution path of a task program).  On a
    single device this degenerates to `matmul` with an extra reduction."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # pre-0.5 jax keeps it under experimental
        from jax.experimental.shard_map import shard_map

    n = int(params.get("n", 256))
    steps = int(params.get("steps", 4))
    batch = int(params.get("batch", 8))
    devices = jax.devices()
    d = len(devices)
    while d > 1 and batch % d != 0:
        d -= 1
    mesh = Mesh(devices[:d], axis_names=("batch",))

    key = jax.random.PRNGKey(int(params.get("seed", 0)))
    a = jax.random.normal(key, (n, n), dtype=jnp.bfloat16)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, n, n),
                          dtype=jnp.bfloat16)
    x = jax.device_put(x, NamedSharding(mesh, P("batch")))

    def local_step(xs):
        def body(carry, _):
            y = (carry @ a).astype(jnp.bfloat16)
            # cross-device normalization: psum of squared norms over ICI
            sq = jnp.mean(jnp.square(y.astype(jnp.float32)))
            total = jax.lax.psum(sq, "batch")
            y = y / jnp.maximum(jnp.sqrt(total / d), 1e-6).astype(jnp.bfloat16)
            return y, ()
        out, _ = jax.lax.scan(body, xs, None, length=steps)
        # replicated scalar result: psum the local contributions
        return jax.lax.psum(jnp.sum(out.astype(jnp.float32)), "batch")

    fn = shard_map(local_step, mesh=mesh, in_specs=P("batch"),
                   out_specs=P())
    return fn, (x,)


def _builtin_pallas_matmul(params: dict):
    """Hand-tiled Pallas matmul chain (MXU tiles, f32 VMEM accumulation) —
    the hand-optimized twin of ``tpu://matmul``; kernels in
    `parallel/pallas_ops.py`, interpreted off-TPU so the image runs on any
    node."""
    import jax
    import jax.numpy as jnp

    from swarmkit_tpu.parallel import pallas_ops
    from swarmkit_tpu.parallel.pallas_ops import _LANE, _on_tpu

    n = int(params.get("n", 256))
    steps = int(params.get("steps", 4))
    if "tile" in params:
        tile = int(params["tile"])
        if tile <= 0:
            raise TaskRejected(f"tile={tile} must be positive")
    else:
        # largest MXU-aligned divisor of n, falling back to one whole-array
        # block (always valid in interpret mode; on TPU the lane check
        # below rejects unalignable sizes cleanly)
        tile = next((t for t in (256, _LANE) if n % t == 0), n)
    if n <= 0 or n % tile:
        raise TaskRejected(f"n={n} must be positive and a multiple of "
                           f"tile={tile}")
    if _on_tpu() and (tile % _LANE or n % _LANE):
        raise TaskRejected(
            f"on TPU, n and tile must be multiples of {_LANE} "
            f"(got n={n}, tile={tile}) — Mosaic lane tiling")
    key = jax.random.PRNGKey(int(params.get("seed", 0)))
    a = jax.random.normal(key, (n, n), dtype=jnp.bfloat16)

    def fn(x):
        out = pallas_ops.matmul_chain(x, a, steps, tile=tile)
        return jnp.sum(out.astype(jnp.float32))

    return fn, (a,)


def _builtin_spin(params: dict):
    """Fixed-length device scan — a long-running task for lifecycle tests."""
    import jax
    import jax.numpy as jnp

    iters = int(params.get("iters", 1000))

    def fn(x):
        def body(c, _):
            return c * 1.000001 + 1e-7, ()
        out, _ = jax.lax.scan(body, x, None, length=iters)
        return out

    return fn, (jnp.float32(1.0),)


register_program("matmul", _builtin_matmul)
register_program("pallas_matmul", _builtin_pallas_matmul)
register_program("pmatmul", _builtin_pmatmul)
register_program("axpy", _builtin_axpy)
register_program("spin", _builtin_spin)


def parse_program(container) -> tuple[str, dict]:
    """(program name, params) from a ContainerSpec, or TaskRejected."""
    image = container.image or ""
    if not image.startswith(SCHEME):
        raise TaskRejected(
            f"image {image!r} is not a {SCHEME} program — this node runs "
            "the TPU executor")
    name = image[len(SCHEME):].strip("/")
    params: dict[str, str] = {}
    for kv in [*container.env, *container.args]:
        if "=" in kv:
            k, v = kv.split("=", 1)
            params[k.lower()] = v
    return name, params


class TpuController(Controller):
    """One task = one compiled XLA program (reference FSM:
    dockerapi/controller.go; Prepare/Start/Wait mapping in module doc).
    Lifecycle + result lines go to the executor's TaskLogBuffer — the
    stdout-equivalent the agent's log publishers serve to `service logs`
    (reference: the Docker controller's log-driver read-back)."""

    def __init__(self, task, executor: "TpuExecutor") -> None:
        self.task = task
        self.executor = executor
        self._compiled = None
        self._args = None
        self._run_fut: Optional[asyncio.Future] = None
        self.result = None

    def _log(self, line: str, stream=None) -> None:
        import time

        from swarmkit_tpu.manager.logbroker import LogStream

        self.executor.logs.publish(
            self.task.id, stream or LogStream.STDOUT,
            line.encode(), service_id=self.task.service_id,
            node_id=self.task.node_id, timestamp=time.time())

    async def update(self, task) -> None:
        self.task = task  # spec changes beyond desired-state are rejected
        # upstream by the orchestrator creating a replacement task

    def _dep_params(self) -> dict:
        """k=v lines from referenced secret/config payloads become program
        parameters (the runtime's analog of mounting secret files; payloads
        are template-expanded per task, template/getter.go)."""
        deps = getattr(self.executor, "dependencies", None)
        c = self.task.spec.container
        if deps is None or c is None or (not c.secrets and not c.configs):
            return {}
        view = deps.templated(self.task, self.executor._node)
        out: dict[str, str] = {}
        for ref, store in ([(r, view.secrets) for r in c.secrets]
                           + [(r, view.configs) for r in c.configs]):
            dep_id = getattr(ref, "secret_id", "")                 or getattr(ref, "config_id", "")
            item = store.get(dep_id)
            if item is None:
                raise TaskError(f"missing dependency {dep_id!r}")
            for line in item.spec.data.decode("utf-8",
                                              "replace").splitlines():
                if "=" in line:
                    k, v = line.split("=", 1)
                    out[k.strip().lower()] = v.strip()
        return out

    async def prepare(self) -> None:
        name, params = parse_program(self.task.spec.container)
        public_params = dict(params)   # loggable: image args/env only
        dep = self._dep_params()
        params.update(dep)
        builder = PROGRAMS.get(name)
        if builder is None:
            raise TaskRejected(f"unknown TPU program {name!r} "
                               f"(have: {sorted(PROGRAMS)})")
        loop = asyncio.get_running_loop()

        def build_and_compile():
            import jax

            ensure_jax_backend()
            fn, args = builder(params)
            return jax.jit(fn).lower(*args).compile(), args

        try:
            self._compiled, self._args = await loop.run_in_executor(
                None, build_and_compile)
            # dependency-sourced params are SECRET material: log their
            # names only, never values (they would be served cluster-wide
            # through `service logs`)
            shown = [f"{k}={v}" for k, v in public_params.items()]
            shown += [f"{k}=<from-dependency>" for k in dep]
            self._log(f"compiled tpu://{name} {' '.join(shown)}")
        except TaskRejected:
            raise
        except Exception as e:
            from swarmkit_tpu.manager.logbroker import LogStream

            self._log(f"compilation of {name!r} failed: {e}",
                      LogStream.STDERR)
            raise TaskError(f"compilation of {name!r} failed: {e}") from e

    async def start(self) -> None:
        if self._compiled is None:
            raise TaskError("start before prepare")
        loop = asyncio.get_running_loop()

        def run():
            import jax

            out = self._compiled(*self._args)
            jax.block_until_ready(out)
            return out

        self._run_fut = loop.run_in_executor(None, run)
        self._log("started on device")

    async def wait(self) -> None:
        if self._run_fut is None:
            raise TaskError("wait before start")
        try:
            self.result = await asyncio.shield(self._run_fut)
            self._log(f"result: {self.result}")
            self._log("task complete")
        except asyncio.CancelledError:
            raise TaskError("task cancelled")
        except Exception as e:
            from swarmkit_tpu.manager.logbroker import LogStream

            self._log(f"device execution failed: {e}", LogStream.STDERR)
            raise TaskError(f"device execution failed: {e}") from e

    async def shutdown(self) -> None:
        if self._run_fut is not None and not self._run_fut.done():
            self._run_fut.cancel()

    async def terminate(self) -> None:
        await self.shutdown()

    async def remove(self) -> None:
        self._compiled = None
        self._args = None

    async def close(self) -> None:
        await self.remove()


class TpuExecutor(Executor):
    """Executor advertising the local JAX devices; reference:
    dockerapi/executor.go Describe + Controller factory."""

    def __init__(self, hostname: str = "") -> None:
        from swarmkit_tpu.agent.logs import TaskLogBuffer

        self.hostname = hostname
        self._node = None
        self.logs = TaskLogBuffer()   # served via `service logs`

    def _devices(self):
        import jax

        ensure_jax_backend()
        try:
            return jax.devices()
        except Exception:
            return []

    async def describe(self) -> NodeDescription:
        devices = self._devices()
        platform = devices[0].platform if devices else "none"
        # Generic-resource key carries the REAL platform so a service
        # reserving tpu-chip never lands on a CPU/GPU node whose jax
        # backend merely enumerates some devices.
        return NodeDescription(
            hostname=self.hostname,
            platform=Platform(architecture=platform, os="xla"),
            engine=EngineDescription(
                engine_version=f"jax/{platform}",
                labels={"executor": "tpu"}),
            resources=NodeResources(
                generic={f"{platform}-chip": len(devices)} if devices
                else {},
                # named ids let the scheduler claim SPECIFIC chips per task
                # (reference: api/genericresource string sets)
                generic_named={f"{platform}-chip":
                               [str(d.id) for d in devices]} if devices
                else {}),
        )

    async def configure(self, node) -> None:
        self._node = node

    async def controller(self, task) -> TpuController:
        return TpuController(task, self)

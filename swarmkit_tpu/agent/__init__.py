from swarmkit_tpu.agent.agent import Agent, AgentConfig
from swarmkit_tpu.agent.exec import Controller, Executor, do_task_state
from swarmkit_tpu.agent.worker import Worker

__all__ = ["Agent", "AgentConfig", "Controller", "Executor", "Worker",
           "do_task_state"]

"""Worker-side secret/config stores.

Reference: agent/secrets/secrets.go, agent/configs/configs.go,
agent/dependency.go — in-memory maps fed by assignment changes, read by
controllers when materializing task filesystems/env.
"""

from __future__ import annotations

from typing import Optional


class _DepStore:
    def __init__(self) -> None:
        self._items: dict[str, object] = {}

    def get(self, dep_id: str) -> Optional[object]:
        return self._items.get(dep_id)

    def add(self, *items) -> None:
        for it in items:
            self._items[it.id] = it

    def remove(self, ids) -> None:
        for dep_id in ids:
            self._items.pop(dep_id, None)

    def reset(self) -> None:
        self._items = {}

    def __len__(self) -> int:
        return len(self._items)


class Secrets(_DepStore):
    """reference: agent/secrets/secrets.go:18."""


class Configs(_DepStore):
    """reference: agent/configs/configs.go:18."""


class Dependencies:
    """reference: agent/dependency.go dependencyManager."""

    def __init__(self) -> None:
        self.secrets = Secrets()
        self.configs = Configs()

    def templated(self, task, node=None) -> "TemplatedDependencies":
        """Per-task view whose gets expand templated payloads
        (reference: template/getter.go NewTemplatedDependencyGetter)."""
        return TemplatedDependencies(self, task, node)


class _TemplatedStore:
    def __init__(self, store: _DepStore, task, node) -> None:
        self._store = store
        self._task = task
        self._node = node

    def get(self, dep_id: str) -> Optional[object]:
        from swarmkit_tpu.template import expand_secret_spec

        item = self._store.get(dep_id)
        if item is None:
            return None
        return expand_secret_spec(item, self._task, self._node)

    def __len__(self) -> int:
        return len(self._store)


class TemplatedDependencies:
    """reference: template/getter.go templatedDependencyGetter."""

    def __init__(self, deps: Dependencies, task, node) -> None:
        self.secrets = _TemplatedStore(deps.secrets, task, node)
        self.configs = _TemplatedStore(deps.configs, task, node)

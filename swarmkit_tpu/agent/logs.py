"""Agent-side task log capture + subscription publishing.

Reference: the agent half of `service logs` — agent/session.go:249-273
(the ListenSubscriptions stream), agent/agent.go:207 (subscription
handling) and the log-driver read-back the Docker controller uses to
serve tails.  Here the runtime is the TPU executor, so workloads write
their stdout/stderr-equivalent lines into an in-memory per-task ring
(`TaskLogBuffer`), and a `SubscriptionPublisher` per active subscription
ships the buffered tail plus (in follow mode) live lines back through
PublishLogs.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from typing import Iterable, Optional

from swarmkit_tpu.manager.logbroker import LogContext, LogMessage, LogStream
from swarmkit_tpu.watch.queue import Queue

log = logging.getLogger("swarmkit_tpu.agent.logs")


async def _cancel_and_wait(task: asyncio.Task, timeout: float = 3.0) -> None:
    """Cancel `task` and wait BOUNDED for it to unwind.

    Two shutdown hazards this guards against (both found by the
    integration suite):
    - absorbing the CURRENT task's own cancellation while awaiting the
      child (it would stay 'cancelling' forever) — re-raised below;
    - a child stuck in a gRPC stream read whose cancel handshake never
      completes: after `timeout` the child is abandoned — it dies when
      the channel closes (Go's context-cancel semantics likewise never
      block shutdown on stream drain)."""
    task.cancel()
    try:
        done, pending = await asyncio.wait({task}, timeout=timeout)
        if pending:
            log.info("abandoning task %r after %.1fs cancel wait",
                     task.get_coro(), timeout)
    except asyncio.CancelledError:
        raise
    cur = asyncio.current_task()
    cancelling = getattr(cur, "cancelling", None)   # 3.11+; 3.10: best effort
    if cancelling is not None and cancelling():
        raise asyncio.CancelledError()


class TaskLogBuffer:
    """Per-task ring of LogMessage + a live fan-out bus.

    The executor writes lines via `publish`; subscription publishers read
    tails and watch for live lines.  Bounded per task (the reference
    relies on the container log driver's retention; here the ring cap
    plays that role).
    """

    def __init__(self, maxlen: int = 1000) -> None:
        self.maxlen = maxlen
        self._rings: dict[str, deque] = {}
        self._bus: Queue = Queue()   # every new LogMessage, all tasks
        self._seq = 0                # monotonic ring position, all tasks

    def publish(self, task_id: str, stream: LogStream, data: bytes,
                service_id: str = "", node_id: str = "",
                timestamp: float = 0.0) -> None:
        self._seq += 1
        msg = LogMessage(
            context=LogContext(service_id=service_id, node_id=node_id,
                               task_id=task_id),
            timestamp=timestamp, stream=stream, data=data, seq=self._seq)
        ring = self._rings.setdefault(task_id, deque(maxlen=self.maxlen))
        ring.append(msg)
        self._bus.publish(msg)

    def tail(self, task_id: str, n: int = -1) -> list[LogMessage]:
        ring = self._rings.get(task_id)
        if not ring:
            return []
        msgs = list(ring)
        return msgs if n < 0 else msgs[len(msgs) - min(n, len(msgs)):]

    def watch(self):
        return self._bus.watch()

    def drop(self, task_id: str) -> None:
        self._rings.pop(task_id, None)


def selector_matches(selector, task, node_id: str) -> bool:
    """Does this local task feed the subscription?  (reference:
    subscription.go match — any of the selector dimensions hits.)"""
    if task.id in (selector.task_ids or []):
        return True
    if getattr(task, "service_id", "") in (selector.service_ids or []):
        return True
    if node_id in (selector.node_ids or []):
        return True
    return False


class SubscriptionPublisher:
    """Publishes one subscription's matching local task logs.

    Backlog first (respecting options.tail), then — in follow mode —
    live lines from the buffer bus; in non-follow mode a close marker
    tells the broker this node is done (broker.go publisher tracking).
    """

    def __init__(self, sub_msg, worker, logs: TaskLogBuffer, client,
                 node_id: str) -> None:
        self.sub = sub_msg
        self.worker = worker
        self.logs = logs
        self.client = client
        self.node_id = node_id
        self.follow = bool(sub_msg.options.get("follow", True))
        self.tail_n = int(sub_msg.options.get("tail", -1))
        self._published: set[str] = set()   # task ids whose tail was sent
        self._tail_seq: dict[str, int] = {}  # last ring seq in that tail
        self._task: Optional[asyncio.Task] = None
        # created HERE, not in _run: a re-announce can arrive before the
        # publisher task ever gets scheduled
        self._rescan_event = asyncio.Event()

    def matching_tasks(self) -> list:
        out = []
        for tm in self.worker.task_managers.values():
            t = getattr(tm, "task", None)
            if t is not None and selector_matches(self.sub.selector, t,
                                                  self.node_id):
                out.append(t)
        return out

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            await _cancel_and_wait(self._task)
            self._task = None

    def rescan(self) -> None:
        """Re-announced subscription (tasks moved onto this node): ship
        tails for newly matching tasks without restarting the stream."""
        if self._task is not None and not self._task.done():
            self._rescan_event.set()

    async def _publish(self, msgs: Iterable[LogMessage],
                       close: bool = False) -> None:
        msgs = list(msgs)
        if msgs or close:
            await self.client.publish_logs(self.sub.id, msgs,
                                           node_id=self.node_id,
                                           close=close)

    async def _send_tails(self) -> None:
        for t in self.matching_tasks():
            if t.id in self._published:
                continue
            self._published.add(t.id)
            msgs = self.logs.tail(t.id, self.tail_n)
            if msgs:
                # live lines at or before this position are already in
                # the snapshot; the follow loop skips them (the watcher
                # opened BEFORE tail(), so overlap means duplicates, not
                # gaps)
                self._tail_seq[t.id] = msgs[-1].seq
            await self._publish(msgs)

    async def _run(self) -> None:
        try:
            if not self.follow:
                await self._send_tails()
                await self._publish([], close=True)
                return
            # follow: open the live watcher BEFORE the tail snapshot so no
            # line can fall between backlog and stream
            watcher = self.logs.watch()
            try:
                await self._send_tails()
                get = asyncio.ensure_future(watcher.__anext__())
                while True:
                    resc = asyncio.ensure_future(self._rescan_event.wait())
                    done, _ = await asyncio.wait(
                        {get, resc}, return_when=asyncio.FIRST_COMPLETED)
                    if resc in done:
                        self._rescan_event.clear()
                        await self._send_tails()
                    else:
                        resc.cancel()
                    if get in done:
                        msg = get.result()
                        t_id = msg.context.task_id
                        if t_id in self._published:
                            if msg.seq > self._tail_seq.get(t_id, 0):
                                await self._publish([msg])
                        elif any(t.id == t_id
                                 for t in self.matching_tasks()):
                            self._published.add(t_id)
                            msgs = self.logs.tail(t_id, self.tail_n)
                            if msgs:
                                # same dedup as _send_tails: this live
                                # line (and any later ones already in
                                # the ring) ride the snapshot
                                self._tail_seq[t_id] = msgs[-1].seq
                            await self._publish(msgs)
                        get = asyncio.ensure_future(watcher.__anext__())
            finally:
                watcher.close()
        except asyncio.CancelledError:
            pass
        except Exception as e:
            log.info("log publisher for %s failed: %s", self.sub.id, e)


class LogSubscriptionLoop:
    """Consumes ListenSubscriptions and manages one publisher per active
    subscription (reference: agent.go:207 handleSubscriptions)."""

    def __init__(self, client, worker, logs: TaskLogBuffer,
                 node_id: str) -> None:
        self.client = client
        self.worker = worker
        self.logs = logs
        self.node_id = node_id
        self.publishers: dict[str, SubscriptionPublisher] = {}
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            await _cancel_and_wait(self._task)
            self._task = None
        for p in list(self.publishers.values()):
            await p.stop()
        self.publishers = {}

    async def _run(self) -> None:
        try:
            async for smsg in self.client.listen_subscriptions(self.node_id):
                pub = self.publishers.get(smsg.id)
                if smsg.close:
                    if pub is not None:
                        await pub.stop()
                        self.publishers.pop(smsg.id, None)
                    continue
                if pub is None:
                    pub = SubscriptionPublisher(smsg, self.worker, self.logs,
                                                self.client, self.node_id)
                    self.publishers[smsg.id] = pub
                    pub.start()
                else:
                    pub.rescan()
        except asyncio.CancelledError:
            pass
        except Exception as e:
            log.info("log subscription loop ended: %s", e)

"""Worker: applies assignment sets to the local runtime.

Reference: agent/worker.go — ``Assign`` (full set, :131) / ``Update``
(incremental, :165) reconcile task managers against the assigned set
(reconcileTaskState :190), persist accepted tasks + statuses to the local DB
(agent/storage.go) so a restarted worker resumes them, and maintain the
secret/config dependency stores.  A Reporter is notified of every status
change; on (re)connection the worker re-reports everything it knows
(reportAll semantics via ``set_reporter``).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Optional

from swarmkit_tpu.agent.dependency import Dependencies
from swarmkit_tpu.agent.exec import Executor
from swarmkit_tpu.agent.storage import TaskDB
from swarmkit_tpu.agent.task import TaskManager
from swarmkit_tpu.api import TaskState, TaskStatus
from swarmkit_tpu.api.dispatcher_msgs import (
    AssignmentAction, AssignmentsMessage, AssignmentsType,
)
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.agent.worker")


class Worker:
    def __init__(self, executor: Executor, db: Optional[TaskDB] = None,
                 clock: Optional[Clock] = None) -> None:
        self.executor = executor
        # controllers resolve task secrets/configs through this (the
        # reference wires a DependencyManager into the executor the same
        # way; template/getter.go wraps it per task)
        executor.dependencies = self.dependencies = Dependencies()
        self.db = db or TaskDB()
        self.clock = clock or SystemClock()
        self.node = None   # latest node object from the session stream
        self.task_managers: dict[str, TaskManager] = {}
        # freshest status per task, for re-reporting on reconnection
        self.statuses: dict[str, TaskStatus] = {}
        self._reporter: Optional[Callable[[str, TaskStatus], None]] = None

    # ------------------------------------------------------------------
    def set_node(self, node) -> None:
        """Latest node object (template-expansion context), persisted so a
        restart can restore templated tasks before the session opens."""
        self.node = node
        try:
            self.db.put_node(node)
        except Exception:
            pass

    async def init(self) -> None:
        """Resume tasks recorded in the local DB (reference: worker.Init —
        restores accepted tasks after an agent restart)."""
        if self.node is None:
            self.node = self.db.get_node()
        for task, status, assigned in list(self.db.walk()):
            if not assigned:
                self.db.delete_task(task.id)
                continue
            if status is not None:
                task.status = status
            await self._start_manager(task)

    async def close(self) -> None:
        for tm in list(self.task_managers.values()):
            await tm.close()
        self.task_managers = {}

    def set_reporter(self, reporter: Optional[Callable[[str, TaskStatus], None]]
                     ) -> None:
        """Attach the status sink and replay everything known
        (reference: worker.Listen → reportAll)."""
        self._reporter = reporter
        if reporter is not None:
            for tid, status in self.statuses.items():
                reporter(tid, status)

    # ------------------------------------------------------------------
    async def assign(self, message: AssignmentsMessage) -> None:
        """Apply a message from the dispatcher: COMPLETE replaces the whole
        set, INCREMENTAL applies the diff (worker.go Assign/Update)."""
        if message.type == AssignmentsType.COMPLETE:
            await self._assign_complete(message)
        else:
            await self._assign_incremental(message)

    async def _assign_complete(self, message: AssignmentsMessage) -> None:
        assigned_tasks = {}
        secrets, configs = [], []
        for ch in message.changes:
            a = ch.assignment
            if a.task is not None:
                assigned_tasks[a.task.id] = a.task
            elif a.secret is not None:
                secrets.append(a.secret)
            elif a.config is not None:
                configs.append(a.config)
        self.dependencies.secrets.reset()
        self.dependencies.secrets.add(*secrets)
        self.dependencies.configs.reset()
        self.dependencies.configs.add(*configs)
        # anything we run that is no longer assigned gets released
        for tid in list(self.task_managers):
            if tid not in assigned_tasks:
                await self._remove_task(tid)
        for task in assigned_tasks.values():
            await self._update_task(task)

    async def _assign_incremental(self, message: AssignmentsMessage) -> None:
        for ch in message.changes:
            a = ch.assignment
            if a.task is not None:
                if ch.action == AssignmentAction.REMOVE:
                    await self._remove_task(a.task.id)
                else:
                    await self._update_task(a.task)
            elif a.secret is not None:
                if ch.action == AssignmentAction.REMOVE:
                    self.dependencies.secrets.remove([a.secret.id])
                else:
                    self.dependencies.secrets.add(a.secret)
            elif a.config is not None:
                if ch.action == AssignmentAction.REMOVE:
                    self.dependencies.configs.remove([a.config.id])
                else:
                    self.dependencies.configs.add(a.config)

    # ------------------------------------------------------------------
    async def _update_task(self, task) -> None:
        tm = self.task_managers.get(task.id)
        if tm is not None:
            self.db.put_task(task)
            await tm.update(task)
            return
        # the dispatcher's copy of status may lag ours (we are the source
        # of truth once the task runs here) — reference: reconcileTaskState
        known = self.db.get_task_status(task.id)
        if known is not None and known.state > task.status.state:
            task = task.copy()
            task.status = known
        await self._start_manager(task)

    async def _start_manager(self, task) -> None:
        self.db.put_task(task)
        self.db.set_task_assignment(task.id, True)
        if task.status.state >= TaskState.COMPLETE:
            self.statuses[task.id] = task.status
            return  # nothing to drive
        # expand {{.Service.Name}}-style templates against this node
        # (reference: dockerapi controller runs ExpandContainerSpec)
        try:
            from swarmkit_tpu.template import expand_container_spec

            expanded = expand_container_spec(task, self.node)
        except Exception as e:
            status = task.status.copy()
            status.state = TaskState.REJECTED
            status.err = f"template expansion failed: {e}"
            status.timestamp = self.clock.now()
            await self._report(task.id, status)
            return
        try:
            controller = await self.executor.controller(expanded)
        except Exception as e:
            status = task.status.copy()
            status.state = TaskState.REJECTED
            status.err = str(e)
            status.timestamp = self.clock.now()
            await self._report(task.id, status)
            return
        tm = TaskManager(task, controller, self._report, self.clock)
        self.task_managers[task.id] = tm
        tm.start()

    async def _remove_task(self, task_id: str) -> None:
        tm = self.task_managers.pop(task_id, None)
        if tm is not None:
            # drive the workload down before dropping it (worker.go releases
            # via taskManager close + controller remove)
            try:
                await tm.controller.shutdown()
                await tm.controller.remove()
            except Exception:
                pass
            await tm.close()
        self.statuses.pop(task_id, None)
        self.db.delete_task(task_id)

    async def _report(self, task_id: str, status: TaskStatus) -> None:
        self.statuses[task_id] = status
        try:
            self.db.put_task_status(task_id, status)
        except Exception:
            pass
        if self._reporter is not None:
            self._reporter(task_id, status)
        await asyncio.sleep(0)

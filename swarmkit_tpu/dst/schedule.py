"""Fault schedules: stacked device arrays + the seeded adversary generator.

A `FaultSchedule` is the compiled, data-only form of an adversary: per-tick
base drop matrices and liveness masks plus two STATE-CONDITIONED gates
(`target_leader`, `crash_campaign`) that the explore/replay drivers resolve
against the cluster's current roles each tick.  The gates make the two
adversaries the fault layer cannot express statically — "isolate whoever
leads right now" and "kill candidates mid-campaign" — pure functions of
(schedule, state), so a replay is bit-identical to the original run.

Generation is counter-based `jax.random`: one fold of the sweep seed per
schedule index, so schedule s of seed k is the same arrays forever (the
repro artifacts pin ``(seed, profile, index)`` for exactly this reason) and
the whole batch generates on device with a single vmap.

Tick-latency note: the synchronous wire retries every message each tick, so
a directed edge that a schedule drops on d consecutive ticks delays that
edge's traffic by d ticks — delay masks lower to drop runs (see
``from_fault_plan`` and `raft/faults.py` ``plan_to_schedule``).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from swarmkit_tpu.raft.sim.state import CANDIDATE, LEADER, SimConfig

I32 = jnp.int32

# Named adversary profiles (ISSUE 3 tentpole part 1).  `make_batch` deals
# them round-robin across the schedule axis.  PROFILES is the default
# rotation and is pinned by seed-stability tests — new special-purpose
# adversaries go in EXTRA_PROFILES and are requested explicitly.
PROFILES = ("random_drop", "partition_flapper", "leader_targeted",
            "asymmetric_links", "crash_restart", "crash_during_campaign")
EXTRA_PROFILES = ("stale_leader_reads", "term_inflation")


@jax.tree_util.register_dataclass
@dataclass
class FaultSchedule:
    """Stacked fault arrays for T ticks (optionally with a leading S axis).

    drop           bool [.., T, N, N]  base per-tick drops, [i, j] = i -> j
    alive          bool [.., T, N]     row liveness (False = crashed)
    target_leader  bool [.., T]        gate: drop all edges touching any
                                       row that is CURRENTLY leader
    crash_campaign bool [.., T]        gate: rows CURRENTLY candidate are
                                       treated as crashed this tick
    term_inflate   bool [.., T, N]     protocol-speaking adversary: the
                                       flagged row's election timer is
                                       forced due this tick, so it
                                       spontaneously campaigns — with
                                       pre_vote off every forced tick
                                       bumps its term (term inflation);
                                       with pre_vote on the campaign is a
                                       non-binding poll and the term holds
                                       (see ``apply_term_inflation``).
                                       None = action absent (old artifacts
                                       and the stock profiles trace the
                                       exact pre-extension program).
    """

    drop: jax.Array
    alive: jax.Array
    target_leader: jax.Array
    crash_campaign: jax.Array
    term_inflate: Optional[jax.Array] = None

    @property
    def ticks(self) -> int:
        return self.target_leader.shape[-1]

    def slice(self, s: int) -> "FaultSchedule":
        """Extract one schedule from a batched [S, ...] stack."""
        return jax.tree_util.tree_map(lambda a: a[s], self)


def effective_faults(role: jax.Array, drop_t: jax.Array, alive_t: jax.Array,
                     target_leader_t: jax.Array, crash_campaign_t: jax.Array):
    """Resolve one tick's state-conditioned gates against current roles.

    Returns (alive, drop) in the exact shapes `kernel.step` consumes; pure
    in (role, schedule slice), so replays reproduce the original faults.
    """
    leaders = role == LEADER
    isolate = target_leader_t & (leaders[:, None] | leaders[None, :])
    drop = drop_t | isolate
    alive = alive_t & ~(crash_campaign_t & (role == CANDIDATE))
    return alive, drop


def apply_term_inflation(state, term_inflate_t: jax.Array,
                         alive: jax.Array):
    """Pre-step transform realizing one tick of the ``term_inflate`` action.

    Flagged live non-leader rows get their election timer forced to the
    firing point, so the KERNEL's own campaign path runs this tick — the
    adversary speaks the protocol instead of corrupting state.  The
    consequences are therefore exactly raft's: with ``cfg.pre_vote`` off
    the campaign bumps the row's term every forced tick (classic term
    inflation, etcd issue #9333 shape); with PreVote on the same force
    only starts a non-binding poll at term+1 — no bump until a quorum
    grants, which CheckQuorum-leased voters refuse — so the documented
    "PreVote neutralizes term inflation" claim is checked against the
    real kernel, not a model of it.  Leaders are exempt (a leader's timer
    drives CheckQuorum, not campaigns), matching the vendor HUP gate.
    """
    force = term_inflate_t & alive & (state.role != LEADER)
    elapsed = jnp.where(force, jnp.maximum(state.elapsed, state.timeout),
                        state.elapsed)
    return dataclasses.replace(state, elapsed=elapsed)


# ---------------------------------------------------------------------------
# profile generators: (key, cfg, ticks) -> FaultSchedule for ONE schedule.
# All shapes are static in (cfg, ticks) so the batch generator can vmap.


def _windows(key, ticks: int, period_lo: int, period_hi: int) -> jax.Array:
    """[T] bool square-wave gate with a random period and phase — the
    flapping primitive shared by several adversaries."""
    kp, kf = jax.random.split(key)
    period = jax.random.randint(kp, (), period_lo, period_hi + 1)
    phase = jax.random.randint(kf, (), 0, period_hi)
    t = jnp.arange(ticks, dtype=I32)
    return ((t + phase) // period) % 2 == 1


def _no_faults(cfg: SimConfig, ticks: int) -> FaultSchedule:
    n = cfg.n
    return FaultSchedule(
        drop=jnp.zeros((ticks, n, n), bool),
        alive=jnp.ones((ticks, n), bool),
        target_leader=jnp.zeros((ticks,), bool),
        crash_campaign=jnp.zeros((ticks,), bool))


def _gen_random_drop(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """iid Bernoulli edge drops at a per-schedule rate in [0.05, 0.4)."""
    kr, kd = jax.random.split(key)
    rate = jax.random.uniform(kr, (), minval=0.05, maxval=0.4)
    drop = jax.random.uniform(kd, (ticks, cfg.n, cfg.n)) < rate
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop)


def _gen_partition_flapper(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """A two-sided split that flaps open/closed: the cut point is random
    and the flap period straddles the election timeout, so elections keep
    starting on one side while commits race on the other."""
    kc, kw = jax.random.split(key)
    cut = jax.random.randint(kc, (), 1, cfg.n)
    side = jnp.arange(cfg.n, dtype=I32) < cut
    cross = side[:, None] != side[None, :]
    gate = _windows(kw, ticks, cfg.election_tick // 2,
                    2 * cfg.election_tick)
    drop = gate[:, None, None] & cross[None, :, :]
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop)


def _gen_leader_targeted(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """Windows during which whoever currently leads is fully isolated —
    the classic availability adversary (resolved per tick from live roles
    by ``effective_faults``), over a light random-drop background."""
    kw, kd = jax.random.split(key)
    gate = _windows(kw, ticks, cfg.election_tick, 3 * cfg.election_tick)
    drop = jax.random.uniform(kd, (ticks, cfg.n, cfg.n)) < 0.05
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop,
                               target_leader=gate)


def _gen_asymmetric_links(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """Persistent one-directional loss: each directed edge gets its own
    loss rate (a few edges near-dead), with NO symmetry — i hears j while
    j never hears i, the regime that breaks naive failure detectors."""
    kp, kd = jax.random.split(key)
    edge_rate = jax.random.uniform(kp, (cfg.n, cfg.n)) ** 3  # skew to low
    drop = jax.random.uniform(kd, (ticks, cfg.n, cfg.n)) < edge_rate[None]
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop)


def _gen_crash_restart(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """Random crash/restart windows: each row draws a crash tick and an
    outage length; up to half the rows crash somewhere in the run."""
    kv, ks, kd = jax.random.split(key, 3)
    crash_at = jax.random.randint(ks, (cfg.n,), 0, max(1, ticks - 2))
    down_for = jax.random.randint(kd, (cfg.n,),
                                  2, max(3, 3 * cfg.election_tick))
    victims = jax.random.uniform(kv, (cfg.n,)) < 0.5
    t = jnp.arange(ticks, dtype=I32)[:, None]
    downed = victims[None, :] & (t >= crash_at[None, :]) \
        & (t < (crash_at + down_for)[None, :])
    return dataclasses.replace(_no_faults(cfg, ticks), alive=~downed)


def _gen_crash_during_campaign(key, cfg: SimConfig, ticks: int
                               ) -> FaultSchedule:
    """Windows during which any row that is mid-campaign (CANDIDATE) is
    crashed — the adversary that maximizes term churn and interrupted
    elections — over a light random-drop background."""
    kw, kd = jax.random.split(key)
    gate = _windows(kw, ticks, cfg.election_tick, 2 * cfg.election_tick)
    drop = jax.random.uniform(kd, (ticks, cfg.n, cfg.n)) < 0.1
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop,
                               crash_campaign=gate)


def _gen_stale_leader_reads(key, cfg: SimConfig, ticks: int
                            ) -> FaultSchedule:
    """The arXiv:2601.00273 stale-read attack shape: ONE random victim row
    is fully edge-isolated for ~3 election timeouts, window start after
    the first election settles.  When the victim happens to be the leader
    (the rotation makes that a constant fraction of the sub-batch), the
    majority elects a successor that commits fresh writes while
    CheckQuorum's recent-activity lag leaves the victim CLAIMING
    leadership with read batches pending — the stale-leader overlap.  A
    correct lease expires inside the window (lease_ticks < election_tick
    <= time to a rival quorum) and refuses those reads; a lease-disabled
    serve (the ``stale_lease_read`` mutation) returns state missing the
    successor's acked writes and must trip LINEARIZABLE_READ.

    Deliberately NOT the target_leader gate: that gate isolates every
    CURRENT leader each tick, so it would muzzle the successor too and
    stall exactly the commit progress the stale read must miss."""
    kv, ks, kd = jax.random.split(key, 3)
    T = cfg.election_tick
    width = 3 * T
    victim = jax.random.randint(kv, (), 0, cfg.n)
    start = jax.random.randint(ks, (), 2 * T, max(2 * T + 1, ticks - width))
    t = jnp.arange(ticks, dtype=I32)
    gate = (t >= start) & (t < start + width)                    # [T]
    row = jnp.arange(cfg.n, dtype=I32)
    touches = (row[:, None] == victim) | (row[None, :] == victim)  # [N, N]
    isolate = gate[:, None, None] & touches[None, :, :]
    drop = (jax.random.uniform(kd, (ticks, cfg.n, cfg.n)) < 0.02) | isolate
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop)


def _gen_term_inflation(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """ROADMAP item 3's protocol-speaking adversary: ONE random victim row
    is fully partitioned away on flapping windows AND fires its election
    timer every windowed tick — the classic rejoin-storm shape (an
    isolated node spins elections nobody hears).  The partition matters
    mechanically, not just narratively: a reachable leader's same-tick
    heartbeat resets the forced timer before the campaign check, so
    without the cut the force mostly no-ops.  With pre_vote off the
    victim's term climbs one notch per forced tick and drags the cluster
    through term churn at every heal; with pre_vote on each forced
    campaign is a non-binding poll the unreachable quorum never grants,
    and the term stays near baseline —
    ``tools/dst_sweep.py --term-inflation-demo`` pins the contrast."""
    kv, kw = jax.random.split(key)
    victim = jax.random.randint(kv, (), 0, cfg.n)
    gate = _windows(kw, ticks, 2, max(3, cfg.election_tick))
    is_victim = jnp.arange(cfg.n, dtype=I32) == victim
    inflate = gate[:, None] & is_victim[None, :]
    cut = is_victim[None, :, None] | is_victim[None, None, :]
    drop = gate[:, None, None] & cut
    return dataclasses.replace(_no_faults(cfg, ticks),
                               drop=drop, term_inflate=inflate)


_GENERATORS = {
    "random_drop": _gen_random_drop,
    "partition_flapper": _gen_partition_flapper,
    "leader_targeted": _gen_leader_targeted,
    "asymmetric_links": _gen_asymmetric_links,
    "crash_restart": _gen_crash_restart,
    "crash_during_campaign": _gen_crash_during_campaign,
    "stale_leader_reads": _gen_stale_leader_reads,
    "term_inflation": _gen_term_inflation,
}


def make_schedule(cfg: SimConfig, ticks: int, profile: str,
                  seed: int, index: int = 0) -> FaultSchedule:
    """One schedule: profile generator keyed by fold_in(seed, index)."""
    gen = _GENERATORS.get(profile)
    if gen is None:
        raise KeyError(f"unknown adversary profile {profile!r}; "
                       f"known: {PROFILES + EXTRA_PROFILES}")
    key = jax.random.fold_in(jax.random.PRNGKey(seed), index)
    return gen(key, cfg, ticks)


def make_batch(cfg: SimConfig, ticks: int, schedules: int, seed: int,
               profiles=PROFILES) -> tuple[FaultSchedule, list[str]]:
    """[S, ...] stacked schedules + the profile name of each index.

    Profiles are dealt round-robin over the schedule axis; each index's key
    is fold_in(seed, index), independent of the batch size, so schedule
    (seed, profile, index) is stable however wide the sweep runs.
    """
    profiles = tuple(profiles)
    names = [profiles[s % len(profiles)] for s in range(schedules)]
    base = jax.random.PRNGKey(seed)
    parts = []
    for s, name in enumerate(names):
        parts.append((s, _GENERATORS[name], jax.random.fold_in(base, s)))
    # group by generator so each profile's sub-batch is ONE vmapped call
    stacks: dict[int, FaultSchedule] = {}
    for gen in {g for _, g, _ in parts}:
        idx = [s for s, g, _ in parts if g is gen]
        keys = jnp.stack([k for s, g, k in parts if g is gen])
        sub = jax.vmap(lambda k, g=gen: g(k, cfg, ticks))(keys)
        for pos, s in enumerate(idx):
            stacks[s] = jax.tree_util.tree_map(lambda a: a[pos], sub)
    scheds = [stacks[s] for s in range(schedules)]
    # a batch mixing term_inflation with inflation-less profiles must agree
    # on tree structure: promote the Nones to all-False gates (value-
    # identical — the transform is the identity on an all-False mask)
    if any(s.term_inflate is not None for s in scheds):
        zero = jnp.zeros((ticks, cfg.n), bool)
        scheds = [dataclasses.replace(s, term_inflate=zero)
                  if s.term_inflate is None else s for s in scheds]
    batch = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *scheds)
    return batch, names


def from_fault_plan(cfg: SimConfig, plan, rows: dict[str, int], ticks: int,
                    inject_at: int = 0, heal_at=None,
                    seed: int = 0) -> FaultSchedule:
    """Lower a declarative `raft.faults.FaultPlan` into a FaultSchedule.

    `rows` maps the plan's wire addresses to kernel row indices.  The
    actual lowering lives next to the plan vocabulary
    (``raft.faults.plan_to_schedule``); this wraps its numpy output in the
    device dataclass with the state-conditioned gates off.
    """
    from swarmkit_tpu.raft.faults import plan_to_schedule

    arrs = plan_to_schedule(plan, rows, n=cfg.n, ticks=ticks,
                            inject_at=inject_at, heal_at=heal_at, seed=seed)
    return FaultSchedule(
        drop=jnp.asarray(arrs["drop"]),
        alive=jnp.asarray(arrs["alive"]),
        target_leader=jnp.zeros((ticks,), bool),
        crash_campaign=jnp.zeros((ticks,), bool))

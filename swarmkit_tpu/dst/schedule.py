"""Fault schedules: stacked device arrays + the seeded adversary generator.

A `FaultSchedule` is the compiled, data-only form of an adversary: per-tick
base drop matrices and liveness masks plus two STATE-CONDITIONED gates
(`target_leader`, `crash_campaign`) that the explore/replay drivers resolve
against the cluster's current roles each tick.  The gates make the two
adversaries the fault layer cannot express statically — "isolate whoever
leads right now" and "kill candidates mid-campaign" — pure functions of
(schedule, state), so a replay is bit-identical to the original run.

Generation is counter-based `jax.random`: one fold of the sweep seed per
schedule index, so schedule s of seed k is the same arrays forever (the
repro artifacts pin ``(seed, profile, index)`` for exactly this reason) and
the whole batch generates on device with a single vmap.

Tick-latency note: the synchronous wire retries every message each tick, so
a directed edge that a schedule drops on d consecutive ticks delays that
edge's traffic by d ticks — delay masks lower to drop runs (see
``from_fault_plan`` and `raft/faults.py` ``plan_to_schedule``).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from swarmkit_tpu.raft.sim.state import (
    CANDIDATE, LEADER, NONE, SimConfig, hash32,
)

I32 = jnp.int32
U32 = jnp.uint32

# Named adversary profiles (ISSUE 3 tentpole part 1).  `make_batch` deals
# them round-robin across the schedule axis.  PROFILES is the default
# rotation and is pinned by seed-stability tests — new special-purpose
# adversaries go in EXTRA_PROFILES and are requested explicitly.
PROFILES = ("random_drop", "partition_flapper", "leader_targeted",
            "asymmetric_links", "crash_restart", "crash_during_campaign")
# The arXiv:2601.00273 attack suite (ISSUE 15): each profile drives one
# counted FaultSchedule verb below, and each verb has a matching kernel
# defense knob (see SimConfig) whose cost is bounded by an SLO invariant.
ATTACK_PROFILES = ("disruptive_rejoin", "vote_equivocation",
                   "append_flood", "transfer_abuse")
# The ISSUE 16 storage-fault suite: each profile drives one storage leaf
# below.  These adversaries attack the durable/volatile boundary instead
# of the wire, so they require the storage model (cfg.fsync_lag_ticks
# >= 1) — the verbs are pure no-ops on a storage-off state — and the
# matching defense is the ack-gating contract (cfg.ack_gating) plus the
# SLO_FSYNC_LAG budget.
STORAGE_PROFILES = ("lost_tail", "torn_write", "snap_corrupt",
                    "disk_stall")
EXTRA_PROFILES = ("stale_leader_reads", "term_inflation") \
    + ATTACK_PROFILES + STORAGE_PROFILES
# Per-attack wiring, pinned by tools/metrics_lint.py check #8: the
# FaultSchedule leaf each profile drives (gate firings feed the
# swarm_dst_attack_ticks_total counter) and the flightrec signature code
# its apply verb emits.
ATTACK_LEAVES = {
    "disruptive_rejoin": "rejoin_campaign",
    "vote_equivocation": "vote_equivocate",
    "append_flood": "append_flood",
    "transfer_abuse": "transfer_abuse",
}
ATTACK_SIGNATURE_CODES = {
    "disruptive_rejoin": "ATTACK_REJOIN",
    "vote_equivocation": "ATTACK_EQUIVOCATE",
    "append_flood": "ATTACK_FLOOD",
    "transfer_abuse": "ATTACK_TRANSFER",
}
# Per-storage-fault wiring, pinned by tools/metrics_lint.py check #9:
# the FaultSchedule leaf each storage profile drives and the flightrec
# signature code its apply verb emits.
STORAGE_LEAVES = {
    "lost_tail": "lost_tail",
    "torn_write": "torn_write",
    "snap_corrupt": "snap_corrupt",
    "disk_stall": "disk_stall",
}
STORAGE_SIGNATURE_CODES = {
    "lost_tail": "RECOVER_TRUNCATE",
    "torn_write": "RECOVER_TORN",
    "snap_corrupt": "SNAP_CORRUPT",
    "disk_stall": "FSYNC_STALL",
}


@jax.tree_util.register_dataclass
@dataclass
class FaultSchedule:
    """Stacked fault arrays for T ticks (optionally with a leading S axis).

    drop           bool [.., T, N, N]  base per-tick drops, [i, j] = i -> j
    alive          bool [.., T, N]     row liveness (False = crashed)
    target_leader  bool [.., T]        gate: drop all edges touching any
                                       row that is CURRENTLY leader
    crash_campaign bool [.., T]        gate: rows CURRENTLY candidate are
                                       treated as crashed this tick
    term_inflate   bool [.., T, N]     protocol-speaking adversary: the
                                       flagged row's election timer is
                                       forced due this tick, so it
                                       spontaneously campaigns — with
                                       pre_vote off every forced tick
                                       bumps its term (term inflation);
                                       with pre_vote on the campaign is a
                                       non-binding poll and the term holds
                                       (see ``apply_term_inflation``).
                                       None = action absent (old artifacts
                                       and the stock profiles trace the
                                       exact pre-extension program).
    rejoin_campaign bool [.., T, N]    disruptive-rejoin barrage: the
                                       flagged row's election timer is
                                       forced due (same mechanics as
                                       term_inflate, distinct signature /
                                       schedule shape: paired with a
                                       partition that HEALS, so the
                                       barrage lands on a reachable
                                       cluster).  Neutralized by
                                       PreVote + CheckQuorum.
    vote_equivocate bool [.., T, N]    crash-restart-without-fsync: the
                                       flagged row's in-memory `vote` is
                                       wiped, so it may grant a SECOND
                                       candidate in the same term
                                       (ElectionSafety trips) unless the
                                       kernel's persisted-vote guard
                                       (cfg.vote_guard) is on.
    append_flood    bool [.., T]       targeted client flood: every row
                                       currently accepting proposals gets
                                       cfg.max_props extra dense appends
                                       this tick, driving ring/Phase-F
                                       compaction pressure.  Bounded by
                                       cfg.prop_inflight_cap.
    transfer_abuse  bool [.., T, N]    leadership-transfer abuse: every
                                       current leader is asked to
                                       transfer to the (lowest) flagged
                                       row this tick — repeated
                                       TimeoutNow thrash.  Bounded by
                                       cfg.transfer_cooldown_ticks.
    lost_tail       bool [.., T, N]    storage fault (needs the storage
                                       model armed): the flagged row
                                       crashed with an unsynced log
                                       suffix — its disk image truncates
                                       back to sync_mark and volatile
                                       state rebuilds from durable
                                       registers only.  Fired on the
                                       crash tick itself (the frozen
                                       image is what the revived row
                                       boots from).
    torn_write      bool [.., T, N]    storage fault: recovery finds the
                                       row's LAST durable entry
                                       checksum-broken (torn sector), so
                                       both last and sync_mark truncate
                                       one below the watermark.
    snap_corrupt    bool [.., T, N]    storage fault: a snapshot arriving
                                       at the flagged row this tick fails
                                       its restore checksum — refused
                                       under ack_gating, installed-and-
                                       poisoned without it.
    disk_stall      bool [.., T, N]    storage fault: the flagged row's
                                       fsync makes no progress this tick;
                                       under ack_gating its acks and vote
                                       grants lag with it, bounded by
                                       SLO_FSYNC_LAG.

    All action leaves default to None = absent, so old artifacts and
    the stock profiles keep tracing the exact pre-extension program.
    """

    drop: jax.Array
    alive: jax.Array
    target_leader: jax.Array
    crash_campaign: jax.Array
    term_inflate: Optional[jax.Array] = None
    rejoin_campaign: Optional[jax.Array] = None
    vote_equivocate: Optional[jax.Array] = None
    append_flood: Optional[jax.Array] = None
    transfer_abuse: Optional[jax.Array] = None
    lost_tail: Optional[jax.Array] = None
    torn_write: Optional[jax.Array] = None
    snap_corrupt: Optional[jax.Array] = None
    disk_stall: Optional[jax.Array] = None

    @property
    def ticks(self) -> int:
        return self.target_leader.shape[-1]

    def slice(self, s: int) -> "FaultSchedule":
        """Extract one schedule from a batched [S, ...] stack."""
        return jax.tree_util.tree_map(lambda a: a[s], self)


def effective_faults(role: jax.Array, drop_t: jax.Array, alive_t: jax.Array,
                     target_leader_t: jax.Array, crash_campaign_t: jax.Array):
    """Resolve one tick's state-conditioned gates against current roles.

    Returns (alive, drop) in the exact shapes `kernel.step` consumes; pure
    in (role, schedule slice), so replays reproduce the original faults.
    """
    leaders = role == LEADER
    isolate = target_leader_t & (leaders[:, None] | leaders[None, :])
    drop = drop_t | isolate
    alive = alive_t & ~(crash_campaign_t & (role == CANDIDATE))
    return alive, drop


def apply_term_inflation(state, term_inflate_t: jax.Array,
                         alive: jax.Array):
    """Pre-step transform realizing one tick of the ``term_inflate`` action.

    Flagged live non-leader rows get their election timer forced to the
    firing point, so the KERNEL's own campaign path runs this tick — the
    adversary speaks the protocol instead of corrupting state.  The
    consequences are therefore exactly raft's: with ``cfg.pre_vote`` off
    the campaign bumps the row's term every forced tick (classic term
    inflation, etcd issue #9333 shape); with PreVote on the same force
    only starts a non-binding poll at term+1 — no bump until a quorum
    grants, which CheckQuorum-leased voters refuse — so the documented
    "PreVote neutralizes term inflation" claim is checked against the
    real kernel, not a model of it.  Leaders are exempt (a leader's timer
    drives CheckQuorum, not campaigns), matching the vendor HUP gate.
    """
    force = term_inflate_t & alive & (state.role != LEADER)
    elapsed = jnp.where(force, jnp.maximum(state.elapsed, state.timeout),
                        state.elapsed)
    return dataclasses.replace(state, elapsed=elapsed)


# ---------------------------------------------------------------------------
# ISSUE 15 attack verbs.  Each is a pre-step transform like
# apply_term_inflation: pure in (state, schedule slice), shapes row-local
# (vmap-safe), and emitting its flightrec signature when the state carries
# an event ring.  COMPOSITION ORDER (explore/repro apply them in this
# fixed sequence so two active attacks never silently mask each other):
#   term_inflate -> rejoin_campaign -> vote_equivocate -> transfer_abuse
#   -> append_flood -> disk_stall -> snap_corrupt -> lost_tail
#   -> torn_write
# The timer verbs commute (both take max(elapsed, timeout)); the vote wipe
# touches only `vote`; transfer_abuse runs BEFORE append_flood so a
# transfer it starts correctly blocks the flood's proposals on that
# leader — the same refusal a real client would see.  The storage verbs
# run after all wire-level attacks: disk_stall/snap_corrupt only set the
# one-tick flags the kernel consults, while lost_tail then torn_write
# rewrite the log frontier itself — torn_write last so its strictly
# deeper truncation wins if a schedule ever arms both on one row.


def _emit_attack(state, mask, code: int, a0, a1):
    """Append an attack-signature event on masked rows (no-op when the
    state carries no ring — attacks never change the traced program of a
    recorder-off run)."""
    if state.ev_buf is None:
        return state
    from swarmkit_tpu.flightrec import codes as _fc
    ev_buf, ev_pos = _fc.ring_append(state.ev_buf, state.ev_pos, mask,
                                     state.tick, code, a0, a1)
    return dataclasses.replace(state, ev_buf=ev_buf, ev_pos=ev_pos)


def apply_rejoin_campaign(state, rejoin_t: jax.Array, alive: jax.Array):
    """One tick of the ``rejoin_campaign`` action (disruptive rejoin,
    arXiv:2601.00273): flagged live non-leader rows get their election
    timer forced due, so the kernel's own campaign path fires — the same
    protocol-speaking mechanics as ``apply_term_inflation``, but the
    generator pairs it with a partition that HEALS, so the barrage lands
    on a reachable cluster and (defense off) deposes the standing leader
    every round.  PreVote turns the barrage into non-binding polls and
    the CheckQuorum lease makes contacted voters ignore them; the demo
    bounds the residual churn with SLO_LEADER_CHURN."""
    force = rejoin_t & alive & (state.role != LEADER)
    elapsed = jnp.where(force, jnp.maximum(state.elapsed, state.timeout),
                        state.elapsed)
    out = dataclasses.replace(state, elapsed=elapsed)
    from swarmkit_tpu.flightrec import codes as _fc
    return _emit_attack(out, force, _fc.ATTACK_REJOIN, state.term,
                        state.timeout)


def apply_vote_equivocation(state, equiv_t: jax.Array, alive: jax.Array):
    """One tick of the ``vote_equivocate`` action: wipe the flagged row's
    in-memory vote — the crash-restart-without-fsync fault model, under
    which the row may grant a SECOND candidate in the same term and
    ElectionSafety trips.  The kernel's persisted-vote guard
    (cfg.vote_guard) shadows every vote into vg_vote/vg_term, which this
    verb deliberately CANNOT touch — with the guard on, the dual grant is
    unrepresentable."""
    wipe = equiv_t & alive & (state.vote != NONE)
    vote = jnp.where(wipe, NONE, state.vote)
    out = dataclasses.replace(state, vote=vote)
    from swarmkit_tpu.flightrec import codes as _fc
    return _emit_attack(out, wipe, _fc.ATTACK_EQUIVOCATE, state.vote,
                        state.term)


def _flood_payload(tick, k):
    """Deterministic on-device flood payloads (distinct from the sweep
    drivers' own payload streams so log-matching stays meaningful)."""
    return hash32(tick.astype(U32) * U32(0x9E3779B9) ^ k ^ U32(0xF100D))


def apply_append_flood(state, cfg: SimConfig, flood_t: jax.Array,
                       alive: jax.Array):
    """One tick of the ``append_flood`` action: every row currently
    accepting proposals takes cfg.max_props EXTRA dense appends — the
    targeted client flood that drives ring occupancy into Phase-F
    compaction pressure.  With cfg.prop_inflight_cap set the leader
    refuses the flood while its uncommitted tail is at the cap (the same
    ProposalDropped a real client sees), and SLO_LOG_OCCUPANCY witnesses
    the bound."""
    from swarmkit_tpu.raft.sim.kernel import propose_dense
    cnt = jnp.where(flood_t, cfg.max_props, 0).astype(I32)
    sig = flood_t & alive & (state.role == LEADER)
    out = propose_dense(state, cfg, _flood_payload, cnt, alive)
    from swarmkit_tpu.flightrec import codes as _fc
    return _emit_attack(out, sig, _fc.ATTACK_FLOOD,
                        jnp.broadcast_to(cnt, (cfg.n,)),
                        state.last - state.commit)


def apply_transfer_abuse(state, cfg: SimConfig, abuse_t: jax.Array,
                         alive: jax.Array):
    """One tick of the ``transfer_abuse`` action: every live current
    leader is asked to transfer leadership to the (lowest) flagged row —
    the repeated-TimeoutNow thrash attack.  Mirrors
    ``kernel.transfer_leadership`` semantics row-wise, INCLUDING the
    cooldown consult: with cfg.transfer_cooldown_ticks set a leader that
    just fired a TIMEOUT_NOW refuses the repeat request, and
    SLO_LEADER_CHURN bounds the residual thrash."""
    n = cfg.n
    node = jnp.arange(n, dtype=I32)
    has_tgt = jnp.any(abuse_t)
    tgt = jnp.argmax(abuse_t).astype(I32)          # lowest flagged row
    req = (state.role == LEADER) & alive & has_tgt & (node != tgt)
    req = req & jnp.take(state.member, tgt, axis=1)   # leader's own view
    ok = req
    cool = jnp.zeros((n,), I32)
    if cfg.transfer_cooldown_ticks > 0 and state.tx_cool is not None:
        cool = state.tx_cool
        ok = ok & (cool == 0)
    changed = ok & (state.transferee != tgt)
    transferee = jnp.where(changed, tgt, state.transferee)
    elapsed = jnp.where(changed, 0, state.elapsed)
    out = dataclasses.replace(state, transferee=transferee, elapsed=elapsed)
    from swarmkit_tpu.flightrec import codes as _fc
    return _emit_attack(out, req, _fc.ATTACK_TRANSFER,
                        jnp.broadcast_to(tgt, (n,)), cool)


# ---------------------------------------------------------------------------
# ISSUE 16 storage-fault verbs.  Same pre-step-transform contract, but
# the target is the durable/volatile boundary: each verb is a pure no-op
# unless the storage model is armed (state.sync_mark is not None), so a
# storage-off run's traced program cannot change.


def _recover_fields(state, g, new_last):
    """The shared recovery rebuild: volatile state on `g` rows restarts
    from durable registers only.  commit re-clamps to the surviving log
    frontier, apply restarts from the snapshot (Phase E re-runs the
    checksummed scan over the surviving prefix, re-deriving apply_chk
    along the way — a poisoned chain cannot survive recovery), and the
    in-flight read batch plus lease die with the process.  dur_commit is
    deliberately NOT touched: it is the durable record RECOVERY_MONOTONIC
    pins, and the kernel alone advances it."""
    last = jnp.where(g, new_last, state.last)
    fields = dict(
        last=last,
        commit=jnp.where(g, jnp.minimum(state.commit, last), state.commit),
        applied=jnp.where(g, state.snap_idx, state.applied),
        apply_chk=jnp.where(g, state.snap_chk, state.apply_chk))
    if state.read_pend is not None:
        fields.update(
            read_pend=jnp.where(g, 0, state.read_pend),
            read_goal=jnp.where(g, 0, state.read_goal),
            read_idx=jnp.where(g, NONE, state.read_idx),
            lease_until=jnp.where(g, 0, state.lease_until))
    return fields


def apply_lost_tail(state, lost_t: jax.Array, alive: jax.Array):
    """One tick of the ``lost_tail`` action: the flagged row crashed with
    an unsynced log suffix, so its disk image truncates back to the
    durable watermark — last falls to max(sync_mark, snap_idx) and
    volatile state rebuilds from durable registers (`_recover_fields`).
    Liveness is NOT consulted: the generator fires the gate on the crash
    tick itself and the verb rewrites the then-frozen image, which is
    exactly what the revived row boots from.  With cfg.ack_gating on,
    every acked-as-committed entry lies at or below a quorum's
    sync_marks, so the truncation can never remove one and DURABILITY
    holds under ANY lost_tail schedule; with gating off a correlated
    crash deletes acked entries from every log and DURABILITY trips —
    the contrast ``fault_sweep.py --storage`` pins."""
    if state.sync_mark is None:
        return state
    new_last = jnp.maximum(jnp.minimum(state.last, state.sync_mark),
                           state.snap_idx)
    out = dataclasses.replace(state,
                              **_recover_fields(state, lost_t, new_last))
    from swarmkit_tpu.flightrec import codes as _fc
    return _emit_attack(out, lost_t & (state.last > new_last),
                        _fc.RECOVER_TRUNCATE, new_last,
                        state.last - new_last)


def apply_torn_write(state, torn_t: jax.Array, alive: jax.Array):
    """One tick of the ``torn_write`` action: recovery's checksummed WAL
    scan finds the flagged row's LAST durable entry broken (a torn
    sector under the crash — the disk acknowledged an fsync it did not
    complete), so last AND sync_mark truncate one below the watermark,
    max(sync_mark - 1, snap_idx), and volatile state rebuilds as in
    ``apply_lost_tail``.  Unlike lost_tail this removes an entry the row
    counted durable — a lying disk — so ack-gating alone cannot defend a
    fully correlated tear; the surviving defense is replication (any row
    the schedule spares still holds the committed prefix), which is
    exactly the f-of-n boundary the storage sweep pins."""
    if state.sync_mark is None:
        return state
    new_last = jnp.maximum(state.sync_mark - 1, state.snap_idx)
    fields = _recover_fields(state, torn_t, new_last)
    fields["sync_mark"] = jnp.where(torn_t, new_last, state.sync_mark)
    out = dataclasses.replace(state, **fields)
    from swarmkit_tpu.flightrec import codes as _fc
    return _emit_attack(out, torn_t & (state.sync_mark > new_last),
                        _fc.RECOVER_TORN, new_last, state.sync_mark)


def apply_disk_stall(state, stall_t: jax.Array, alive: jax.Array):
    """One tick of the ``disk_stall`` action: the flagged live row's
    fsync makes no progress this tick (the kernel's sync round skips it
    and, under cfg.ack_gating, it refuses vote grants — a stalled disk
    cannot persist the vote record).  The flag is transient; a sustained
    stall is a run of flagged ticks.  Acks lag with the watermark and
    commit stalls boundedly: SLO_FSYNC_LAG budgets the unsynced suffix
    and cfg.prop_inflight_cap caps its growth at the client interface."""
    if state.fsync_stall is None:
        return state
    g = stall_t & alive
    out = dataclasses.replace(state, fsync_stall=state.fsync_stall | g)
    from swarmkit_tpu.flightrec import codes as _fc
    return _emit_attack(out, g, _fc.FSYNC_STALL,
                        state.last - state.sync_mark, state.sync_mark)


def apply_snap_corrupt(state, corrupt_t: jax.Array, alive: jax.Array):
    """One tick of the ``snap_corrupt`` action: any snapshot arriving at
    the flagged live row this tick fails its checksum at restore.  With
    cfg.ack_gating the row refuses the install and keeps its state (the
    sender's unadvanced progress re-sends — the re-request); without it
    the corrupt image installs and poisons the apply/snap checksum
    chain, which CHECKSUM_AGREEMENT catches at the next cross-row
    comparison.  The flag is transient, so the post-window re-request
    installs clean."""
    if state.snap_bad is None:
        return state
    g = corrupt_t & alive
    out = dataclasses.replace(state, snap_bad=state.snap_bad | g)
    from swarmkit_tpu.flightrec import codes as _fc
    return _emit_attack(out, g, _fc.SNAP_CORRUPT, state.snap_idx,
                        state.commit)


# ---------------------------------------------------------------------------
# profile generators: (key, cfg, ticks) -> FaultSchedule for ONE schedule.
# All shapes are static in (cfg, ticks) so the batch generator can vmap.


def _windows(key, ticks: int, period_lo: int, period_hi: int) -> jax.Array:
    """[T] bool square-wave gate with a random period and phase — the
    flapping primitive shared by several adversaries."""
    kp, kf = jax.random.split(key)
    period = jax.random.randint(kp, (), period_lo, period_hi + 1)
    phase = jax.random.randint(kf, (), 0, period_hi)
    t = jnp.arange(ticks, dtype=I32)
    return ((t + phase) // period) % 2 == 1


def _no_faults(cfg: SimConfig, ticks: int) -> FaultSchedule:
    n = cfg.n
    return FaultSchedule(
        drop=jnp.zeros((ticks, n, n), bool),
        alive=jnp.ones((ticks, n), bool),
        target_leader=jnp.zeros((ticks,), bool),
        crash_campaign=jnp.zeros((ticks,), bool))


def _gen_random_drop(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """iid Bernoulli edge drops at a per-schedule rate in [0.05, 0.4)."""
    kr, kd = jax.random.split(key)
    rate = jax.random.uniform(kr, (), minval=0.05, maxval=0.4)
    drop = jax.random.uniform(kd, (ticks, cfg.n, cfg.n)) < rate
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop)


def _gen_partition_flapper(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """A two-sided split that flaps open/closed: the cut point is random
    and the flap period straddles the election timeout, so elections keep
    starting on one side while commits race on the other."""
    kc, kw = jax.random.split(key)
    cut = jax.random.randint(kc, (), 1, cfg.n)
    side = jnp.arange(cfg.n, dtype=I32) < cut
    cross = side[:, None] != side[None, :]
    gate = _windows(kw, ticks, cfg.election_tick // 2,
                    2 * cfg.election_tick)
    drop = gate[:, None, None] & cross[None, :, :]
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop)


def _gen_leader_targeted(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """Windows during which whoever currently leads is fully isolated —
    the classic availability adversary (resolved per tick from live roles
    by ``effective_faults``), over a light random-drop background."""
    kw, kd = jax.random.split(key)
    gate = _windows(kw, ticks, cfg.election_tick, 3 * cfg.election_tick)
    drop = jax.random.uniform(kd, (ticks, cfg.n, cfg.n)) < 0.05
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop,
                               target_leader=gate)


def _gen_asymmetric_links(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """Persistent one-directional loss: each directed edge gets its own
    loss rate (a few edges near-dead), with NO symmetry — i hears j while
    j never hears i, the regime that breaks naive failure detectors."""
    kp, kd = jax.random.split(key)
    edge_rate = jax.random.uniform(kp, (cfg.n, cfg.n)) ** 3  # skew to low
    drop = jax.random.uniform(kd, (ticks, cfg.n, cfg.n)) < edge_rate[None]
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop)


def _gen_crash_restart(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """Random crash/restart windows: each row draws a crash tick and an
    outage length; up to half the rows crash somewhere in the run."""
    kv, ks, kd = jax.random.split(key, 3)
    crash_at = jax.random.randint(ks, (cfg.n,), 0, max(1, ticks - 2))
    down_for = jax.random.randint(kd, (cfg.n,),
                                  2, max(3, 3 * cfg.election_tick))
    victims = jax.random.uniform(kv, (cfg.n,)) < 0.5
    t = jnp.arange(ticks, dtype=I32)[:, None]
    downed = victims[None, :] & (t >= crash_at[None, :]) \
        & (t < (crash_at + down_for)[None, :])
    return dataclasses.replace(_no_faults(cfg, ticks), alive=~downed)


def _gen_crash_during_campaign(key, cfg: SimConfig, ticks: int
                               ) -> FaultSchedule:
    """Windows during which any row that is mid-campaign (CANDIDATE) is
    crashed — the adversary that maximizes term churn and interrupted
    elections — over a light random-drop background."""
    kw, kd = jax.random.split(key)
    gate = _windows(kw, ticks, cfg.election_tick, 2 * cfg.election_tick)
    drop = jax.random.uniform(kd, (ticks, cfg.n, cfg.n)) < 0.1
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop,
                               crash_campaign=gate)


def _gen_stale_leader_reads(key, cfg: SimConfig, ticks: int
                            ) -> FaultSchedule:
    """The arXiv:2601.00273 stale-read attack shape: ONE random victim row
    is fully edge-isolated for ~3 election timeouts, window start after
    the first election settles.  When the victim happens to be the leader
    (the rotation makes that a constant fraction of the sub-batch), the
    majority elects a successor that commits fresh writes while
    CheckQuorum's recent-activity lag leaves the victim CLAIMING
    leadership with read batches pending — the stale-leader overlap.  A
    correct lease expires inside the window (lease_ticks < election_tick
    <= time to a rival quorum) and refuses those reads; a lease-disabled
    serve (the ``stale_lease_read`` mutation) returns state missing the
    successor's acked writes and must trip LINEARIZABLE_READ.

    Deliberately NOT the target_leader gate: that gate isolates every
    CURRENT leader each tick, so it would muzzle the successor too and
    stall exactly the commit progress the stale read must miss."""
    kv, ks, kd = jax.random.split(key, 3)
    T = cfg.election_tick
    width = 3 * T
    victim = jax.random.randint(kv, (), 0, cfg.n)
    start = jax.random.randint(ks, (), 2 * T, max(2 * T + 1, ticks - width))
    t = jnp.arange(ticks, dtype=I32)
    gate = (t >= start) & (t < start + width)                    # [T]
    row = jnp.arange(cfg.n, dtype=I32)
    touches = (row[:, None] == victim) | (row[None, :] == victim)  # [N, N]
    isolate = gate[:, None, None] & touches[None, :, :]
    drop = (jax.random.uniform(kd, (ticks, cfg.n, cfg.n)) < 0.02) | isolate
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop)


def _gen_term_inflation(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """ROADMAP item 3's protocol-speaking adversary: ONE random victim row
    is fully partitioned away on flapping windows AND fires its election
    timer every windowed tick — the classic rejoin-storm shape (an
    isolated node spins elections nobody hears).  The partition matters
    mechanically, not just narratively: a reachable leader's same-tick
    heartbeat resets the forced timer before the campaign check, so
    without the cut the force mostly no-ops.  With pre_vote off the
    victim's term climbs one notch per forced tick and drags the cluster
    through term churn at every heal; with pre_vote on each forced
    campaign is a non-binding poll the unreachable quorum never grants,
    and the term stays near baseline —
    ``tools/dst_sweep.py --term-inflation-demo`` pins the contrast."""
    kv, kw = jax.random.split(key)
    victim = jax.random.randint(kv, (), 0, cfg.n)
    gate = _windows(kw, ticks, 2, max(3, cfg.election_tick))
    is_victim = jnp.arange(cfg.n, dtype=I32) == victim
    inflate = gate[:, None] & is_victim[None, :]
    cut = is_victim[None, :, None] | is_victim[None, None, :]
    drop = gate[:, None, None] & cut
    return dataclasses.replace(_no_faults(cfg, ticks),
                               drop=drop, term_inflate=inflate)


def _gen_disruptive_rejoin(key, cfg: SimConfig, ticks: int
                           ) -> FaultSchedule:
    """arXiv:2601.00273 disruptive-rejoin shape: ONE random victim row is
    fully partitioned for ~2 election timeouts and fires its election
    timer every cut tick (inflating its term with pre_vote off), then the
    partition HEALS while the barrage keeps firing for ~3 more timeouts —
    the healed node rejoins with a high term and a campaign storm.  With
    the defenses off (pre_vote=False, check_quorum=False) every barrage
    tick deposes the standing leader; with them on the barrage is
    lease-refused non-binding polls and churn stays at the initial
    election — ``tools/dst_sweep.py --disruptive-rejoin-demo`` pins the
    contrast under an SLO_LEADER_CHURN budget."""
    kv, ks = jax.random.split(key)
    T = cfg.election_tick
    victim = jax.random.randint(kv, (), 0, cfg.n)
    start = jax.random.randint(ks, (), 2 * T,
                               max(2 * T + 1, ticks - 5 * T))
    heal = start + 2 * T
    t = jnp.arange(ticks, dtype=I32)
    cut_gate = (t >= start) & (t < heal)                         # [T]
    # one campaign every OTHER election timeout, not per tick: each
    # firing deposes the standing leader and LETS the re-election finish
    # (randomized timeouts make that up to 2T), so the damage lands in
    # completed leader changes — the churn histogram counts wins; a
    # per-tick barrage would just hold the cluster leaderless, which
    # SLO_LEADER_CHURN cannot see.  The barrage runs to the end of the
    # run: longer sweeps see proportionally more churn.
    barrage = (t >= start) & ((t - start) % (2 * T) == 0)
    is_victim = jnp.arange(cfg.n, dtype=I32) == victim
    touches = is_victim[None, :, None] | is_victim[None, None, :]
    drop = cut_gate[:, None, None] & touches
    rejoin = barrage[:, None] & is_victim[None, :]
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop,
                               rejoin_campaign=rejoin)


def _gen_vote_equivocation(key, cfg: SimConfig, ticks: int
                           ) -> FaultSchedule:
    """Faulty voters that forget their persisted grant (crash-restart
    without fsyncing the vote) under engineered rival candidacies.

    Two quorums among n rows overlap in at least ``f = 2*quorum - n``
    rows, so exactly f equivocating voters suffice for a dual election.
    Rows A and B are forced to campaign on the SAME tick k (same new
    term).  On tick k each rival is kept one voter short of quorum: A's
    requests reach only the f designated equivocators (who grant A), B's
    only his q-1-f loyalists — no one wins, so every log stays empty and
    the later grants cannot be refused on log freshness.  From tick k+1
    the equivocators' vote registers are wiped every tick and A's
    requests to them are cut, so B's re-request lands on an empty
    register and they grant the SAME term twice; meanwhile the remaining
    bystanders (cut from B) grant A.  Both rivals reach quorum on tick
    k+1: two leaders in one term, the textbook ElectionSafety violation.
    cfg.vote_guard (the WAL-shadow register the wipe cannot touch) makes
    the second grant unrepresentable and A wins alone.  Runs are expected
    with check_quorum=False on BOTH sides of the defense comparison (the
    CheckQuorum lease refuses re-requests for the unrelated reason of
    fresh leader contact, masking the hole this profile exists to
    expose)."""
    kp, kt = jax.random.split(key)
    n = cfg.n
    T = cfg.election_tick
    q = n // 2 + 1
    f = 2 * q - n                    # equivocators needed (1 odd, 2 even)
    perm = jax.random.permutation(kp, jnp.arange(n, dtype=I32))
    pos = jnp.zeros((n,), I32).at[perm].set(jnp.arange(n, dtype=I32))
    a, b = perm[0], perm[1]
    is_v = (pos >= 2) & (pos < 2 + f)            # equivocating voters
    is_loy = (pos >= 2 + f) & (pos < 1 + q)      # B's q-1-f loyalists
    is_x = pos >= 1 + q                          # A's k+1 bystanders
    k = jax.random.randint(kt, (), 1, max(2, min(T, ticks - 3)))
    t = jnp.arange(ticks, dtype=I32)
    row = jnp.arange(n, dtype=I32)
    at_k = t == k
    after = t > k
    # both rivals' election timers forced due on tick k -> same new term
    rejoin = at_k[:, None] & ((row == a) | (row == b))[None, :]
    row_a, row_b = row == a, row == b
    # tick k: A reaches only the equivocators, B only his loyalists
    cut_k = (row_a[:, None] & (~is_v & ~row_a)[None, :]) \
        | (row_b[:, None] & (~is_loy & ~row_b)[None, :])
    # afterwards: A never reaches the equivocators again (their empty
    # logs stay empty and their re-grant goes to B), B never reaches the
    # bystanders or A (they complete A's quorum undisturbed)
    cut_after = (row_a[:, None] & (is_v | row_b)[None, :]) \
        | (row_b[:, None] & (is_x | row_a)[None, :])
    drop = (at_k[:, None, None] & cut_k[None, :, :]) \
        | (after[:, None, None] & cut_after[None, :, :])
    equiv = after[:, None] & is_v[None, :]
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop,
                               rejoin_campaign=rejoin,
                               vote_equivocate=equiv)


def _gen_append_flood(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """Targeted client flood against an isolated leader: once the first
    election has settled, a ~2-timeout window isolates whoever currently
    leads (the straggler-making cut) while every tick of the window
    stuffs cfg.max_props extra appends into all proposal-accepting rows.
    The quorum-less leader cannot commit, so its uncommitted tail races
    toward ring capacity — compaction pressure with nothing to compact.
    cfg.prop_inflight_cap caps the tail at the client interface and
    SLO_LOG_OCCUPANCY witnesses the bound."""
    ks, kd = jax.random.split(key)
    T = cfg.election_tick
    start = jax.random.randint(ks, (), 2 * T,
                               max(2 * T + 1, ticks - 3 * T))
    t = jnp.arange(ticks, dtype=I32)
    window = (t >= start) & (t < start + 2 * T)                  # [T]
    drop = (jax.random.uniform(kd, (ticks, cfg.n, cfg.n)) < 0.02)
    return dataclasses.replace(_no_faults(cfg, ticks), drop=drop,
                               target_leader=window,
                               append_flood=window)


def _gen_transfer_abuse(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """Leadership ping-pong: after the first election settles, two random
    rows alternate as the demanded transfer target on a fast flap, so
    every standing leader is immediately asked to hand off — each
    completed handoff is a TIMEOUT_NOW election (leader churn with no
    fault cover).  cfg.transfer_cooldown_ticks rate-limits the handoffs
    and SLO_LEADER_CHURN bounds the residual."""
    ka, kb, kw = jax.random.split(key, 3)
    T = cfg.election_tick
    a = jax.random.randint(ka, (), 0, cfg.n)
    b = jax.random.randint(kb, (), 0, cfg.n)
    t = jnp.arange(ticks, dtype=I32)
    settled = t >= 2 * T
    flip = _windows(kw, ticks, 2, max(3, T // 2))
    row = jnp.arange(cfg.n, dtype=I32)
    tgt = jnp.where(flip, a, b)                                  # [T]
    abuse = settled[:, None] & (row[None, :] == tgt[:, None])
    return dataclasses.replace(_no_faults(cfg, ticks),
                               transfer_abuse=abuse)


def _gen_lost_tail(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """Correlated power loss: EVERY row crashes on the same tick (drawn
    after the first election settles) for a short outage, and each loses
    its unsynced log suffix — the cluster-wide fsync gap that is the
    classic acked-then-lost Raft failure.  With cfg.ack_gating off and a
    lazy fsync policy, commit outruns every sync_mark and the shared
    truncation deletes acked-as-committed entries from all n logs
    (DURABILITY trips); with gating on a commit implies a durable quorum
    and the identical schedule is clean."""
    ks, kd = jax.random.split(key)
    T = cfg.election_tick
    crash_at = jax.random.randint(ks, (), 2 * T, max(2 * T + 1, ticks - 3))
    down_for = jax.random.randint(kd, (), 2, max(3, T))
    t = jnp.arange(ticks, dtype=I32)
    downed = (t >= crash_at) & (t < crash_at + down_for)           # [T]
    alive = jnp.broadcast_to(~downed[:, None], (ticks, cfg.n))
    lost = jnp.broadcast_to((t == crash_at)[:, None], (ticks, cfg.n))
    return dataclasses.replace(_no_faults(cfg, ticks), alive=alive,
                               lost_tail=lost)


def _gen_torn_write(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """ONE victim row crashes mid-run and recovery finds its last durable
    entry torn — the single-disk lying-fsync fault.  Replication covers
    it: every committed entry survives on the other n-1 logs, the victim
    re-fetches its truncated tail, and the sweep pins the run clean under
    gating while counting the RECOVER_TORN signature.  (A correlated
    all-row tear is deliberately NOT this generator — that is beyond any
    quorum system's fault model.)"""
    kv, ks, kd = jax.random.split(key, 3)
    T = cfg.election_tick
    victim = jax.random.randint(kv, (), 0, cfg.n)
    crash_at = jax.random.randint(ks, (), 2 * T, max(2 * T + 1, ticks - 3))
    down_for = jax.random.randint(kd, (), 2, max(3, T))
    t = jnp.arange(ticks, dtype=I32)
    is_v = jnp.arange(cfg.n, dtype=I32) == victim
    downed = ((t >= crash_at) & (t < crash_at + down_for))[:, None] \
        & is_v[None, :]
    torn = (t == crash_at)[:, None] & is_v[None, :]
    return dataclasses.replace(_no_faults(cfg, ticks), alive=~downed,
                               torn_write=torn)


def _gen_snap_corrupt(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """ONE victim row is crashed long enough to fall behind the leader's
    Phase-F compaction horizon, then restarts and the leader must send a
    SNAPSHOT — which fails its restore checksum on every tick of the
    post-restart window.  Under cfg.ack_gating the victim refuses each
    corrupt install and keeps re-requesting; after the window the clean
    re-send installs and the victim catches up.  Without gating the
    first corrupt image installs and poisons the checksum chain
    (CHECKSUM_AGREEMENT trips).  The cut is a CRASH, not a partition: an
    isolated-but-ticking victim would campaign itself into a high-term
    candidate the lease-protected cluster ignores (the PreVote rejoin
    livelock), and a candidate never installs the snapshot under test."""
    kv, ks = jax.random.split(key)
    T = cfg.election_tick
    victim = jax.random.randint(kv, (), 0, cfg.n)
    start = jax.random.randint(ks, (), 2 * T, max(2 * T + 1, ticks - 8 * T))
    heal = start + 5 * T
    t = jnp.arange(ticks, dtype=I32)
    cut = (t >= start) & (t < heal)                                # [T]
    is_v = jnp.arange(cfg.n, dtype=I32) == victim
    alive = ~(cut[:, None] & is_v[None, :])
    bad = ((t >= heal) & (t < heal + 2 * T))[:, None] & is_v[None, :]
    return dataclasses.replace(_no_faults(cfg, ticks), alive=alive,
                               snap_corrupt=bad)


def _gen_disk_stall(key, cfg: SimConfig, ticks: int) -> FaultSchedule:
    """A random MAJORITY of rows shares a slow disk: their fsyncs freeze
    on flapping windows that straddle the election timeout.  Under
    cfg.ack_gating the stalled quorum's acks lag with their watermarks
    and commit stalls for the window — the bounded brownout whose
    unsynced suffix SLO_FSYNC_LAG budgets (cfg.prop_inflight_cap caps
    its growth at the client interface)."""
    kq, kw = jax.random.split(key)
    q = cfg.n // 2 + 1
    perm = jax.random.permutation(kq, jnp.arange(cfg.n, dtype=I32))
    pos = jnp.zeros((cfg.n,), I32).at[perm].set(
        jnp.arange(cfg.n, dtype=I32))
    stalled = pos < q
    T = cfg.election_tick
    gate = _windows(kw, ticks, T, 3 * T)
    settled = jnp.arange(ticks, dtype=I32) >= 2 * T
    stall = (gate & settled)[:, None] & stalled[None, :]
    return dataclasses.replace(_no_faults(cfg, ticks), disk_stall=stall)


_GENERATORS = {
    "random_drop": _gen_random_drop,
    "partition_flapper": _gen_partition_flapper,
    "leader_targeted": _gen_leader_targeted,
    "asymmetric_links": _gen_asymmetric_links,
    "crash_restart": _gen_crash_restart,
    "crash_during_campaign": _gen_crash_during_campaign,
    "stale_leader_reads": _gen_stale_leader_reads,
    "term_inflation": _gen_term_inflation,
    "disruptive_rejoin": _gen_disruptive_rejoin,
    "vote_equivocation": _gen_vote_equivocation,
    "append_flood": _gen_append_flood,
    "transfer_abuse": _gen_transfer_abuse,
    "lost_tail": _gen_lost_tail,
    "torn_write": _gen_torn_write,
    "snap_corrupt": _gen_snap_corrupt,
    "disk_stall": _gen_disk_stall,
}


def make_schedule(cfg: SimConfig, ticks: int, profile: str,
                  seed: int, index: int = 0) -> FaultSchedule:
    """One schedule: profile generator keyed by fold_in(seed, index)."""
    gen = _GENERATORS.get(profile)
    if gen is None:
        raise KeyError(f"unknown adversary profile {profile!r}; "
                       f"known: {PROFILES + EXTRA_PROFILES}")
    key = jax.random.fold_in(jax.random.PRNGKey(seed), index)
    return gen(key, cfg, ticks)


# FaultSchedule leaves that default to None (old artifacts keep tracing
# the pre-extension program) and their gate shape: "T" -> [ticks],
# "TN" -> [ticks, n].  make_batch promotes absent leaves to all-False
# zeros of this shape when any schedule in the batch carries the leaf.
_OPTIONAL_LEAVES = {
    "term_inflate": "TN",
    "rejoin_campaign": "TN",
    "vote_equivocate": "TN",
    "append_flood": "T",
    "transfer_abuse": "TN",
    "lost_tail": "TN",
    "torn_write": "TN",
    "snap_corrupt": "TN",
    "disk_stall": "TN",
}


def make_batch(cfg: SimConfig, ticks: int, schedules: int, seed: int,
               profiles=PROFILES) -> tuple[FaultSchedule, list[str]]:
    """[S, ...] stacked schedules + the profile name of each index.

    Profiles are dealt round-robin over the schedule axis; each index's key
    is fold_in(seed, index), independent of the batch size, so schedule
    (seed, profile, index) is stable however wide the sweep runs.
    """
    profiles = tuple(profiles)
    names = [profiles[s % len(profiles)] for s in range(schedules)]
    base = jax.random.PRNGKey(seed)
    parts = []
    for s, name in enumerate(names):
        parts.append((s, _GENERATORS[name], jax.random.fold_in(base, s)))
    # group by generator so each profile's sub-batch is ONE vmapped call
    stacks: dict[int, FaultSchedule] = {}
    for gen in {g for _, g, _ in parts}:
        idx = [s for s, g, _ in parts if g is gen]
        keys = jnp.stack([k for s, g, k in parts if g is gen])
        sub = jax.vmap(lambda k, g=gen: g(k, cfg, ticks))(keys)
        for pos, s in enumerate(idx):
            stacks[s] = jax.tree_util.tree_map(lambda a: a[pos], sub)
    scheds = [stacks[s] for s in range(schedules)]
    # a batch mixing attack profiles with attack-less ones must agree on
    # tree structure: promote absent optional leaves to all-False gates
    # (value-identical — every verb is the identity on an all-False mask)
    for leaf, shape in _OPTIONAL_LEAVES.items():
        if any(getattr(s, leaf) is not None for s in scheds):
            dims = (ticks,) if shape == "T" else (ticks, cfg.n)
            zero = jnp.zeros(dims, bool)
            scheds = [dataclasses.replace(s, **{leaf: zero})
                      if getattr(s, leaf) is None else s for s in scheds]
    batch = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *scheds)
    return batch, names


def from_fault_plan(cfg: SimConfig, plan, rows: dict[str, int], ticks: int,
                    inject_at: int = 0, heal_at=None,
                    seed: int = 0) -> FaultSchedule:
    """Lower a declarative `raft.faults.FaultPlan` into a FaultSchedule.

    `rows` maps the plan's wire addresses to kernel row indices.  The
    actual lowering lives next to the plan vocabulary
    (``raft.faults.plan_to_schedule``); this wraps its numpy output in the
    device dataclass with the state-conditioned gates off.
    """
    from swarmkit_tpu.raft.faults import plan_to_schedule

    arrs = plan_to_schedule(plan, rows, n=cfg.n, ticks=ticks,
                            inject_at=inject_at, heal_at=heal_at, seed=seed)
    return FaultSchedule(
        drop=jnp.asarray(arrs["drop"]),
        alive=jnp.asarray(arrs["alive"]),
        target_leader=jnp.zeros((ticks,), bool),
        crash_campaign=jnp.zeros((ticks,), bool))

"""Deterministic simulation testing (DST) for the batched raft kernel.

FoundationDB-style schedule search at XLA speed: the tick kernel already
advances N simulated managers as rows of device arrays, so exploring S
adversarial fault schedules is ONE more leading vmap axis — S x N clusters
advance per tick in a single jitted scan, with raft's safety properties
checked on device every tick (see PAPERS.md: Raft in mCRL2 arXiv:2403.18916
and LNT arXiv:2004.13284 do this by explicit-state model checking; "From
Consensus to Chaos" arXiv:2601.00273 by searching fault schedules).

Layout:

- :mod:`schedule`  — `FaultSchedule` (stacked per-tick drop/partition
  matrices, crash windows, adversary gates) + the seeded `jax.random`
  generator and its named adversary profiles.
- :mod:`invariants` — on-device checkers (ElectionSafety, LogMatching,
  LeaderCompleteness, commit monotonicity, applied-checksum agreement,
  read linearizability) reduced into a per-schedule violation bitmask.
- :mod:`explore`   — `explore()`: the vmapped scan driver.
- :mod:`repro`     — counterexample pipeline: host extraction, differential
  oracle replay (field-level trace), greedy shrinking, seed-pinned JSON
  artifacts replayable by ``tools/dst_sweep.py``.
"""

from swarmkit_tpu.dst.schedule import (
    ATTACK_LEAVES, ATTACK_PROFILES, ATTACK_SIGNATURE_CODES, EXTRA_PROFILES,
    PROFILES, STORAGE_LEAVES, STORAGE_PROFILES, STORAGE_SIGNATURE_CODES,
    FaultSchedule, apply_append_flood, apply_disk_stall, apply_lost_tail,
    apply_rejoin_campaign, apply_snap_corrupt, apply_term_inflation,
    apply_torn_write, apply_transfer_abuse, apply_vote_equivocation,
    from_fault_plan, make_batch, make_schedule,
)
from swarmkit_tpu.dst.invariants import (
    BIT_NAMES, CHECKSUM_AGREEMENT, COMMIT_MONOTONIC, DURABILITY,
    ELECTION_SAFETY, LEADER_COMPLETENESS, LINEARIZABLE_READ, LOG_MATCHING,
    RECOVERY_MONOTONIC, SAFETY_BITS, SLO_COMMIT_P99, SLO_FSYNC_LAG,
    SLO_LEADER_CHURN, SLO_LOG_OCCUPANCY,
    bits_to_names, check_state, check_transition,
)
from swarmkit_tpu.dst.explore import ExploreResult, explore, postmortem
from swarmkit_tpu.dst.repro import (
    capture_flight, fault_count, from_artifact, load_artifact, oracle_trace,
    replay, replay_artifact, save_artifact, shrink, to_artifact,
)

__all__ = [
    "ATTACK_LEAVES", "ATTACK_PROFILES", "ATTACK_SIGNATURE_CODES",
    "EXTRA_PROFILES", "PROFILES", "STORAGE_LEAVES", "STORAGE_PROFILES",
    "STORAGE_SIGNATURE_CODES", "FaultSchedule", "apply_append_flood",
    "apply_disk_stall", "apply_lost_tail", "apply_rejoin_campaign",
    "apply_snap_corrupt", "apply_term_inflation", "apply_torn_write",
    "apply_transfer_abuse", "apply_vote_equivocation", "from_fault_plan",
    "make_batch", "make_schedule",
    "BIT_NAMES", "CHECKSUM_AGREEMENT", "COMMIT_MONOTONIC", "DURABILITY",
    "ELECTION_SAFETY", "LEADER_COMPLETENESS", "LINEARIZABLE_READ",
    "LOG_MATCHING", "RECOVERY_MONOTONIC", "SAFETY_BITS", "SLO_COMMIT_P99",
    "SLO_FSYNC_LAG", "SLO_LEADER_CHURN", "SLO_LOG_OCCUPANCY",
    "bits_to_names", "check_state", "check_transition",
    "ExploreResult", "explore", "postmortem",
    "capture_flight", "fault_count", "from_artifact", "load_artifact",
    "oracle_trace", "replay", "replay_artifact", "save_artifact", "shrink",
    "to_artifact",
]

"""On-device raft safety checkers, reduced to a violation bitmask.

Each checker is a vectorized reduction over one cluster's SimState — no
host round trip, so the explore scan evaluates all of them for S x N
clusters every tick at array cost.  The formulations are the observable
forms of the Raft paper's Figure 3 properties (the mCRL2/LNT encodings in
PAPERS.md check the same five):

ELECTION_SAFETY      at most one leader per term among current leaders.
LOG_MATCHING         if two logs hold the same (index, term), the entries
                     carry the same payload.
LEADER_COMPLETENESS  a leader at the current globally-maximal term holds
                     every committed entry (last >= max commit).  Sound:
                     any commit reflected in some row's commit index was
                     decided at a term <= the global max term; if decided
                     AT the max term, the unique max-term leader decided
                     it himself — either way the entry is in his log.
                     Stale minority leaders (term < max) are exempt, as
                     the property requires.
COMMIT_MONOTONIC     per-row commit/applied never regress across one tick,
                     and applied never passes commit (transition check).
CHECKSUM_AGREEMENT   equal applied index => equal applied-state checksum
                     (state-machine safety; sourced through
                     ``run.quorum_applied_checksum``).
LINEARIZABLE_READ    no served read batch observed a state missing a
                     write acknowledged before the batch was submitted:
                     read_srv_idx (applied at serve) >= read_srv_goal
                     (max(commit) anywhere at submit).  Only checked
                     when the read path is compiled in
                     (cfg.read_batch > 0); the goal register is pure
                     oracle bookkeeping the serving decisions never
                     read, exactly like apply_chk for checksums.
SLO_COMMIT_P99       OPTIONAL performance oracle (not a Raft safety
                     property): the device-computed p99 propose->commit
                     latency bucket edge exceeds
                     cfg.slo_p99_commit_ticks.  Only checked when the
                     bound is set (> 0, which requires
                     cfg.collect_telemetry) and samples exist — latency
                     anomalies flag protocol-level attacks (term
                     inflation, election storms) long before a safety
                     invariant trips.
SLO_LEADER_CHURN     OPTIONAL availability oracle: cumulative election
                     wins (the election-histogram mass) exceed
                     cfg.slo_leader_changes.  Bounds the residual cost
                     of the disruptive_rejoin / transfer_abuse defenses
                     — a defended cluster may still change leaders, but
                     only this many times over the run.  Needs
                     cfg.collect_telemetry like SLO_COMMIT_P99.
SLO_LOG_OCCUPANCY    OPTIONAL backpressure oracle: some row's uncommitted
                     tail max(last - commit) exceeds cfg.slo_log_occupancy.
                     The witness that the append_flood defense
                     (prop_inflight_cap) keeps ring/compaction pressure
                     bounded — the cap gates acceptance on exactly this
                     tail, while total occupancy sum(last - snap_idx)
                     would count committed-but-uncompacted entries a
                     HEALTHY flooded leader legitimately accumulates
                     (compaction is lazy).  Computed straight from cursor
                     state, so it needs no telemetry plane.
DURABILITY           no entry ever acked-as-committed is absent from
                     every log after any crash schedule:
                     max(ack_frontier) <= max(last) cluster-wide.
                     ack_frontier is pure oracle bookkeeping (the
                     running max of observed commit, never read by
                     decisions, never touched by storage verbs), so the
                     check is exactly "did a storage fault delete
                     something the cluster told a client was committed".
                     Only checked when the storage model is armed
                     (cfg.fsync_lag_ticks >= 1): with ack-gating off and
                     lost_tail armed it must trip; with gating on it
                     must not.
RECOVERY_MONOTONIC   recovery never regresses a row's durable commit
                     record: dur_commit is non-decreasing across every
                     tick, storage verbs included (transition check,
                     storage-gated like DURABILITY).
SLO_FSYNC_LAG        OPTIONAL durability-lag oracle: some row's unsynced
                     suffix max(last - sync_mark) exceeds
                     cfg.slo_fsync_lag.  The witness that bounds a
                     disk_stall brownout — prop_inflight_cap stops the
                     suffix growing at the client interface, so the
                     defended bound is the cap plus the commit/sync
                     spread, while an undefended stall grows it by the
                     propose rate per stalled tick.
"""

from __future__ import annotations

import jax.numpy as jnp

from swarmkit_tpu.raft.sim.run import quorum_applied_checksum
from swarmkit_tpu.raft.sim.state import LEADER, SimConfig, SimState

U32 = jnp.uint32

ELECTION_SAFETY = 1 << 0
LOG_MATCHING = 1 << 1
LEADER_COMPLETENESS = 1 << 2
COMMIT_MONOTONIC = 1 << 3
CHECKSUM_AGREEMENT = 1 << 4
LINEARIZABLE_READ = 1 << 5
SLO_COMMIT_P99 = 1 << 6
SLO_LEADER_CHURN = 1 << 7
SLO_LOG_OCCUPANCY = 1 << 8
DURABILITY = 1 << 9
RECOVERY_MONOTONIC = 1 << 10
SLO_FSYNC_LAG = 1 << 11

BIT_NAMES = {
    ELECTION_SAFETY: "election_safety",
    LOG_MATCHING: "log_matching",
    LEADER_COMPLETENESS: "leader_completeness",
    COMMIT_MONOTONIC: "commit_monotonic",
    CHECKSUM_AGREEMENT: "checksum_agreement",
    LINEARIZABLE_READ: "linearizable_read",
    SLO_COMMIT_P99: "slo_commit_p99",
    SLO_LEADER_CHURN: "slo_leader_churn",
    SLO_LOG_OCCUPANCY: "slo_log_occupancy",
    DURABILITY: "durability",
    RECOVERY_MONOTONIC: "recovery_monotonic",
    SLO_FSYNC_LAG: "slo_fsync_lag",
}
ALL_BITS = tuple(BIT_NAMES)
# Bits whose violation leaves the kernel in a state CORRECT raft cannot
# represent (e.g. two leaders sharing a term after vote_equivocation, or
# an acked-as-committed entry deleted from every log by lost_tail) — the
# differential oracle is only comparable over the clean prefix of such
# runs.  The SLO_* bits are telemetry bounds: state stays legal.
SAFETY_BITS = (ELECTION_SAFETY | LOG_MATCHING | LEADER_COMPLETENESS
               | COMMIT_MONOTONIC | CHECKSUM_AGREEMENT | LINEARIZABLE_READ
               | DURABILITY | RECOVERY_MONOTONIC)


def bits_to_names(bits: int) -> list[str]:
    return [name for bit, name in BIT_NAMES.items() if bits & bit]


def _bit(cond, bit: int):
    return jnp.where(cond, jnp.uint32(bit), jnp.uint32(0))


def _live_index(state: SimState, cfg: SimConfig):
    """Per (row, slot): the live 1-based log index stored there, and its
    validity.  Slot of index i is (i-1) % L and the ring holds
    (snap_idx, last], so slot l of row r holds index
    snap_idx[r] + 1 + ((l - snap_idx[r]) mod L) iff that is <= last[r]."""
    L = cfg.log_len
    slot = jnp.arange(L, dtype=jnp.int32)[None, :]
    snap = state.snap_idx[:, None]
    idx = snap + 1 + jnp.mod(slot - snap, L)
    return idx, idx <= state.last[:, None]


def check_state(state: SimState, cfg: SimConfig) -> jnp.ndarray:
    """uint32 bitmask of the per-tick (non-transition) invariants."""
    leaders = state.role == LEADER

    # -- ELECTION_SAFETY: no two current leaders share a term
    lterm = jnp.where(leaders, state.term, -1)
    same = (lterm[:, None] == lterm[None, :]) \
        & leaders[:, None] & leaders[None, :] \
        & ~jnp.eye(cfg.n, dtype=bool)
    elect = _bit(jnp.any(same), ELECTION_SAFETY)

    # -- LOG_MATCHING: same (index, term) in two rings => same payload
    idx, valid = _live_index(state, cfg)
    both = valid[:, None, :] & valid[None, :, :]    # [N, N, L] (ring slots
    # are index-determined, so idx equality per slot is snap-independent
    # only when snaps differ mod L — compare explicitly to stay exact)
    same_idx = idx[:, None, :] == idx[None, :, :]
    same_term = state.log_term[:, None, :] == state.log_term[None, :, :]
    diff_data = state.log_data[:, None, :] != state.log_data[None, :, :]
    match = _bit(jnp.any(both & same_idx & same_term & diff_data),
                 LOG_MATCHING)

    # -- LEADER_COMPLETENESS: max-term leaders hold every committed entry
    top = leaders & (state.term == jnp.max(state.term))
    complete = _bit(jnp.any(top & (state.last < jnp.max(state.commit))),
                    LEADER_COMPLETENESS)

    # -- CHECKSUM_AGREEMENT: equal applied => equal checksum
    applied, chk = quorum_applied_checksum(state)
    agree = (applied[:, None] == applied[None, :]) \
        & (chk[:, None] != chk[None, :])
    chk_bit = _bit(jnp.any(agree), CHECKSUM_AGREEMENT)

    # -- LINEARIZABLE_READ: every served batch saw the writes acked
    # before it was submitted (Python-gated on the read path's registers,
    # so reads-off sweeps trace the same five-checker program as before)
    read_bit = jnp.uint32(0)
    if state.read_srv_idx is not None:
        read_bit = _bit(jnp.any(state.read_srv_idx < state.read_srv_goal),
                        LINEARIZABLE_READ)

    # -- SLO_COMMIT_P99: optional latency oracle over the telemetry
    # histogram (Python-gated on both the bound and the telemetry plane,
    # so every existing sweep traces the same checker program)
    slo_bit = jnp.uint32(0)
    if cfg.slo_p99_commit_ticks > 0 and state.tel_commit_hist is not None:
        from swarmkit_tpu.telemetry import series as _tel
        total = jnp.sum(state.tel_commit_hist)
        edge = _tel.percentile_edge_device(state.tel_commit_hist, 99)
        slo_bit = _bit((total > 0) & (edge > cfg.slo_p99_commit_ticks),
                       SLO_COMMIT_P99)

    # -- SLO_LEADER_CHURN: the availability bound on the rejoin/transfer
    # defenses — cumulative election wins over the run stay under the
    # budget (gated like SLO_COMMIT_P99: bound set + telemetry carried)
    churn_bit = jnp.uint32(0)
    if cfg.slo_leader_changes > 0 and state.tel_elect_hist is not None:
        churn_bit = _bit(jnp.sum(state.tel_elect_hist)
                         > cfg.slo_leader_changes, SLO_LEADER_CHURN)

    # -- SLO_LOG_OCCUPANCY: the append_flood backpressure witness —
    # every row's uncommitted tail stays under the budget.  The tail is
    # what prop_inflight_cap gates acceptance on (kernel _leader_ok), so
    # the defended bound is cap - 1 + max_props regardless of flood
    # duration, while an UNDEFENDED isolated leader grows its tail by
    # max_props per flooded tick until the ring's room check stops it.
    # Pure cursor arithmetic, so only the bound gates it.
    occ_bit = jnp.uint32(0)
    if cfg.slo_log_occupancy > 0:
        occ_bit = _bit(jnp.max(state.last - state.commit)
                       > cfg.slo_log_occupancy, SLO_LOG_OCCUPANCY)

    # -- DURABILITY: every entry the cluster ever counted committed still
    # exists on SOME log (Python-gated on the storage model, so
    # storage-off sweeps trace the exact prior checker program)
    dur_bit = jnp.uint32(0)
    if state.ack_frontier is not None:
        dur_bit = _bit(jnp.max(state.ack_frontier) > jnp.max(state.last),
                       DURABILITY)

    # -- SLO_FSYNC_LAG: the disk_stall brownout bound — every row's
    # unsynced suffix stays under the budget (bound set + storage armed)
    flag_bit = jnp.uint32(0)
    if cfg.slo_fsync_lag > 0 and state.sync_mark is not None:
        flag_bit = _bit(jnp.max(state.last - state.sync_mark)
                        > cfg.slo_fsync_lag, SLO_FSYNC_LAG)

    return (elect | match | complete | chk_bit | read_bit | slo_bit
            | churn_bit | occ_bit | dur_bit | flag_bit)


def check_transition(prev: SimState, new: SimState,
                     recovering=None) -> jnp.ndarray:
    """uint32 bitmask of the across-one-tick invariants (the kernel models
    durable state: even a crashed/restarted row never loses its commit).

    `recovering` (bool [N], optional) marks rows a storage-fault verb
    legally truncated THIS tick — lost_tail / torn_write rebuild volatile
    commit/applied from durable registers, the one sanctioned regression.
    Their durable record is still pinned: RECOVERY_MONOTONIC checks
    dur_commit never falls for ANY row, recovering or not."""
    commit_ok = new.commit >= prev.commit
    applied_ok = new.applied >= prev.applied
    if recovering is not None:
        commit_ok = commit_ok | recovering
        applied_ok = applied_ok | recovering
    regress = jnp.any(~commit_ok) | jnp.any(~applied_ok) \
        | jnp.any(new.applied > new.commit)
    bits = _bit(regress, COMMIT_MONOTONIC)
    if new.dur_commit is not None and prev.dur_commit is not None:
        bits = bits | _bit(jnp.any(new.dur_commit < prev.dur_commit),
                           RECOVERY_MONOTONIC)
    return bits

"""Counterexample pipeline: replay, field-level oracle trace, shrinking,
and seed-pinned JSON repro artifacts.

A violating schedule index found by `explore()` flows through four steps:

1. `replay()` — re-run the single schedule through the same compiled tick
   path and confirm the violation bits + first tick reproduce (the whole
   subsystem is counter-seeded, so this is exact, not statistical).
2. `oracle_trace()` — drive the schedule tick-by-tick through BOTH the
   kernel and the host differential oracle (`raft/sim/oracle.py`) and
   record the first tick where any comparable field diverges — the
   field-level trace that localizes a kernel bug.
3. `shrink()` — greedy delta-debugging over the schedule arrays: clear
   tick chunks, then whole edges, then whole-row outages, keeping each
   clearing iff the violation persists.  Minimal repros replay in
   milliseconds instead of re-searching.
4. `to_artifact()`/`save_artifact()` — dump the shrunk schedule (sparse),
   the SimConfig, and the pinned provenance (sweep seed, profile, index,
   mutation) as JSON; ``tools/dst_sweep.py --replay`` re-runs it through
   steps 1-2, turning every caught bug into a one-command regression.
"""

from __future__ import annotations

import dataclasses
import json
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from swarmkit_tpu.dst.explore import _tick_one
from swarmkit_tpu.dst.invariants import bits_to_names
from swarmkit_tpu.dst.schedule import _OPTIONAL_LEAVES, FaultSchedule
from swarmkit_tpu.raft.sim.state import CANDIDATE, LEADER, NONE, SimConfig, \
    init_state

ARTIFACT_VERSION = 1


# ---------------------------------------------------------------------------
# single-schedule replay (the shrinker's oracle — compiled once, ~ms/call)


@partial(jax.jit, static_argnames=("cfg", "prop_count", "mutation"))
def _replay_compiled(state, cfg: SimConfig, schedule: FaultSchedule,
                     prop_count: int, mutation: Optional[str]):
    def body(carry, sched_t):
        st, acc = carry
        new, bits = _tick_one(st, cfg, sched_t, prop_count, mutation)
        return (new, acc | bits), bits

    init = (state, jnp.uint32(0))
    (final, viol), bits = jax.lax.scan(body, init, schedule)
    any_t = bits > 0
    first = jnp.where(jnp.any(any_t), jnp.argmax(any_t), -1)
    return viol, first.astype(jnp.int32)


def replay(cfg: SimConfig, schedule: FaultSchedule, prop_count: int = 2,
           mutation: Optional[str] = None) -> tuple[int, int]:
    """(violation bits, first violating tick or -1) for ONE schedule."""
    schedule = jax.tree_util.tree_map(jnp.asarray, schedule)
    viol, first = _replay_compiled(init_state(cfg), cfg, schedule,
                                   prop_count, mutation)
    return int(viol), int(first)


# ---------------------------------------------------------------------------
# flight-recorder post-mortem (re-run one schedule with recording on)


@partial(jax.jit, static_argnames=("cfg", "prop_count", "mutation"))
def _replay_final(state, cfg: SimConfig, schedule: FaultSchedule,
                  prop_count: int, mutation: Optional[str]):
    def body(carry, sched_t):
        st, acc = carry
        new, bits = _tick_one(st, cfg, sched_t, prop_count, mutation)
        return (new, acc | bits), bits

    (final, viol), bits = jax.lax.scan(body, (state, jnp.uint32(0)),
                                       schedule)
    any_t = bits > 0
    first = jnp.where(jnp.any(any_t), jnp.argmax(any_t), -1)
    return final, viol, first.astype(jnp.int32)


def capture_flight(cfg: SimConfig, schedule: FaultSchedule,
                   prop_count: int = 2, mutation: Optional[str] = None, *,
                   first_tick: int = -1, window: int = 40,
                   trigger: str = "dst_violation", obs=None) -> dict:
    """Re-run ONE schedule with the flight recorder on and return the
    decoded post-mortem: the event window leading up to the violation
    plus the re-run's own verdict.

    The re-run STOPS right after `first_tick` (when known), so the ring's
    tail holds the ticks that produced the violation instead of whatever
    happened afterwards.  Determinism makes this exact: same schedule,
    same seed, same trajectory — recording only adds the ring writes,
    and the telemetry plane (also switched on here so the post-mortem
    carries latency histograms and counter tracks) only adds write-only
    side buffers no decision ever reads.  The violation verdict cannot
    change either: cfg arrives with slo_p99_commit_ticks as the sweep
    set it, so no oracle bit appears that the sweep didn't ask for.
    """
    from swarmkit_tpu.flightrec import record as flight_record
    from swarmkit_tpu.telemetry import summarize_state

    rcfg = dataclasses.replace(cfg, record_events=True,
                               event_ring=max(cfg.event_ring, 128),
                               collect_telemetry=True)
    schedule = jax.tree_util.tree_map(jnp.asarray, schedule)
    if first_tick >= 0:
        stop = min(int(schedule.ticks), first_tick + 1)
        schedule = jax.tree_util.tree_map(lambda a: a[:stop], schedule)
    final, viol, first = _replay_final(init_state(rcfg), rcfg, schedule,
                                       prop_count, mutation)
    rec = flight_record.capture(
        final, trigger=trigger, obs=obs, cfg=rcfg,
        meta={"mutation": mutation, "prop_count": prop_count,
              "violation_bits": int(viol),
              "violations": bits_to_names(int(viol)),
              "first_tick": int(first)})
    return {
        "violation_bits": int(viol),
        "violations": bits_to_names(int(viol)),
        "first_tick": int(first),
        "dropped": rec.dropped,
        "window": [e.to_dict() for e in rec.window(window)],
        "telemetry": summarize_state(final, rcfg),
        "record": rec,
    }


# ---------------------------------------------------------------------------
# greedy shrinking


def fault_count(schedule: FaultSchedule) -> int:
    """Total injected fault-events: dropped edge-ticks + downed row-ticks
    + active adversary-gate ticks + attack-verb gate ticks (the
    shrinker's minimization metric)."""
    verbs = sum(int(np.asarray(getattr(schedule, leaf)).sum())
                for leaf in _OPTIONAL_LEAVES
                if getattr(schedule, leaf) is not None)
    return (int(np.asarray(schedule.drop).sum())
            + int((~np.asarray(schedule.alive)).sum())
            + int(np.asarray(schedule.target_leader).sum())
            + int(np.asarray(schedule.crash_campaign).sum())
            + verbs)


def _clear_ticks(arrs: dict, lo: int, hi: int) -> dict:
    out = {k: v.copy() for k, v in arrs.items()}
    out["drop"][lo:hi] = False
    out["alive"][lo:hi] = True
    out["target_leader"][lo:hi] = False
    out["crash_campaign"][lo:hi] = False
    for leaf in _OPTIONAL_LEAVES:
        if leaf in out:
            out[leaf][lo:hi] = False
    return out


def shrink(cfg: SimConfig, schedule: FaultSchedule, required_bits: int,
           prop_count: int = 2, mutation: Optional[str] = None,
           obs=None) -> tuple[FaultSchedule, int]:
    """Greedily drop faults while any of `required_bits` still trips.

    Returns (minimal schedule, replay evaluations spent).  Three passes:
    tick chunks at halving granularity (ddmin-style), then whole directed
    edges, then whole-row crash histories and the adversary gates.
    """
    from swarmkit_tpu.metrics import catalog
    from swarmkit_tpu.metrics import registry as obs_registry

    obs = obs or obs_registry.DEFAULT
    m_rounds = catalog.get(obs, "swarm_dst_shrink_rounds_total")
    evals = 0

    arrs = {f.name: np.asarray(getattr(schedule, f.name)).copy()
            for f in dataclasses.fields(schedule)
            if getattr(schedule, f.name) is not None}

    def still_fails(cand: dict) -> bool:
        nonlocal evals
        evals += 1
        viol, _ = replay(cfg, FaultSchedule(**cand), prop_count, mutation)
        hit = bool(viol & required_bits)
        m_rounds.labels(result="required" if not hit else "removed").inc()
        return hit

    ticks = arrs["target_leader"].shape[0]

    # pass 1: clear tick windows, halving the chunk size
    size = max(1, ticks // 2)
    while size >= 1:
        lo = 0
        while lo < ticks:
            hi = min(ticks, lo + size)
            cand = _clear_ticks(arrs, lo, hi)
            if any((cand[k] != arrs[k]).any() for k in arrs) \
                    and still_fails(cand):
                arrs = cand
            lo = hi
        if size == 1:
            break
        size //= 2

    # pass 2: clear whole directed edges
    for i in range(cfg.n):
        for j in range(cfg.n):
            if arrs["drop"][:, i, j].any():
                cand = {k: v.copy() for k, v in arrs.items()}
                cand["drop"][:, i, j] = False
                if still_fails(cand):
                    arrs = cand

    # pass 3: clear whole-row outages and forced-campaign histories, then
    # each adversary gate
    for r in range(cfg.n):
        if (~arrs["alive"][:, r]).any():
            cand = {k: v.copy() for k, v in arrs.items()}
            cand["alive"][:, r] = True
            if still_fails(cand):
                arrs = cand
    for leaf, shape in _OPTIONAL_LEAVES.items():
        if leaf not in arrs:
            continue
        if shape == "TN":
            for r in range(cfg.n):
                if arrs[leaf][:, r].any():
                    cand = {k: v.copy() for k, v in arrs.items()}
                    cand[leaf][:, r] = False
                    if still_fails(cand):
                        arrs = cand
        elif arrs[leaf].any():
            cand = {k: v.copy() for k, v in arrs.items()}
            cand[leaf][:] = False
            if still_fails(cand):
                arrs = cand
    for gate in ("target_leader", "crash_campaign"):
        if arrs[gate].any():
            cand = {k: v.copy() for k, v in arrs.items()}
            cand[gate][:] = False
            if still_fails(cand):
                arrs = cand

    return FaultSchedule(**{k: jnp.asarray(v) for k, v in arrs.items()}), \
        evals


# ---------------------------------------------------------------------------
# differential-oracle replay (field-level trace)

_VIEW_FIELDS = ("term", "vote", "role", "lead", "last", "commit", "applied",
                "apply_chk", "member")


def _kernel_view(state) -> dict:
    return {f: np.asarray(getattr(state, f)) for f in _VIEW_FIELDS}


def oracle_trace(cfg: SimConfig, schedule: FaultSchedule,
                 prop_count: int = 2, mutation: Optional[str] = None,
                 stop_after_first: bool = True,
                 until: Optional[int] = None) -> dict:
    """Replay one schedule through kernel AND host oracle, comparing every
    comparable field per tick (the `run_differential` protocol).

    The state-conditioned gates are resolved against the KERNEL's pre-step
    roles on host, and the realized (alive, drop) arrays feed both sides —
    so a mutated (or genuinely buggy) kernel diverges from the correct
    oracle at a deterministic tick, and the returned trace names the first
    differing fields with both sides' values.

    `until` bounds the comparison to ticks t < until.  Callers replaying
    an adversary-induced SAFETY violation pass the first violating tick:
    past it the kernel is in a state correct raft cannot represent (e.g.
    two leaders in one term after vote_equivocation), so the two sides'
    resolutions of the impossible state are incomparable by construction.
    """
    from swarmkit_tpu.raft.sim.kernel import propose, step
    from swarmkit_tpu.raft.sim.oracle import OracleCluster
    from swarmkit_tpu.dst.explore import apply_mutation
    from swarmkit_tpu.dst.schedule import (
        _flood_payload, apply_append_flood, apply_disk_stall,
        apply_lost_tail, apply_snap_corrupt, apply_torn_write,
        apply_transfer_abuse,
    )

    _step = jax.jit(step, static_argnames=("cfg",))
    _propose = jax.jit(propose, static_argnames=("cfg",))
    _mutate = jax.jit(apply_mutation, static_argnames=("cfg", "mutation"))

    state = init_state(cfg)
    oracle = OracleCluster(cfg)
    n = cfg.n
    drop_s = np.asarray(schedule.drop)
    alive_s = np.asarray(schedule.alive)
    tl_s = np.asarray(schedule.target_leader)
    cc_s = np.asarray(schedule.crash_campaign)
    def _opt(leaf):
        arr = getattr(schedule, leaf)
        return None if arr is None else np.asarray(arr)

    ti_s = _opt("term_inflate")
    rj_s = _opt("rejoin_campaign")
    eq_s = _opt("vote_equivocate")
    fl_s = _opt("append_flood")
    tx_s = _opt("transfer_abuse")
    storage_leaves = {leaf: _opt(leaf) for leaf in
                      ("disk_stall", "snap_corrupt", "lost_tail",
                       "torn_write")}
    storage_verbs = {"disk_stall": apply_disk_stall,
                     "snap_corrupt": apply_snap_corrupt,
                     "lost_tail": apply_lost_tail,
                     "torn_write": apply_torn_write}

    trace: list[dict] = []
    diverged_at = -1
    stop = schedule.ticks if until is None else min(until, schedule.ticks)
    for t in range(stop):
        role = np.asarray(state.role)
        leaders = role == LEADER
        drop = drop_s[t] | (tl_s[t] & (leaders[:, None] | leaders[None, :]))
        alive = alive_s[t] & ~(cc_s[t] & (role == CANDIDATE))
        # resolve the forced-campaign mask against the KERNEL's pre-step
        # roles (like the gates above) and mirror the same timer force on
        # both sides — elapsed := timeout on the oracle's scheduler.
        # term_inflate and rejoin_campaign share the transform (they
        # differ only in how their generators gate it), so one merged
        # mask keeps the mirror exact under composition.
        force = np.zeros(n, bool)
        if ti_s is not None:
            force |= ti_s[t]
        if rj_s is not None:
            force |= rj_s[t]
        force &= alive & (role != LEADER)
        if force.any():
            elapsed = jnp.where(jnp.asarray(force),
                                jnp.maximum(state.elapsed, state.timeout),
                                state.elapsed)
            state = dataclasses.replace(state, elapsed=elapsed)
            for i in range(n):
                if force[i]:
                    oracle.elapsed[i] = max(oracle.elapsed[i],
                                            oracle.timeout[i])
        if eq_s is not None and eq_s[t].any():
            # adversarial vote wipe, resolved against the kernel's
            # pre-step vote registers; core's vote is 1-based (0 = none)
            wipe = eq_s[t] & alive & (np.asarray(state.vote) != NONE)
            if wipe.any():
                state = dataclasses.replace(
                    state, vote=jnp.where(jnp.asarray(wipe), NONE,
                                          state.vote))
                for i in range(n):
                    if wipe[i]:
                        oracle.nodes[i].vote = 0
        if tx_s is not None and tx_s[t].any():
            # kernel side realizes the request through the cooldown gate;
            # the oracle mirror only holds with the defense off
            # (transfer_cooldown_ticks=0), which is how differential
            # sweeps run — oracle.transfer repeats are no-ops like the
            # kernel's `changed` gate
            tgt = int(np.argmax(tx_s[t]))
            state = apply_transfer_abuse(state, cfg, jnp.asarray(tx_s[t]),
                                         jnp.asarray(alive))
            for i in range(n):
                if leaders[i] and alive[i] and i != tgt:
                    oracle.transfer(i, tgt)
        if fl_s is not None and fl_s[t]:
            # flood: cfg.max_props dense proposals on every accepting
            # leader; the oracle replays the SAME device-computed
            # payloads through its propose phase (room/transfer gates
            # mirror _leader_ok with defenses off)
            cnt = cfg.max_props
            fl_pl = np.asarray(_flood_payload(
                state.tick, jnp.arange(cnt, dtype=jnp.uint32)))
            state = apply_append_flood(state, cfg, jnp.asarray(fl_s[t]),
                                       jnp.asarray(alive))
            oracle._phase_propose(alive, fl_pl, cnt)
        # storage-fault verbs mirror on the KERNEL side only: the host
        # oracle models a perfect disk (no sync_mark register), so a
        # compared range must stop before the first storage verb fires —
        # which replay_artifact's SAFETY_BITS `until` does for
        # DURABILITY artifacts (the verb tick IS the violation tick).
        if state.sync_mark is not None:
            for leaf, arr in storage_leaves.items():
                if arr is not None and arr[t].any():
                    state = storage_verbs[leaf](state, jnp.asarray(arr[t]),
                                                jnp.asarray(alive))

        payloads = np.zeros(cfg.max_props, np.uint32)
        if prop_count:
            tick = int(np.asarray(state.tick))
            k = np.arange(prop_count, dtype=np.uint32)
            payloads[:prop_count] = \
                (np.uint32(tick) * np.uint32(1 << 16) + k + np.uint32(1)) \
                & np.uint32(0x7FFFFFFF)
            state = _propose(state, cfg, jnp.asarray(payloads),
                             jnp.asarray(prop_count, jnp.int32),
                             alive=jnp.asarray(alive))
        state = _step(state, cfg, alive=jnp.asarray(alive),
                      drop=jnp.asarray(drop))
        state = _mutate(state, cfg, mutation)
        oracle.tick(alive, drop, payloads, prop_count)

        kv = _kernel_view(state)
        ov = oracle.view()
        diffs = [f for f in _VIEW_FIELDS
                 if not np.array_equal(kv[f], getattr(ov, f))]
        if diffs:
            if diverged_at < 0:
                diverged_at = t
            trace.append({
                "tick": t,
                "fields": diffs,
                "kernel": {f: kv[f].tolist() for f in diffs},
                "oracle": {f: np.asarray(getattr(ov, f)).tolist()
                           for f in diffs},
            })
            if stop_after_first:
                break
    return {"diverged_at": diverged_at, "trace": trace}


# ---------------------------------------------------------------------------
# JSON artifacts (seed-pinned, sparse, replayable by tools/dst_sweep.py)


def to_artifact(cfg: SimConfig, schedule: FaultSchedule, *, seed: int,
                profile: str, index: int, prop_count: int,
                mutation: Optional[str], viol: int,
                first_tick: int, flight: Optional[dict] = None) -> dict:
    """Sparse JSON form of one (usually shrunk) repro schedule.

    When `flight` is given (see :func:`capture_flight`), its decoded
    event window rides along so the artifact explains itself: the last
    device events before the violation, without re-running anything.
    """
    drop = np.asarray(schedule.drop)
    alive = np.asarray(schedule.alive)
    t, i, j = np.nonzero(drop)
    dt, dr = np.nonzero(~alive)
    art = {
        "version": ARTIFACT_VERSION,
        "seed": seed,
        "profile": profile,
        "index": index,
        "cfg": dataclasses.asdict(cfg),
        "ticks": int(schedule.ticks),
        "prop_count": prop_count,
        "mutation": mutation,
        "violation_bits": viol,
        "violations": bits_to_names(viol),
        "first_tick": first_tick,
        "fault_count": fault_count(schedule),
        "faults": {
            "drop": np.stack([t, i, j], axis=1).tolist(),
            "down": np.stack([dt, dr], axis=1).tolist(),
            "target_leader":
                np.nonzero(np.asarray(schedule.target_leader))[0].tolist(),
            "crash_campaign":
                np.nonzero(np.asarray(schedule.crash_campaign))[0].tolist(),
        },
    }
    # attack-verb leaves go in sparse (absent leaf = absent key, so old
    # artifacts and verb-less schedules keep the exact pre-extension JSON)
    for leaf, shape in _OPTIONAL_LEAVES.items():
        arr = getattr(schedule, leaf)
        if arr is None:
            continue
        if shape == "TN":
            it, ir = np.nonzero(np.asarray(arr))
            art["faults"][leaf] = np.stack([it, ir], axis=1).tolist()
        else:
            art["faults"][leaf] = np.nonzero(np.asarray(arr))[0].tolist()
    if flight is not None:
        art["flight"] = {
            "window": flight.get("window", []),
            "dropped": flight.get("dropped", []),
            "first_tick": flight.get("first_tick", -1),
            "violations": flight.get("violations", []),
            "telemetry": flight.get("telemetry", {}),
        }
    return art


def from_artifact(art: dict):
    """(cfg, schedule, prop_count, mutation) reconstructed from JSON."""
    if art.get("version") != ARTIFACT_VERSION:
        raise ValueError(f"unsupported artifact version {art.get('version')}")
    cfg = SimConfig(**art["cfg"])
    ticks, n = art["ticks"], cfg.n
    drop = np.zeros((ticks, n, n), bool)
    alive = np.ones((ticks, n), bool)
    tl = np.zeros(ticks, bool)
    cc = np.zeros(ticks, bool)
    for t, i, j in art["faults"]["drop"]:
        drop[t, i, j] = True
    for t, r in art["faults"]["down"]:
        alive[t, r] = False
    tl[art["faults"]["target_leader"]] = True
    cc[art["faults"]["crash_campaign"]] = True
    # artifacts predating a verb carry no key for it and replay the exact
    # pre-extension program (the leaf stays None; still version 1)
    verbs = {}
    for leaf, shape in _OPTIONAL_LEAVES.items():
        if leaf not in art["faults"]:
            continue
        if shape == "TN":
            m = np.zeros((ticks, n), bool)
            for t, r in art["faults"][leaf]:
                m[t, r] = True
        else:
            m = np.zeros((ticks,), bool)
            m[art["faults"][leaf]] = True
        verbs[leaf] = jnp.asarray(m)
    schedule = FaultSchedule(drop=jnp.asarray(drop), alive=jnp.asarray(alive),
                             target_leader=jnp.asarray(tl),
                             crash_campaign=jnp.asarray(cc), **verbs)
    return cfg, schedule, art["prop_count"], art["mutation"]


def save_artifact(path: str, art: dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(art, f, indent=1, sort_keys=True)


def load_artifact(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def replay_artifact(art, with_trace: bool = True) -> dict:
    """Re-run an artifact: the recorded violation must reproduce exactly
    (bits AND first tick).  Returns the verdict + optional oracle trace."""
    if isinstance(art, str):
        art = load_artifact(art)
    cfg, schedule, prop_count, mutation = from_artifact(art)
    viol, first = replay(cfg, schedule, prop_count, mutation)
    out = {
        "violation_bits": viol,
        "violations": bits_to_names(viol),
        "first_tick": first,
        "matches_recorded": (viol == art["violation_bits"]
                             and first == art["first_tick"]),
    }
    if with_trace:
        # adversary-induced safety violations (no mutation) put the
        # kernel into spec-unrepresentable territory at the violation
        # tick; compare the oracle only over the clean prefix there.
        # Mutation artifacts keep the full trace — the divergence IS
        # the diagnostic localizing the injected kernel bug.
        from swarmkit_tpu.dst.invariants import SAFETY_BITS
        until = (first if mutation is None and (viol & SAFETY_BITS)
                 and first >= 0 else None)
        out["oracle"] = oracle_trace(cfg, schedule, prop_count, mutation,
                                     until=until)
    return out

"""Batched schedule exploration: one jitted scan over S x N clusters.

`explore()` broadcasts one init state across a leading schedule axis S and
vmaps the tick kernel over it, so every tick advances S independent
clusters — each under its own `FaultSchedule` — in a single XLA program.
The invariant checkers (:mod:`invariants`) run inside the same scan as
vectorized reductions and OR into a per-schedule violation bitmask; the
host sees only [S] masks and first-violation ticks.

The S axis is data-parallel, so when the process has several devices (the
CPU test mesh forces 8) the batch is sharded across them through the same
`parallel` helpers the sim kernel uses for its row axis.

The `mutation` knob compiles a DELIBERATELY broken kernel variant (e.g.
``commit_no_quorum``) — the detection self-test: the checkers must catch
it and the repro pipeline must shrink it (tools/dst_sweep.py --mutate).
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from swarmkit_tpu import parallel
from swarmkit_tpu.dst.invariants import (
    ALL_BITS, BIT_NAMES, check_state, check_transition,
)
from swarmkit_tpu.dst.schedule import (
    ATTACK_LEAVES, STORAGE_LEAVES, FaultSchedule, apply_append_flood,
    apply_disk_stall, apply_lost_tail, apply_rejoin_campaign,
    apply_snap_corrupt, apply_term_inflation, apply_torn_write,
    apply_transfer_abuse, apply_vote_equivocation, effective_faults,
)
from swarmkit_tpu.raft.sim.kernel import propose_dense, step
from swarmkit_tpu.raft.sim.run import _payload_at
from swarmkit_tpu.raft.sim.state import LEADER, SimConfig, SimState

I32 = jnp.int32

MUTATIONS = ("commit_no_quorum", "stale_lease_read")


def apply_mutation(state: SimState, cfg: SimConfig,
                   mutation: Optional[str]) -> SimState:
    """Post-step state corruption implementing a named kernel bug."""
    if mutation is None:
        return state
    if mutation == "commit_no_quorum":
        # a leader commits its whole log without waiting for a quorum of
        # match acks — invisible while messages flow (the synchronous wire
        # acks within the tick) but fatal once a minority leader keeps
        # accepting proposals behind a partition
        leaders = state.role == LEADER
        commit = jnp.where(leaders, jnp.maximum(state.commit, state.last),
                           state.commit)
        return dataclasses.replace(state, commit=commit)
    if mutation == "stale_lease_read":
        # leases force-disabled: any row still CLAIMING leadership serves
        # its pending read batch immediately at its own applied index,
        # skipping every gate (lease validity, quorum-ack confirmation,
        # own-term commit, applied >= read_index) — the arXiv:2601.00273
        # stale-read attack.  Healthy leaders get away with it most ticks;
        # a partitioned stale leader serves reads missing the writes the
        # NEW leader has been committing, and LINEARIZABLE_READ fires
        # (srv_idx = stale applied < srv_goal = submit-time max(commit)).
        if state.read_pend is None:
            raise ValueError("stale_lease_read requires cfg.read_batch > 0")
        leaders = state.role == LEADER
        serve = leaders & (state.read_pend > 0)
        return dataclasses.replace(
            state,
            read_srv=state.read_srv + jnp.where(serve, state.read_pend, 0),
            read_srv_idx=jnp.where(serve, state.applied, state.read_srv_idx),
            read_srv_goal=jnp.where(serve, state.read_goal,
                                    state.read_srv_goal),
            read_pend=jnp.where(serve, 0, state.read_pend),
            read_idx=jnp.where(serve, jnp.full_like(state.read_idx, -1),
                               state.read_idx))
    raise KeyError(f"unknown mutation {mutation!r}; known: {MUTATIONS}")


def broadcast_state(state: SimState, schedules: int) -> SimState:
    """Stack one init state S times along a new leading axis."""
    return jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a, (schedules,) + a.shape), state)


def _tick_one(st: SimState, cfg: SimConfig, sched_t: FaultSchedule,
              prop_count: int, mutation: Optional[str]):
    """Advance ONE cluster one tick under its schedule slice (a
    FaultSchedule holding one tick's arrays); returns the new state and
    this tick's violation bits."""
    alive, drop = effective_faults(st.role, sched_t.drop, sched_t.alive,
                                   sched_t.target_leader,
                                   sched_t.crash_campaign)
    # protocol-speaking adversary verbs, in schedule.py's documented
    # composition order (inflate -> rejoin -> equivocate -> transfer ->
    # flood): each forces the flagged rows' state BEFORE the step, so the
    # kernel's own paths (PreVote, vote guard, cooldown, inflight cap)
    # realize — or refuse — the action
    if sched_t.term_inflate is not None:
        st = apply_term_inflation(st, sched_t.term_inflate, alive)
    if sched_t.rejoin_campaign is not None:
        st = apply_rejoin_campaign(st, sched_t.rejoin_campaign, alive)
    if sched_t.vote_equivocate is not None:
        st = apply_vote_equivocation(st, sched_t.vote_equivocate, alive)
    if sched_t.transfer_abuse is not None:
        st = apply_transfer_abuse(st, cfg, sched_t.transfer_abuse, alive)
    if sched_t.append_flood is not None:
        st = apply_append_flood(st, cfg, sched_t.append_flood, alive)
    # storage-fault verbs (all no-ops on a storage-off state); lost_tail
    # and torn_write legally regress volatile commit/applied, so their
    # rows are excused from COMMIT_MONOTONIC for exactly this transition
    recovering = None
    if st.sync_mark is not None:
        if sched_t.disk_stall is not None:
            st = apply_disk_stall(st, sched_t.disk_stall, alive)
        if sched_t.snap_corrupt is not None:
            st = apply_snap_corrupt(st, sched_t.snap_corrupt, alive)
        if sched_t.lost_tail is not None:
            st = apply_lost_tail(st, sched_t.lost_tail, alive)
            recovering = sched_t.lost_tail
        if sched_t.torn_write is not None:
            st = apply_torn_write(st, sched_t.torn_write, alive)
            recovering = sched_t.torn_write if recovering is None \
                else recovering | sched_t.torn_write
    if prop_count:
        # fused propose (kernel.step docstring): one [N, L] write cond per
        # scan iteration keeps the vmapped log buffers in place
        new = step(st, cfg, alive=alive, drop=drop,
                   prop_count=jnp.asarray(prop_count, I32),
                   payload_fn=_payload_at)
    else:
        new = step(st, cfg, alive=alive, drop=drop)
    new = apply_mutation(new, cfg, mutation)
    bits = check_state(new, cfg) | check_transition(st, new, recovering)
    return new, bits


@partial(jax.jit, static_argnames=("cfg", "prop_count", "mutation"))
def _explore_compiled(batched: SimState, cfg: SimConfig,
                      schedule: FaultSchedule, prop_count: int,
                      mutation: Optional[str]):
    """scan over T of vmap over S. Returns (final, viol [S], first [S])."""
    # scan consumes xs with a leading T axis; schedules batch as [S, T, ..]
    xs = jax.tree_util.tree_map(lambda a: jnp.moveaxis(a, 1, 0), schedule)

    def body(carry, sched_t):
        st, acc = carry
        new, bits = jax.vmap(
            lambda s, sch: _tick_one(s, cfg, sch, prop_count, mutation)
        )(st, sched_t)
        return (new, acc | bits), bits

    schedules = schedule.target_leader.shape[0]
    init = (batched, jnp.zeros((schedules,), jnp.uint32))
    (final, viol), bits_by_tick = jax.lax.scan(body, init, xs)  # [T, S]
    any_t = bits_by_tick > 0
    first = jnp.where(jnp.any(any_t, axis=0),
                      jnp.argmax(any_t, axis=0).astype(I32), -1)
    return final, viol, first, bits_by_tick


@dataclass
class ExploreResult:
    viol: np.ndarray          # [S] uint32 violation bitmasks
    first_tick: np.ndarray    # [S] int32 first violating tick, -1 = clean
    bits_by_tick: np.ndarray  # [T, S] per-tick bitmasks (diagnostics)
    final_state: SimState
    profiles: list            # profile name per schedule index (may be [])
    elapsed: float
    schedules_per_sec: float

    @property
    def violating(self) -> np.ndarray:
        return np.nonzero(self.viol)[0]


def postmortem(result: ExploreResult, cfg: SimConfig,
               schedule: FaultSchedule, prop_count: int = 2,
               mutation: Optional[str] = None, window: int = 40,
               limit: int = 4, obs=None) -> dict:
    """Flight-record the violating schedules of an explore batch.

    Each violating index is re-run solo with `record_events=True`
    (stopping right after its first violating tick) and decoded; returns
    {index: capture dict} — see :func:`swarmkit_tpu.dst.repro.capture_flight`.
    `limit` caps the re-runs: post-mortems are for reading, and one sweep
    can violate hundreds of schedules with the same root cause.
    """
    from swarmkit_tpu.dst import repro  # late: repro imports this module

    out: dict[int, dict] = {}
    for idx in result.violating[:limit]:
        idx = int(idx)
        one = jax.tree_util.tree_map(lambda a: jnp.asarray(a)[idx], schedule)
        out[idx] = repro.capture_flight(
            cfg, one, prop_count, mutation,
            first_tick=int(result.first_tick[idx]), window=window,
            trigger="dst_violation", obs=obs)
    return out


def explore(state: SimState, cfg: SimConfig, schedule: FaultSchedule,
            profiles=(), prop_count: int = 2,
            mutation: Optional[str] = None, shard: bool = True,
            obs=None) -> ExploreResult:
    """Run every schedule in the batch to completion and check invariants.

    `state` is ONE cluster's init state (broadcast internally);
    `schedule` is a [S, T, ...] batch from `schedule.make_batch`.
    """
    from swarmkit_tpu.metrics import catalog
    from swarmkit_tpu.metrics import registry as obs_registry

    schedules = schedule.target_leader.shape[0]
    batched = broadcast_state(state, schedules)
    if shard and len(jax.devices()) > 1:
        mesh = parallel.schedule_mesh(schedules)
        batched = parallel.shard_rows(batched, mesh,
                                      axis=parallel.SCHEDULE_AXIS)
        schedule = parallel.shard_rows(schedule, mesh,
                                       axis=parallel.SCHEDULE_AXIS)

    t0 = time.monotonic()
    final, viol, first, bits = _explore_compiled(
        batched, cfg, schedule, prop_count, mutation)
    viol = np.asarray(jax.device_get(viol))
    first = np.asarray(jax.device_get(first))
    bits = np.asarray(jax.device_get(bits))
    elapsed = time.monotonic() - t0
    rate = schedules / elapsed if elapsed > 0 else float("inf")

    obs = obs or obs_registry.DEFAULT
    m_sched = catalog.get(obs, "swarm_dst_schedules_total")
    m_viol = catalog.get(obs, "swarm_dst_violations_total")
    m_rate = catalog.get(obs, "swarm_dst_schedules_per_second")
    clean = int((viol == 0).sum())
    if clean:
        m_sched.labels(result="clean").inc(clean)
    if schedules - clean:
        m_sched.labels(result="violation").inc(schedules - clean)
    for bit in ALL_BITS:
        hits = int(((viol & bit) != 0).sum())
        if hits:
            m_viol.labels(invariant=BIT_NAMES[bit]).inc(hits)
    m_rate.labels(config=f"n{cfg.n}x{schedule.ticks}t").set(rate)
    m_att = catalog.get(obs, "swarm_dst_attack_ticks_total")
    for attack, leaf in {**ATTACK_LEAVES, **STORAGE_LEAVES}.items():
        gate = getattr(schedule, leaf)
        if gate is not None:
            fired = int(np.asarray(jax.device_get(gate)).sum())
            if fired:
                m_att.labels(attack=attack).inc(fired)

    return ExploreResult(viol=viol, first_tick=first, bits_by_tick=bits,
                         final_state=final, profiles=list(profiles),
                         elapsed=elapsed, schedules_per_sec=rate)

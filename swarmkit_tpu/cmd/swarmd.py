"""swarmd: the node daemon.

Reference: cmd/swarmd/main.go (flags at :255-273 — --state-dir,
--join-addr, --join-token, --listen-control-api, --hostname,
--heartbeat-tick, --election-tick, --manager).  Runs one
``swarmkit_tpu.node.Node``; the control API is served on a unix socket for
swarmctl.  Single-process transport today (in-proc Network); the gRPC
transport slots in via --backend once cross-host raft lands.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal
from typing import Optional

from swarmkit_tpu.agent.testutils import TestExecutor
from swarmkit_tpu.cmd.ctl import ControlSocketServer
from swarmkit_tpu.node import Node, NodeConfig
from swarmkit_tpu.raft.transport import Network


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="swarmd", description="swarmkit-tpu node daemon")
    p.add_argument("--state-dir", default="./swarmkitstate",
                   help="state directory (reference default: "
                        "$HOME/.swarmkit)")
    p.add_argument("--hostname", default="",
                   help="override reported hostname")
    p.add_argument("--node-id", default="", help="node id (default: random)")
    p.add_argument("--join-addr", default="",
                   help="address of a manager to join")
    p.add_argument("--join-token", default="", help="cluster join token")
    p.add_argument("--advertise-remote-api", default="",
                   help="address peers should dial (defaults to "
                        "--listen-remote-api; set when binding a "
                        "wildcard or NAT-internal address)")
    p.add_argument("--listen-remote-api", default="0.0.0.0:4242",
                   help="listen address for raft/dispatcher traffic")
    p.add_argument("--listen-control-api", default="./swarmkitstate/swarmd.sock",
                   help="control API unix socket for swarmctl")
    p.add_argument("--manager", action="store_true",
                   help="start as a manager (bootstrap if no join-addr)")
    p.add_argument("--force-new-cluster", action="store_true")
    p.add_argument("--heartbeat-tick", type=int, default=1)
    p.add_argument("--election-tick", type=int, default=10)
    p.add_argument("--unlock-key", default="")
    p.add_argument("--autolock", action="store_true",
                   help="bootstrap the cluster with manager autolock "
                        "enabled (reference swarmd --autolock); the "
                        "unlock key prints once on stdout")
    p.add_argument("--log-level", default="info",
                   choices=["debug", "info", "warning", "error"])
    p.add_argument("--listen-debug", default="",
                   help="serve the live diagnostic surface (asyncio task "
                        "dump, store wedge state, watch-queue depths, "
                        "metrics) on host:port or a unix socket path "
                        "(reference: swarmd --listen-debug pprof/expvar, "
                        "cmd/swarmd/main.go:183)")
    p.add_argument("--backend", choices=["grpc", "inproc"], default="grpc",
                   help="raft/cluster wire: real gRPC sockets (default) or "
                        "in-process (single-node/testing)")
    def _gnr(value: str) -> str:
        try:
            _parse_generic_resources(value)   # validate at CLI-parse time
        except ValueError as e:
            # argparse swallows ValueError's message ("invalid _gnr
            # value"); ArgumentTypeError's str() is shown to the operator
            raise argparse.ArgumentTypeError(str(e))
        return value

    p.add_argument("--generic-node-resources", default="", type=_gnr,
                   help="user-defined generic resources this node offers, "
                        "e.g. 'fpga=2,gpu=UUID1,gpu=UUID2' — integer "
                        "values are discrete counts, strings are named "
                        "ids; a kind is either discrete OR named "
                        "(reference: cmd/swarmd/main.go:267)")
    p.add_argument("--executor", choices=["tpu", "test"], default="tpu",
                   help="task runtime: compiled JAX programs on the local "
                        "devices (tpu, default) or the instant fake (test)")
    return p


def _parse_generic_resources(spec: str):
    """'fpga=2,gpu=UUID1,gpu=UUID2' -> (discrete counts, named id sets).

    A kind is EITHER discrete or named — mixing ('gpu=2,gpu=UUID1') or
    duplicate ids are rejected, like the reference's parser
    (cmd/swarmd/main.go:155-158 + api/genericresource validation):
    the scheduler sizes a named kind by its id set, so a mixed spec
    would advertise phantom capacity no task could ever claim."""
    counts: dict[str, int] = {}
    named: dict[str, list[str]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, eq, value = part.partition("=")
        name, value = name.strip(), value.strip()
        if not eq or not name or not value:
            raise ValueError(
                f"--generic-node-resources wants name=value, got {part!r}")
        if any(ch.isspace() for ch in name) or \
                any(ch.isspace() for ch in value):
            raise ValueError(
                f"--generic-node-resources: whitespace inside "
                f"name or value: {part!r}")
        try:
            n = int(value)
        except ValueError:
            if name in counts:
                raise ValueError(
                    f"--generic-node-resources: kind {name!r} mixes a "
                    f"discrete count with named ids")
            ids = named.setdefault(name, [])
            if value in ids:
                raise ValueError(
                    f"--generic-node-resources: duplicate id "
                    f"{name}={value}")
            ids.append(value)
        else:
            if name in named:
                raise ValueError(
                    f"--generic-node-resources: kind {name!r} mixes a "
                    f"discrete count with named ids")
            if n <= 0:
                raise ValueError(
                    f"--generic-node-resources: discrete count must be "
                    f"positive, got {name}={n}")
            counts[name] = counts.get(name, 0) + n
    # named ids are ALSO countable (the scheduler counts, then claims ids)
    for name, ids in named.items():
        counts[name] = len(ids)
    return counts, named


class _GenericResourcesExecutor:
    """Executor wrapper merging operator-declared generic resources into
    the node description the agent registers with."""

    def __init__(self, inner, parsed) -> None:
        self._inner = inner
        self._counts, self._named = parsed

    def __getattr__(self, name):
        return getattr(self._inner, name)

    async def describe(self):
        desc = await self._inner.describe()
        if desc.resources is None:
            from swarmkit_tpu.api.types import NodeResources
            desc.resources = NodeResources()
        for k, v in self._counts.items():
            if k in self._named:
                continue  # named kinds get their count from the id set
            if k in desc.resources.generic_named:
                # the executor already advertises this kind as NAMED ids
                # (e.g. tpu-chip): a flat count would be phantom capacity
                # the scheduler can never claim — drop it loudly
                logging.getLogger("swarmkit_tpu.swarmd").warning(
                    "--generic-node-resources: ignoring discrete count "
                    "for %r — the executor advertises it as named ids", k)
                continue
            desc.resources.generic[k] = \
                desc.resources.generic.get(k, 0) + v
        for k, ids in self._named.items():
            if k in desc.resources.generic \
                    and k not in desc.resources.generic_named:
                # mirror of the discrete-over-named guard: the executor
                # advertises this kind as a DISCRETE count; operator ids
                # would overwrite real capacity with phantom claimable
                # ids no runtime backs — drop them loudly
                logging.getLogger("swarmkit_tpu.swarmd").warning(
                    "--generic-node-resources: ignoring named ids for "
                    "%r — the executor advertises it as a discrete "
                    "count", k)
                continue
            have = desc.resources.generic_named.setdefault(k, [])
            have.extend(i for i in ids if i not in have)
            desc.resources.generic[k] = len(have)
        return desc


async def run(args, network=None, executor=None, registry=None) -> Node:
    """Build + start the node; returns it (caller owns shutdown).

    ``registry`` is the node directory the dialer resolves addresses
    against — share one dict (and one Network) across run() calls to host
    several joined nodes in one process.  Cross-process joins ride the gRPC
    transport once wired; a lone daemon resolves only itself.
    """
    from swarmkit_tpu.utils.identity import new_id

    use_grpc = getattr(args, "backend", "inproc") == "grpc" \
        and network is None
    node_box: list = []
    if use_grpc:
        from swarmkit_tpu.raft.grpc_transport import GrpcNetwork

        # late-bound security: the node's TLS identity is loaded during
        # node.start(), before the raft listener registers
        network = GrpcNetwork(
            security=lambda: node_box[0].security if node_box else None)
    network = network or Network()
    node_id = args.node_id or new_id()
    if executor is None:
        if getattr(args, "executor", "tpu") == "tpu":
            from swarmkit_tpu.agent.tpu import TpuExecutor

            executor = TpuExecutor(hostname=args.hostname or node_id)
        else:
            executor = TestExecutor(hostname=args.hostname or node_id)
    extra = getattr(args, "generic_node_resources", "")
    if extra:
        executor = _GenericResourcesExecutor(
            executor, _parse_generic_resources(extra))
    nodes = registry if registry is not None else {}
    remote_managers: dict[str, object] = {}

    def dialer(addr):
        for n in nodes.values():
            m = n._running_manager()
            if m is not None and m.addr == addr:
                return m
        if use_grpc and addr:
            from swarmkit_tpu.rpc import RemoteManager

            rm = remote_managers.get(addr)
            if rm is None:
                expected_digest = ""
                if args.join_token:
                    from swarmkit_tpu.ca.config import parse_join_token

                    expected_digest = parse_join_token(
                        args.join_token).ca_digest
                rm = RemoteManager(
                    addr,
                    security_ref=lambda: (node_box[0].security
                                          if node_box else None),
                    expected_ca_digest=expected_digest)
                rm.start()
                remote_managers[addr] = rm
            return rm
        return None

    advertise = getattr(args, "advertise_remote_api", "") \
        or args.listen_remote_api
    if use_grpc:
        # serve dispatcher/CA/control alongside raft on the same port
        # (reference: manager.go:526-548 service registrations); services
        # are keyed by the ADVERTISED address (the node's identity on the
        # wire) while the sockets bind the listen address
        from swarmkit_tpu.rpc import ClusterService

        cluster_service = ClusterService(
            lambda: node_box[0] if node_box else None)
        if advertise != args.listen_remote_api:
            network.set_bind_addr(advertise, args.listen_remote_api)
        network.add_service(advertise, cluster_service.handlers())
        network.add_join_service(advertise, cluster_service.join_handlers())

    node = Node(NodeConfig(
        node_id=node_id,
        state_dir=args.state_dir,
        executor=executor,
        network=network,
        dialer=dialer,
        listen_addr=args.listen_remote_api,
        advertise_addr=getattr(args, "advertise_remote_api", ""),
        join_addr=args.join_addr,
        join_token=args.join_token,
        is_manager=args.manager,
        force_new_cluster=args.force_new_cluster,
        election_tick=args.election_tick,
        heartbeat_tick=args.heartbeat_tick,
        unlock_key=args.unlock_key.encode() if args.unlock_key else None))
    node_box.append(node)
    nodes[node_id] = node
    await node.start()
    node._remote_managers = remote_managers

    if getattr(args, "autolock", False) and not (
            args.manager and not args.join_addr):
        logging.getLogger("swarmd").warning(
            "--autolock only applies to the bootstrap (seed) manager; "
            "use `swarmctl cluster-autolock on` on a running cluster")
    if getattr(args, "autolock", False) and args.manager \
            and not args.join_addr:
        # bootstrap-time autolock (reference swarmd --autolock): enable it
        # the moment this seed manager leads, and print the unlock key
        # once — the only time the operator can capture it
        async def _enable_autolock():
            # leadership comes first, the seeded cluster object a beat
            # later — retry the whole read-modify-write until both exist
            last_err: Optional[Exception] = None
            for _ in range(600):
                m = node._running_manager()
                if m is not None and node.is_leader():
                    try:
                        c = m.control_api
                        cl = c.get_cluster()
                        spec = cl.spec.copy()
                        spec.encryption_config.auto_lock_managers = True
                        await c.update_cluster(
                            cl.id, spec, version=cl.meta.version.index)
                        print(f"cluster autolock enabled; unlock key: "
                              f"{c.get_unlock_key()['unlock_key']}",
                              flush=True)
                        return
                    except Exception as e:
                        last_err = e   # not seeded yet / version race
                await asyncio.sleep(0.1)
            logging.getLogger("swarmd").error(
                "autolock bootstrap never completed (last error: %r)",
                last_err)

        t = asyncio.get_running_loop().create_task(_enable_autolock())
        node._autolock_bootstrap = t
        node._aux_tasks = getattr(node, "_aux_tasks", []) + [t]

    os.makedirs(os.path.dirname(args.listen_control_api) or ".",
                exist_ok=True)
    if os.path.exists(args.listen_control_api):
        os.unlink(args.listen_control_api)
    ctl = ControlSocketServer(node, args.listen_control_api)
    await ctl.start()
    node._ctl_server = ctl
    node._debug_server = None
    if args.listen_debug:
        from swarmkit_tpu.node.debug import DebugServer
        dbg = DebugServer(node)
        await dbg.start(args.listen_debug)
        node._debug_server = dbg
    return node


async def main_async(argv=None) -> None:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper()),
        format="%(asctime)s %(name)s %(message)s")
    node = await run(args)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if getattr(node, "_debug_server", None) is not None:
        await node._debug_server.stop()
    await node._ctl_server.stop()
    await node.stop()


def main(argv=None) -> None:
    asyncio.run(main_async(argv))


if __name__ == "__main__":
    main()

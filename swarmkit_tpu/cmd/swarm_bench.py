"""swarm-bench: time-to-N-running-tasks for the full control plane.

Reference: cmd/swarm-bench — creates a replicated service of N tasks that
"phone home" and measures time until all N connect (Benchmark.Run
benchmark.go:38, Collector percentiles).  Here the phone-home is the task
status write-back through the real dispatcher/agent loop; the measurement
is time from CreateService until N tasks report RUNNING, with per-task
latency percentiles.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time

from swarmkit_tpu.agent import Agent, AgentConfig
from swarmkit_tpu.agent.testutils import TestExecutor
from swarmkit_tpu.api import (
    Annotations, ContainerSpec, MembershipState, NodeSpec, ReplicatedService,
    ServiceSpec, TaskSpec, TaskState,
)
from swarmkit_tpu.api.objects import Node as ApiNode, NodeStatus
from swarmkit_tpu.manager.manager import Manager
from swarmkit_tpu.raft.transport import Network
from swarmkit_tpu.store.by import ByService
from swarmkit_tpu.store.memory import Event, match


async def bench(replicas: int, workers: int, managers: int = 1,
                transport: str = "inproc", tick_interval: float = 0.05,
                election_tick: int = 4, proposals: int = 0,
                batch: int = 1, coalesce_window: float = 0.0) -> dict:
    import tempfile

    transport_factory = None
    if transport == "device":
        # manager-quorum consensus over the device-mesh mailbox wire
        # (SURVEY §7; same path tests/test_integration.py's device-mesh
        # variant exercises).  Pin the JAX platform BEFORE any backend
        # init: the axon sitecustomize otherwise routes to the TPU tunnel,
        # which hangs indefinitely when the tunnel is wedged.  Set
        # SWARM_BENCH_JAX_PLATFORM=tpu to run the quorum on a real chip.
        import os as _os

        import jax as _jax
        _jax.config.update(
            "jax_platforms",
            _os.environ.get("SWARM_BENCH_JAX_PLATFORM", "cpu"))
        # the config update is a no-op if a backend is already live (e.g.
        # a programmatic caller did sim work first); drop cached backends
        # so the pin takes effect — this is a bench entry point, nothing
        # long-lived holds device buffers here
        import jax.extend.backend as _jxb
        _jxb.clear_backends()
        from swarmkit_tpu.transport import DeviceMeshNet, DeviceMeshTransport
        net = DeviceMeshNet(seed=1, rows=max(8, managers))
        transport_factory = DeviceMeshTransport
    else:
        net = Network(seed=1)
    tmp = tempfile.TemporaryDirectory(prefix="swarm-bench-")
    mgrs: list[Manager] = []
    for i in range(managers):
        m = Manager(node_id=f"m{i}", addr=f"m{i}:4242", network=net,
                    state_dir=f"{tmp.name}/m{i}",
                    join_addr=mgrs[0].addr if mgrs else "",
                    tick_interval=tick_interval,
                    election_tick=election_tick, seed=i,
                    transport_factory=transport_factory)
        await m.start()
        mgrs.append(m)
        if i == 0:
            while not m.is_leader():
                await asyncio.sleep(0.02)

    lead = mgrs[0]

    def connect():
        for m in mgrs:
            if m.is_leader():
                return m.dispatcher
        return lead.dispatcher

    if proposals > 0:
        # BASELINE.json config 2: N-manager quorum ProposeValue appends
        # through the leader's replicated store — per-proposal commit
        # latency through the real raft path (reference swarm-bench's
        # role for control-plane throughput).  batch > 1 switches the
        # store to the coalescing proposal pipeline (store/pipeline.py)
        # and keeps k appends in flight concurrently, so many txns pack
        # into one raft round ("k appends/round" in PERF.md).
        from swarmkit_tpu.api import Config as ApiConfig, ConfigSpec

        if batch > 1:
            from swarmkit_tpu.store.pipeline import CoalesceConfig
            lead.store.set_coalescing(CoalesceConfig(
                window=coalesce_window, max_entries=max(batch, 2)))

        lat: list[float] = []

        async def one(i: int) -> None:
            p0 = time.perf_counter()
            await lead.store.update(lambda tx: tx.create(ApiConfig(
                id=f"bench-cfg-{i}",
                spec=ConfigSpec(annotations=Annotations(name=f"p{i}"),
                                data=b"x"))))
            lat.append(time.perf_counter() - p0)

        t0 = time.perf_counter()
        if batch > 1:
            for base in range(0, proposals, batch):
                await asyncio.gather(*(
                    one(i) for i in range(base,
                                          min(base + batch, proposals))))
        else:
            for i in range(proposals):
                await one(i)
        total = time.perf_counter() - t0
        lat.sort()

        def ppct(p):
            return lat[min(len(lat) - 1, int(p * len(lat)))]

        packed = committed = 0.0
        if batch > 1:
            from swarmkit_tpu.metrics import catalog as obs_catalog
            packed = obs_catalog.get(lead.obs, "swarm_cpl_proposals_total") \
                .labels(outcome="committed").value
            committed = obs_catalog.get(lead.obs, "swarm_cpl_txns_total") \
                .labels(outcome="committed").value
        for m in mgrs:
            await m.stop()
        close = getattr(net, "close", None)
        if close is not None:
            close()
        return {
            "managers": managers, "transport": transport,
            "proposals": proposals, "batch": batch,
            "entries_per_proposal": round(committed / packed, 2)
            if packed else 1.0,
            "coalesce_window_ms": round(coalesce_window * 1e3, 3),
            "proposals_per_s": round(proposals / total, 1),
            "propose_p50_ms": round(ppct(0.5) * 1e3, 3),
            "propose_p99_ms": round(ppct(0.99) * 1e3, 3),
        }

    agents = []
    for i in range(workers):
        await lead.store.update(lambda tx, i=i: tx.create(ApiNode(
            id=f"w{i}", spec=NodeSpec(annotations=Annotations(name=f"w{i}"),
                                      membership=MembershipState.ACCEPTED),
            status=NodeStatus())))
        a = Agent(AgentConfig(node_id=f"w{i}",
                              executor=TestExecutor(hostname=f"w{i}"),
                              connect=connect))
        await a.start()
        agents.append(a)
    for a in agents:
        await a.ready()

    # measure: create service -> all replicas RUNNING.  Subscribe BEFORE
    # creating so instantly-running tasks can't slip past the watcher.
    latencies: dict[str, float] = {}
    watcher = lead.store.watch(match(kind="task", action="update"))
    start = time.perf_counter()
    svc = await lead.control_api.create_service(ServiceSpec(
        annotations=Annotations(name="bench"),
        task=TaskSpec(container=ContainerSpec(image="img")),
        replicated=ReplicatedService(replicas=replicas)))
    running = set()
    async for ev in watcher:
        t = ev.object
        if t.service_id == svc.id and t.status.state == TaskState.RUNNING \
                and t.id not in running:
            running.add(t.id)
            latencies[t.id] = time.perf_counter() - start
            if len(running) >= replicas:
                break
    watcher.close()
    total = time.perf_counter() - start

    lat = sorted(latencies.values())

    def pct(p):
        return lat[min(len(lat) - 1, int(p * len(lat)))]

    for a in agents:
        await a.stop()
    for m in mgrs:
        await m.stop()
    close = getattr(net, "close", None)
    if close is not None:
        close()
    return {
        "replicas": replicas, "workers": workers, "transport": transport,
        "time_to_all_running_s": round(total, 4),
        "tasks_per_s": round(replicas / total, 2),
        "p50_s": round(pct(0.50), 4),
        "p90_s": round(pct(0.90), 4),
        "p99_s": round(pct(0.99), 4),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="swarm-bench")
    p.add_argument("--replicas", type=int, default=100)
    p.add_argument("--workers", type=int, default=10)
    p.add_argument("--managers", type=int, default=1)
    p.add_argument("--transport", choices=["inproc", "device"],
                   default="inproc",
                   help="raft wire: in-process queues or the device-mesh "
                        "mailbox backend")
    p.add_argument("--tick-interval", type=float, default=0.05,
                   help="raft tick seconds (raise to ~0.5 when the device "
                        "wire runs on a real chip through a slow tunnel)")
    p.add_argument("--election-tick", type=int, default=4)
    p.add_argument("--proposals", type=int, default=0,
                   help="measure N sequential ProposeValue appends through "
                        "the manager quorum instead of the task-startup "
                        "flow (BASELINE config 2)")
    p.add_argument("--batch", type=int, default=1,
                   help="keep k proposals in flight and coalesce them into "
                        "packed raft rounds via the store's proposal "
                        "pipeline (1 = the sequential baseline path)")
    p.add_argument("--coalesce-window", type=float, default=0.0,
                   help="pipeline gathering window in seconds (0 = one "
                        "event-loop pass)")
    args = p.parse_args(argv)
    result = asyncio.run(bench(args.replicas, args.workers, args.managers,
                               transport=args.transport,
                               tick_interval=args.tick_interval,
                               election_tick=args.election_tick,
                               proposals=args.proposals,
                               batch=args.batch,
                               coalesce_window=args.coalesce_window))
    json.dump(result, sys.stdout)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

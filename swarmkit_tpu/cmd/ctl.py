"""Control-API-over-unix-socket: the swarmd ↔ swarmctl wire.

Reference: swarmd's ``--listen-control-api`` unix socket serving the
Control gRPC service (cmd/swarmd/main.go:255-273, manager.go:526) and
swarmctl dialing it (cmd/swarmctl).  Here the wire is newline-delimited
JSON ``{"method": ..., "params": {...}}`` → ``{"result": ...}`` /
``{"error": ..., "code": ...}`` — the gRPC semantics (method-per-RPC,
typed errors) without protobuf.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

from swarmkit_tpu.api import (
    ConfigSpec, NetworkSpec, NodeAvailability, NodeRole, SecretSpec,
    ServiceSpec,
)
from swarmkit_tpu.manager.controlapi import ControlError

log = logging.getLogger("swarmkit_tpu.ctl")


class CtlError(Exception):
    def __init__(self, message: str, code: str = "unknown") -> None:
        super().__init__(message)
        self.code = code


class ControlSocketServer:
    """Serves a Node's control API on a unix socket."""

    def __init__(self, node, path: str) -> None:
        self.node = node
        self.path = path
        self._server: Optional[asyncio.AbstractServer] = None

    def _control(self):
        from swarmkit_tpu.node.connectionbroker import NoManagerError

        if self.node._running_manager() is None:
            raise CtlError("this node is not a manager", "unavailable")
        try:
            # follower sockets forward to the leader (the raftproxy analog);
            # a remote leader is driven via its Control.Call gRPC
            leader = self.node.broker.select_leader()
        except NoManagerError:
            raise CtlError("no leader available", "unavailable")
        return leader

    async def start(self) -> None:
        self._server = await asyncio.start_unix_server(
            self._handle, path=self.path)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    method = req.get("method", "")
                    if method == "logs.subscribe":
                        await self._stream_logs(req.get("params", {}),
                                                writer)
                        continue
                    result = await self._dispatch(method,
                                                  req.get("params", {}))
                    resp = {"result": result}
                except ControlError as e:
                    resp = {"error": str(e), "code": e.code}
                except CtlError as e:
                    resp = {"error": str(e), "code": e.code}
                except Exception as e:
                    log.exception("ctl request failed")
                    resp = {"error": str(e), "code": "internal"}
                writer.write(json.dumps(resp).encode() + b"\n")
                await writer.drain()
        except (asyncio.CancelledError, ConnectionError):
            pass
        finally:
            writer.close()

    # ------------------------------------------------------------------
    async def _stream_logs(self, p: dict, writer) -> None:
        """`service logs` over the socket: one {"stream": msg} line per
        LogMessage, then {"result": "eof"} (reference: the Logs gRPC
        server stream, api/logbroker.proto SubscribeLogs)."""
        from swarmkit_tpu.manager.logbroker import (
            LogSelector, SubscribeLogsOptions,
        )

        leader = self._control()
        lb = getattr(leader, "logbroker", None)
        if lb is None:
            raise CtlError("leader has no log broker", "unavailable")
        selector = LogSelector(service_ids=p.get("service_ids") or [],
                               node_ids=p.get("node_ids") or [],
                               task_ids=p.get("task_ids") or [])
        options = SubscribeLogsOptions(follow=bool(p.get("follow", False)),
                                       tail=int(p.get("tail", -1)))
        try:
            async for m in lb.subscribe_logs(selector, options):
                writer.write(json.dumps({"stream": {
                    "service_id": m.context.service_id,
                    "node_id": m.context.node_id,
                    "task_id": m.context.task_id,
                    "timestamp": m.timestamp,
                    "stream": int(m.stream),
                    "data": m.data.decode("utf-8", "replace"),
                }}).encode() + b"\n")
                await writer.drain()
        except Exception as e:
            # terminate with the ERROR, never a clean eof: the client must
            # see truncation as a failure, and exactly ONE response line
            # may end the stream (a second would corrupt the next request)
            writer.write(json.dumps(
                {"error": str(e), "code": "unavailable"}).encode() + b"\n")
            await writer.drain()
            return
        writer.write(json.dumps({"result": "eof"}).encode() + b"\n")
        await writer.drain()

    async def _dispatch(self, method: str, p: dict):
        leader = self._control()
        if hasattr(leader, "control_call"):
            # remote leader (gRPC): forward the raw JSON request
            return await leader.control_call(method, p)
        return await dispatch_control(leader.control_api, method, p)


async def dispatch_control(c, method: str, p: dict):
    """Shared control-API JSON dispatch (unix socket + gRPC Control.Call)."""
    if method == "cluster.inspect":
        return c.get_cluster().to_dict()
    if method == "cluster.metrics":
        # hot-path latency percentiles + object gauges (reference: the
        # prometheus endpoint; names match raft.go:69-71 / memory.go:81-110)
        from swarmkit_tpu.utils import metrics as _metrics

        reg = getattr(c, "metrics_registry", None) or _metrics.REGISTRY
        out = {"timers": reg.snapshot()}
        collector = getattr(c, "metrics", None)
        if collector is not None:
            out["gauges"] = collector.snapshot()
        return out
    if method == "cluster.update":
        # mutate the live spec (reference: cmd/swarmctl/cluster/update.go
        # reads-modifies-writes ClusterSpec; components re-read on
        # EventUpdateCluster — reaper retention, dispatcher heartbeat
        # period, CA cert expiry)
        cl = c.get_cluster()
        spec = cl.spec.copy()
        if "task_history" in p:
            spec.orchestration.task_history_retention_limit = \
                int(p["task_history"])
        if "heartbeat_period" in p:
            spec.dispatcher.heartbeat_period = float(p["heartbeat_period"])
        if "cert_expiry" in p:
            spec.ca_config.node_cert_expiry = float(p["cert_expiry"])
        cl2 = await c.update_cluster(
            cl.id, spec, version=cl.meta.version.index,
            rotate_worker_token=bool(p.get("rotate_worker_token")),
            rotate_manager_token=bool(p.get("rotate_manager_token")))
        return cl2.to_dict()
    if method == "cluster.rotate-ca":
        return await c.rotate_root_ca()
    if method == "cluster.autolock":
        cl = c.get_cluster()
        spec = cl.spec.copy()
        spec.encryption_config.auto_lock_managers = bool(p["enabled"])
        await c.update_cluster(cl.id, spec,
                               version=cl.meta.version.index)
        return c.get_unlock_key()
    if method == "cluster.get-unlock-key":
        return c.get_unlock_key()
    if method == "cluster.rotate-unlock-key":
        return await c.rotate_unlock_key()
    if method == "cluster.unlock-key":
        # historical name: returns the JOIN TOKENS (swarmctl
        # cluster-tokens); the autolock key lives at cluster.get-unlock-key
        cl = c.get_cluster()
        return {"worker": cl.root_ca.join_token_worker,
                "manager": cl.root_ca.join_token_manager}
    if method == "node.ls":
        return [n.to_dict() for n in c.list_nodes()]
    if method == "node.inspect":
        return c.get_node(p["id"]).to_dict()
    if method == "node.rm":
        await c.remove_node(p["id"], force=p.get("force", False))
        return {}
    if method in ("node.promote", "node.demote", "node.update"):
        node = c.get_node(p["id"])
        spec = node.spec.copy()
        if method == "node.promote":
            spec.desired_role = NodeRole.MANAGER
        elif method == "node.demote":
            spec.desired_role = NodeRole.WORKER
        if "availability" in p:
            spec.availability = NodeAvailability(p["availability"])
        if "labels_add" in p or "labels_rm" in p:
            # node labels live on the SPEC annotations (the operator's
            # half; reference cmd/swarmctl/node/update.go) — the
            # constraint language reads them from there.  `spec` is
            # already a deep copy (Message.copy), so mutate in place.
            spec.annotations.labels.update(p.get("labels_add") or {})
            for k in p.get("labels_rm") or []:
                spec.annotations.labels.pop(k, None)
        node2 = await c.update_node(p["id"], spec,
                                    version=node.meta.version.index)
        return node2.to_dict()
    if method == "service.create":
        spec = ServiceSpec.from_dict(p["spec"])
        return (await c.create_service(spec)).to_dict()
    if method == "service.ls":
        return [s.to_dict() for s in c.list_services()]
    if method == "service.inspect":
        return c.get_service(p["id"]).to_dict()
    if method == "service.update":
        spec = ServiceSpec.from_dict(p["spec"])
        return (await c.update_service(
            p["id"], spec, version=p.get("version"))).to_dict()
    if method == "service.rollback":
        return (await c.rollback_service(
            p["id"], version=p.get("version"))).to_dict()
    if method == "service.rm":
        await c.remove_service(p["id"])
        return {}
    if method == "task.ls":
        return [t.to_dict() for t in c.list_tasks(
            service_ids=p.get("service_ids"),
            node_ids=p.get("node_ids"))]
    if method == "task.inspect":
        return c.get_task(p["id"]).to_dict()
    if method == "network.create":
        spec = NetworkSpec.from_dict(p["spec"])
        return (await c.create_network(spec)).to_dict()
    if method == "network.ls":
        return [n.to_dict() for n in c.list_networks()]
    if method == "network.inspect":
        return c.get_network(p["id"]).to_dict()
    if method == "network.rm":
        await c.remove_network(p["id"])
        return {}
    if method == "secret.create":
        spec = SecretSpec.from_dict(p["spec"])
        return (await c.create_secret(spec)).to_dict()
    if method == "secret.ls":
        return [s.to_dict() for s in c.list_secrets()]
    if method == "secret.inspect":
        return c.get_secret(p["id"]).to_dict()
    if method == "secret.rm":
        await c.remove_secret(p["id"])
        return {}
    if method == "config.create":
        spec = ConfigSpec.from_dict(p["spec"])
        return (await c.create_config(spec)).to_dict()
    if method == "config.inspect":
        return c.get_config(p["id"]).to_dict()
    if method == "config.ls":
        return [s.to_dict() for s in c.list_configs()]
    if method == "config.rm":
        await c.remove_config(p["id"])
        return {}
    raise CtlError(f"unknown method {method!r}", "unimplemented")


class ControlSocketClient:
    """swarmctl's side of the socket."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_unix_connection(
            self.path)

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
            self._reader = None

    async def stream(self, method: str, **params):
        """Server-streaming call: yields {"stream": ...} payloads until
        the terminating {"result": "eof"} line."""
        if self._writer is None:
            await self.connect()
        self._writer.write(json.dumps(
            {"method": method, "params": params}).encode() + b"\n")
        await self._writer.drain()
        while True:
            line = await self._reader.readline()
            if not line:
                raise CtlError("connection closed", "unavailable")
            resp = json.loads(line)
            if "error" in resp:
                raise CtlError(resp["error"], resp.get("code", "unknown"))
            if "stream" in resp:
                yield resp["stream"]
                continue
            return

    async def call(self, method: str, **params):
        if self._writer is None:
            await self.connect()
        self._writer.write(json.dumps(
            {"method": method, "params": params}).encode() + b"\n")
        await self._writer.drain()
        line = await self._reader.readline()
        if not line:
            raise CtlError("connection closed", "unavailable")
        resp = json.loads(line)
        if "error" in resp:
            raise CtlError(resp["error"], resp.get("code", "unknown"))
        return resp["result"]

"""swarm-rafttool: offline decrypt + dump of raft WAL segments and
snapshots.

Reference: cmd/swarm-rafttool (main.go:19, dump.go) — dump-wal, dump-snapshot,
dump-object against a stopped node's state dir, decrypting with the node's
DEK.
"""

from __future__ import annotations

import argparse
import json
import sys

from swarmkit_tpu.raft.messages import EntryType
from swarmkit_tpu.raft.storage import EncryptedRaftLogger


def _logger(state_dir: str) -> EncryptedRaftLogger:
    return EncryptedRaftLogger(state_dir)


def dump_wal(state_dir: str, out=sys.stdout) -> int:
    """Decode every entry in the WAL (reference: dump.go dumpWAL)."""
    lg = _logger(state_dir)
    result = lg.bootstrap_from_disk()
    count = 0
    for e in result.entries:
        rec = {"index": e.index, "term": e.term,
               "type": EntryType(e.type).name}
        if e.type == EntryType.NORMAL and e.data:
            try:
                from swarmkit_tpu.api.raft_msgs import InternalRaftRequest

                req = InternalRaftRequest.decode(e.data)
                rec["request"] = req.to_dict()
            except Exception:
                rec["data_bytes"] = len(e.data)
        elif e.data:
            rec["data_bytes"] = len(e.data)
        json.dump(rec, out, default=str)
        out.write("\n")
        count += 1
    print(f"dumped {count} entries", file=sys.stderr)
    return 0


def dump_snapshot(state_dir: str, out=sys.stdout) -> int:
    """reference: dump.go dumpSnapshot."""
    lg = _logger(state_dir)
    result = lg.bootstrap_from_disk()
    if result.snapshot is None:
        print("no snapshot", file=sys.stderr)
        return 1
    snap = result.snapshot
    rec = {"index": snap.meta.index, "term": snap.meta.term,
           "data_bytes": len(snap.data)}
    try:
        from swarmkit_tpu.api.raft_msgs import Snapshot as ApiSnapshot

        payload = ApiSnapshot.decode(snap.data)
        rec["payload_type"] = type(payload).__name__
        rec["version"] = payload.version
        rec["members"] = len(payload.membership.members)
    except Exception:
        pass
    json.dump(rec, out, default=str)
    out.write("\n")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="swarm-rafttool")
    p.add_argument("--state-dir", required=True)
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("dump-wal")
    sub.add_parser("dump-snapshot")
    args = p.parse_args(argv)
    if args.cmd == "dump-wal":
        return dump_wal(args.state_dir)
    return dump_snapshot(args.state_dir)


if __name__ == "__main__":
    raise SystemExit(main())

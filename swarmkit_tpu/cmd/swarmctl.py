"""swarmctl: CLI over the control socket.

Reference: cmd/swarmctl — cluster/node/service/task/network/secret/config
subcommands against the Control API unix socket.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from swarmkit_tpu.api import TaskState
from swarmkit_tpu.cmd.ctl import ControlSocketClient, CtlError


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="swarmctl")
    p.add_argument("--socket", "-s", default="./swarmkitstate/swarmd.sock")
    sub = p.add_subparsers(dest="cmd", required=True)

    sub.add_parser("cluster-inspect")
    sub.add_parser("metrics")
    sub.add_parser("cluster-tokens")
    sub.add_parser("cluster-rotate-ca")
    sp = sub.add_parser("cluster-update")
    sp.add_argument("--task-history", type=int, default=None,
                    help="dead tasks retained per slot (reaper)")
    sp.add_argument("--heartbeat-period", type=float, default=None,
                    help="agent heartbeat period seconds (dispatcher)")
    sp.add_argument("--cert-expiry", type=float, default=None,
                    help="node certificate lifetime seconds (CA)")
    sp.add_argument("--rotate-worker-token", action="store_true")
    sp.add_argument("--rotate-manager-token", action="store_true")
    sp = sub.add_parser("cluster-autolock")
    sp.add_argument("enabled", choices=["on", "off"])
    sp = sub.add_parser("cluster-unlock-key")
    sp.add_argument("--rotate", action="store_true")

    sub.add_parser("node-ls")
    for name in ("node-inspect", "node-rm", "node-promote", "node-demote"):
        sp = sub.add_parser(name)
        sp.add_argument("id")
        if name == "node-rm":
            sp.add_argument("--force", action="store_true")
    # reference: cmd/swarmctl/node/update.go (activate/pause/drain live as
    # their own verbs there; one verb + --availability covers all three)
    sp = sub.add_parser("node-update")
    sp.add_argument("id")
    sp.add_argument("--availability", choices=["active", "pause", "drain"])
    sp.add_argument("--label-add", action="append", default=[],
                    metavar="KEY=VALUE")
    sp.add_argument("--label-rm", action="append", default=[],
                    metavar="KEY")

    sp = sub.add_parser("service-create")
    sp.add_argument("--name", required=True)
    sp.add_argument("--image", required=True)
    sp.add_argument("--mode", choices=["replicated", "global"],
                    default="replicated")
    sp.add_argument("--replicas", type=int, default=None,
                    help="replica count (replicated mode only; default 1)")
    sp.add_argument("--env", action="append", default=[])
    sp.add_argument("--constraint", action="append", default=[])
    sp.add_argument("--mount", action="append", default=[],
                    help="type=bind|volume|tmpfs,source=...,target=...,"
                         "[readonly] (repeatable; reference swarmctl "
                         "--bind/--volume/--tmpfs folded into one flag)")
    sp.add_argument("--label", action="append", default=[],
                    metavar="KEY=VALUE", help="service label (repeatable)")
    sp.add_argument("--hostname", default=None,
                    help="container hostname (templated, e.g. "
                         "{{.Service.Name}}-{{.Task.Slot}})")
    sp.add_argument("--command", action="append", default=[],
                    help="override entrypoint (repeatable)")
    sp.add_argument("--arg", action="append", default=[],
                    help="container arg (repeatable)")
    sp.add_argument("--restart-window", type=float, default=None,
                    help="seconds over which restart attempts are counted")
    sp.add_argument("--generic-resource", action="append", default=[],
                    metavar="KIND=N",
                    help="generic resource reservation, e.g. tpu-chip=2")
    sp.add_argument("--limit-cpu", type=float, default=None,
                    help="CPU cores limit per task")
    sp.add_argument("--limit-memory", type=int, default=None,
                    help="bytes of memory limit per task")
    sp.add_argument("--log-driver", default=None)
    sp.add_argument("--log-opt", action="append", default=[],
                    metavar="KEY=VALUE")
    sp.add_argument("--publish", action="append", default=[],
                    help="published:target port, e.g. 8080:80")
    sp.add_argument("--network", action="append", default=[],
                    help="attach to network (name or id; repeatable)")
    sp.add_argument("--secret", action="append", default=[],
                    help="expose secret to the task (name; repeatable)")
    sp.add_argument("--config", action="append", default=[],
                    help="expose config to the task (name; repeatable)")
    sp.add_argument("--reserve-cpu", type=float, default=None,
                    help="CPUs to reserve per task (cores, e.g. 0.5)")
    sp.add_argument("--reserve-memory", type=int, default=None,
                    help="bytes of memory to reserve per task")
    sp.add_argument("--restart-condition", default=None,
                    choices=["any", "failure", "none"])
    sp.add_argument("--restart-delay", type=float, default=None,
                    help="seconds between restarts")
    sp.add_argument("--restart-max-attempts", type=int, default=None)
    sub.add_parser("service-ls")
    for name in ("service-inspect", "service-rm"):
        sub.add_parser(name).add_argument("id")
    sp = sub.add_parser("service-scale")
    sp.add_argument("id")
    sp.add_argument("replicas", type=int)

    sp = sub.add_parser("task-ls")
    sp.add_argument("--service", default=None)
    sub.add_parser("task-inspect").add_argument("id")

    sp = sub.add_parser("service-update")
    sp.add_argument("id")
    sp.add_argument("--image", default=None)
    sp.add_argument("--replicas", type=int, default=None)
    sp.add_argument("--env", action="append", default=None,
                    help="replace the env list (repeatable)")
    sp.add_argument("--command", action="append", default=None,
                    help="replace the entrypoint (repeatable)")
    sp.add_argument("--arg", action="append", default=None,
                    help="replace the args list (repeatable)")
    sp.add_argument("--hostname", default=None)
    sp.add_argument("--mount", action="append", default=None,
                    help="replace the mount list (repeatable; same syntax "
                         "as service-create)")
    sp.add_argument("--label-add", action="append", default=[],
                    metavar="KEY=VALUE")
    sp.add_argument("--label-rm", action="append", default=[],
                    metavar="KEY")
    sp.add_argument("--restart-condition", default=None,
                    choices=["any", "failure", "none"])
    sp.add_argument("--restart-delay", type=float, default=None)
    sp.add_argument("--restart-max-attempts", type=int, default=None)
    sp.add_argument("--restart-window", type=float, default=None)
    sp.add_argument("--force", action="store_true",
                    help="bump force_update to replace tasks even with an "
                         "unchanged spec")
    sp.add_argument("--update-parallelism", type=int, default=None)
    sp.add_argument("--update-delay", type=float, default=None)
    sp.add_argument("--update-order", default=None,
                    choices=["stop-first", "start-first"])
    sp.add_argument("--update-failure-action", default=None,
                    choices=["pause", "continue", "rollback"])
    sp.add_argument("--update-monitor", type=float, default=None)
    sp.add_argument("--update-max-failure-ratio", type=float, default=None)
    sp.add_argument("--rollback-parallelism", type=int, default=None)
    sp.add_argument("--rollback-order", default=None,
                    choices=["stop-first", "start-first"])
    sub.add_parser("service-rollback").add_argument("id")

    sp = sub.add_parser("service-logs")
    sp.add_argument("id", help="service id (or task id with --task)")
    sp.add_argument("--task", action="store_true",
                    help="treat id as a task id")
    sp.add_argument("--follow", "-f", action="store_true")
    sp.add_argument("--tail", type=int, default=-1,
                    help="last N buffered lines per task (-1 = all)")

    sp = sub.add_parser("network-create")
    sp.add_argument("--name", required=True)
    sp.add_argument("--driver", default=None,
                    help="network driver name (scheduler plugin-filters "
                         "driver-named networks)")
    sp.add_argument("--subnet", action="append", default=[],
                    help="CIDR pool (repeatable; default: auto 10.x.0.0/24)")
    sub.add_parser("network-ls")
    sub.add_parser("network-inspect").add_argument("id")
    sub.add_parser("network-rm").add_argument("id")

    for kind in ("secret", "config"):
        sp = sub.add_parser(f"{kind}-create")
        sp.add_argument("name")
        sp.add_argument("--data", required=True)
        sub.add_parser(f"{kind}-ls")
        sub.add_parser(f"{kind}-inspect").add_argument("id")
        sub.add_parser(f"{kind}-rm").add_argument("id")
    return p


def _parse_mount(text: str) -> dict:
    """type=bind,source=/x,target=/y[,readonly] -> Mount dict."""
    m: dict = {"type": "bind", "read_only": False}
    for part in text.split(","):
        if part == "readonly" or part == "ro":
            m["read_only"] = True
        elif "=" in part:
            k, _, v = part.partition("=")
            if k not in ("type", "source", "target"):
                raise CtlError(f"unknown mount option {k!r}", "invalid")
            m[k] = v
        elif part:
            raise CtlError(f"bad mount option {part!r}", "invalid")
    return m


_RESTART_CONDITIONS = {"none": 0, "failure": 1, "any": 2}


def _restart_flags(args) -> Optional[dict]:
    """RestartPolicy fields present on `args`, or None if none given
    (shared by service-create and service-update)."""
    if args.restart_condition is None and args.restart_delay is None \
            and args.restart_max_attempts is None \
            and args.restart_window is None:
        return None
    restart: dict = {}
    if args.restart_condition is not None:
        restart["condition"] = _RESTART_CONDITIONS[args.restart_condition]
    if args.restart_delay is not None:
        restart["delay"] = args.restart_delay
    if args.restart_max_attempts is not None:
        restart["max_attempts"] = args.restart_max_attempts
    if args.restart_window is not None:
        restart["window"] = args.restart_window
    return restart


def _kv_pairs(items: list[str], what: str) -> dict:
    out = {}
    for kv in items:
        if "=" not in kv:
            raise CtlError(f"{what} wants KEY=VALUE, got {kv!r}", "invalid")
        k, _, v = kv.partition("=")
        out[k] = v
    return out


def _service_spec(args, networks=None, secrets=None, configs=None) -> dict:
    container = {"image": args.image, "env": args.env}
    if args.mount:
        container["mounts"] = [_parse_mount(s) for s in args.mount]
    if args.hostname:
        container["hostname"] = args.hostname
    if args.command:
        container["command"] = list(args.command)
    if args.arg:
        container["args"] = list(args.arg)
    if secrets:
        container["secrets"] = [
            {"secret_id": sid, "secret_name": name}
            for sid, name in secrets]
    if configs:
        container["configs"] = [
            {"config_id": cid, "config_name": name}
            for cid, name in configs]
    task = {"container": container,
            "placement": {"constraints": args.constraint}}
    if networks:
        task["networks"] = list(networks)
    resources: dict = {}
    generic = {}
    for k, v in _kv_pairs(args.generic_resource,
                          "--generic-resource").items():
        try:
            generic[k] = int(v)
        except ValueError:
            raise CtlError(
                f"--generic-resource wants KIND=N, got {k}={v!r}",
                "invalid")
        if generic[k] < 0:
            raise CtlError(
                f"--generic-resource {k} must be non-negative", "invalid")
    if args.reserve_cpu is not None or args.reserve_memory is not None \
            or generic:
        resources["reservations"] = {
            "nano_cpus": int((args.reserve_cpu or 0) * 1e9),
            "memory_bytes": args.reserve_memory or 0,
            "generic": generic}
    if args.limit_cpu is not None or args.limit_memory is not None:
        resources["limits"] = {
            "nano_cpus": int((args.limit_cpu or 0) * 1e9),
            "memory_bytes": args.limit_memory or 0}
    if resources:
        task["resources"] = resources
    restart = _restart_flags(args)
    if restart is not None:
        task["restart"] = restart
    if args.log_opt and not args.log_driver:
        raise CtlError("--log-opt requires --log-driver", "invalid")
    if args.log_driver:
        task["log_driver"] = {
            "name": args.log_driver,
            "options": _kv_pairs(args.log_opt, "--log-opt")}
    spec = {
        "annotations": {"name": args.name,
                        "labels": _kv_pairs(args.label, "--label")},
        "task": task,
    }
    if getattr(args, "mode", "replicated") == "global":
        from swarmkit_tpu.api.specs import Mode
        spec["mode"] = int(Mode.GLOBAL)
        spec["global_"] = {}
    else:
        spec["replicated"] = {"replicas": 1 if args.replicas is None
                              else args.replicas}
    if args.publish:
        ports = []
        for spec_str in args.publish:
            pub, _, tgt = spec_str.partition(":")
            ports.append({"protocol": "tcp", "published_port": int(pub),
                          "target_port": int(tgt or pub),
                          "publish_mode": "ingress"})
        spec["endpoint"] = {"ports": ports}
    return spec


async def _resolve(client, kind: str, names: list[str]) -> list:
    """Resolve refs (name | id | unique id prefix) to (id, name) pairs.

    The <kind>.ls scan is fetched at most once per call no matter how many
    refs miss the direct-Get fast path."""
    out, objs = [], None
    for ref in names:
        try:
            obj = await client.call(f"{kind}.inspect", id=ref)
        except CtlError as e:
            if e.code != "not_found":
                raise
            if objs is None:
                objs = await client.call(f"{kind}.ls")
            obj = _match_ref(kind, objs, ref)
        out.append((obj["id"], _display_name(kind, obj)))
    return out


def _display_name(kind: str, obj: dict) -> str:
    if kind == "node":
        # nodes are addressed by hostname (reference cmd/swarmctl/node/
        # util.go getNode: ID first, then hostname scan)
        return (obj.get("description") or {}).get("hostname") or ""
    return (((obj.get("spec") or {}).get("annotations") or {})
            .get("name") or "")


async def _resolve_obj(client, kind: str, ref: str) -> dict:
    """Exact id, name (hostname for nodes), or unique id prefix -> object.

    Every positional object argument accepts any of the three, the way the
    reference CLI does (cmd/swarmctl/service/util.go getService,
    node/util.go getNode, network/util.go, secret/config util) — ambiguity
    and absence are CLI errors, never a silent no-match.  Like the
    reference, the direct Get is tried first; the <kind>.ls scan only runs
    when the ref is not an exact id.  Returns the fetched object so
    callers never pay a second inspect for it.
    """
    try:
        return await client.call(f"{kind}.inspect", id=ref)
    except CtlError as e:
        if e.code != "not_found":
            raise
    return _match_ref(kind, await client.call(f"{kind}.ls"), ref)


def _match_ref(kind: str, objs: list, ref: str) -> dict:
    """Scan a <kind>.ls result for a name or unique-id-prefix match."""
    by_name: dict[str, list[dict]] = {}
    for o in objs:
        nm = _display_name(kind, o)
        if nm:
            by_name.setdefault(nm, []).append(o)
    if ref in by_name:
        matches = by_name[ref]
        if len(matches) > 1:
            raise CtlError(f"{kind} name {ref!r} is ambiguous "
                           f"({len(matches)} matches)", "ambiguous")
        return matches[0]
    pref = [o for o in objs if o["id"].startswith(ref)] if ref else []
    if len(pref) == 1:
        return pref[0]
    if len(pref) > 1:
        raise CtlError(f"{kind} id prefix {ref!r} is ambiguous "
                       f"({len(pref)} matches)", "ambiguous")
    raise CtlError(f"{kind} {ref!r} not found", "not_found")


async def _resolve_ref(client, kind: str, ref: str) -> str:
    return (await _resolve_obj(client, kind, ref))["id"]


async def run(args, out=None) -> int:
    out = out or sys.stdout
    client = ControlSocketClient(args.socket)

    def show(obj):
        json.dump(obj, out, indent=2, default=str)
        out.write("\n")

    try:
        c = args.cmd
        # Normalize the positional object ref (name | id | unique id
        # prefix) for every `<kind>-<verb>` command that takes one.
        kind = c.split("-")[0]
        resolved = None   # the fetched object; saves handlers a re-inspect
        if getattr(args, "id", None) is not None and kind in (
                "service", "node", "network", "secret", "config", "task"):
            if c == "service-logs" and args.task:
                kind = "task"
            resolved = await _resolve_obj(client, kind, args.id)
            args.id = resolved["id"]
        if c == "task-ls" and args.service:
            args.service = await _resolve_ref(client, "service",
                                              args.service)
        if c == "cluster-inspect":
            show(await client.call("cluster.inspect"))
        elif c == "metrics":
            show(await client.call("cluster.metrics"))
        elif c == "cluster-tokens":
            show(await client.call("cluster.unlock-key"))
        elif c == "cluster-update":
            p2: dict = {}
            if args.task_history is not None:
                p2["task_history"] = args.task_history
            if args.heartbeat_period is not None:
                p2["heartbeat_period"] = args.heartbeat_period
            if args.cert_expiry is not None:
                p2["cert_expiry"] = args.cert_expiry
            if args.rotate_worker_token:
                p2["rotate_worker_token"] = True
            if args.rotate_manager_token:
                p2["rotate_manager_token"] = True
            show(await client.call("cluster.update", **p2))
        elif c == "cluster-rotate-ca":
            show(await client.call("cluster.rotate-ca"))
        elif c == "cluster-autolock":
            show(await client.call("cluster.autolock",
                                   enabled=args.enabled == "on"))
        elif c == "cluster-unlock-key":
            method = ("cluster.rotate-unlock-key" if args.rotate
                      else "cluster.get-unlock-key")
            show(await client.call(method))
        elif c == "node-ls":
            for n in await client.call("node.ls"):
                role = "manager" if n.get("role") else "worker"
                state = {0: "unknown", 1: "down", 2: "ready",
                         3: "disconnected"}.get(
                    n.get("status", {}).get("state", 0), "?")
                out.write(f"{n['id']}\t{role}\t{state}\n")
        elif c == "node-inspect":
            show(resolved)
        elif c == "node-rm":
            await client.call("node.rm", id=args.id, force=args.force)
        elif c == "node-promote":
            await client.call("node.promote", id=args.id)
        elif c == "node-demote":
            await client.call("node.demote", id=args.id)
        elif c == "node-update":
            p: dict = {"id": args.id}
            if args.availability is not None:
                from swarmkit_tpu.api.types import NodeAvailability
                p["availability"] = int(
                    NodeAvailability[args.availability.upper()])
            if args.label_add:
                p["labels_add"] = _kv_pairs(args.label_add, "--label-add")
            if args.label_rm:
                p["labels_rm"] = list(args.label_rm)
            show(await client.call("node.update", **p))
        elif c == "service-create":
            if args.mode == "global" and args.replicas is not None:
                print("error: --replicas conflicts with --mode global "
                      "(global services run one task per node)",
                      file=sys.stderr)
                return 1
            networks = [nid for nid, _ in
                        await _resolve(client, "network", args.network)]
            secrets = await _resolve(client, "secret", args.secret)
            configs = await _resolve(client, "config", args.config)
            show(await client.call("service.create",
                                   spec=_service_spec(args, networks,
                                                      secrets, configs)))
        elif c == "service-ls":
            for s in await client.call("service.ls"):
                name = s["spec"]["annotations"]["name"]
                replicas = s["spec"].get("replicated", {}).get("replicas", "")
                out.write(f"{s['id']}\t{name}\t{replicas}\n")
        elif c == "service-inspect":
            show(resolved)
        elif c == "service-scale":
            svc = resolved
            if not svc["spec"].get("replicated"):
                print("error: only replicated services can be scaled",
                      file=sys.stderr)
                return 1
            svc["spec"]["replicated"]["replicas"] = args.replicas
            show(await client.call(
                "service.update", id=args.id, spec=svc["spec"],
                version=svc["meta"]["version"]["index"]))
        elif c == "service-rm":
            await client.call("service.rm", id=args.id)
        elif c == "service-update":
            cur = resolved
            spec = cur["spec"]
            # only materialize task/container sub-objects when a container
            # flag was actually given — an unrelated update must not
            # mutate a container-less service spec
            cont_flags = {"image": args.image, "env": args.env,
                          "command": args.command, "args": args.arg,
                          "hostname": args.hostname}
            if any(v is not None for v in cont_flags.values()) \
                    or args.mount is not None:
                cont = spec.setdefault("task", {}).setdefault(
                    "container", {})
                for key, v in cont_flags.items():
                    if v is not None:
                        cont[key] = list(v) if isinstance(v, list) else v
                if args.mount is not None:
                    cont["mounts"] = [_parse_mount(s) for s in args.mount]
            if args.label_add or args.label_rm:
                labels = spec.setdefault("annotations", {}).setdefault(
                    "labels", {})
                labels.update(_kv_pairs(args.label_add, "--label-add"))
                for k in args.label_rm:
                    labels.pop(k, None)
            rflags = _restart_flags(args)
            if rflags is not None:
                spec.setdefault("task", {}).setdefault(
                    "restart", {}).update(rflags)
            if args.replicas is not None and spec.get("replicated"):
                spec["replicated"]["replicas"] = args.replicas
            if args.force:
                task_spec = spec.setdefault("task", {})
                task_spec["force_update"] = \
                    int(task_spec.get("force_update", 0)) + 1
            upd = spec.get("update") or {}
            for flag, key in (("update_parallelism", "parallelism"),
                              ("update_delay", "delay"),
                              ("update_monitor", "monitor"),
                              ("update_max_failure_ratio",
                               "max_failure_ratio")):
                v = getattr(args, flag)
                if v is not None:
                    upd[key] = v
            if args.update_order is not None:
                upd["order"] = {"stop-first": 0,
                                "start-first": 1}[args.update_order]
            if args.update_failure_action is not None:
                upd["failure_action"] = {
                    "pause": 0, "continue": 1,
                    "rollback": 2}[args.update_failure_action]
            if upd:
                spec["update"] = upd
            rb = spec.get("rollback") or {}
            if args.rollback_parallelism is not None:
                rb["parallelism"] = args.rollback_parallelism
            if args.rollback_order is not None:
                rb["order"] = {"stop-first": 0,
                               "start-first": 1}[args.rollback_order]
            if rb:
                spec["rollback"] = rb
            show(await client.call(
                "service.update", id=args.id, spec=spec,
                version=cur["meta"]["version"]["index"]))
        elif c == "service-rollback":
            show(await client.call("service.rollback", id=args.id))
        elif c == "service-logs":
            sel = ({"task_ids": [args.id]} if args.task
                   else {"service_ids": [args.id]})
            async for m in client.stream("logs.subscribe", follow=args.follow,
                                         tail=args.tail, **sel):
                tag = "ERR" if m["stream"] == 2 else "OUT"
                out.write(f"{m['task_id'][:12]}@{m['node_id'][:12]} "
                          f"{tag} | {m['data']}\n")
        elif c == "task-inspect":
            show(resolved)
        elif c == "task-ls":
            ids = [args.service] if args.service else None
            for t in await client.call("task.ls", service_ids=ids):
                state = TaskState(t.get("status", {}).get("state", 0)).name
                out.write(f"{t['id']}\t{t.get('node_id','')}\t{state}\n")
        elif c == "network-create":
            nspec: dict = {"annotations": {"name": args.name}}
            if args.driver:
                nspec["driver_config"] = {"name": args.driver}
            if args.subnet:
                nspec["ipam"] = {"configs": [{"subnet": sn}
                                             for sn in args.subnet]}
            show(await client.call("network.create", spec=nspec))
        elif c == "network-inspect":
            show(resolved)
        elif c == "network-ls":
            for n in await client.call("network.ls"):
                out.write(f"{n['id']}\t{n['spec']['annotations']['name']}\n")
        elif c == "network-rm":
            await client.call("network.rm", id=args.id)
        elif c.endswith("-create") and c.split("-")[0] in ("secret",
                                                          "config"):
            kind = c.split("-")[0]
            import base64

            show(await client.call(
                f"{kind}.create",
                spec={"annotations": {"name": args.name},
                      "data": {"__b64__": base64.b64encode(
                          args.data.encode()).decode()}}))
        elif c in ("secret-inspect", "config-inspect"):
            show(resolved)
        elif c in ("secret-ls", "config-ls"):
            kind = c.split("-")[0]
            for s in await client.call(f"{kind}.ls"):
                out.write(f"{s['id']}\t{s['spec']['annotations']['name']}\n")
        elif c in ("secret-rm", "config-rm"):
            await client.call(f"{c.split('-')[0]}.rm", id=args.id)
        else:
            out.write(f"unknown command {c}\n")
            return 2
        return 0
    except CtlError as e:
        print(f"error ({e.code}): {e}", file=sys.stderr)
        return 1
    finally:
        await client.close()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return asyncio.run(run(args))
    except BrokenPipeError:
        # stdout consumer (e.g. `| head`) went away — normal CLI etiquette
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), 1)
        return 0


if __name__ == "__main__":
    raise SystemExit(main())

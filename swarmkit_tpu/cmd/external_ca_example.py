"""External CA example server: a minimal CFSSL-protocol sign endpoint
backed by a RootCA key.

Reference: cmd/external-ca-example — demonstrates holding the cluster's
signing key OUTSIDE the managers: the cluster's CAServer (with a
key-less RootCA) posts CSRs here and this daemon signs them.

POST body:  {"certificate_request": pem, "subject": {"CN", "names": [{"OU","O"}]},
             "hosts": [...]}
Response:   {"success": true, "result": {"certificate": pem}}
"""

from __future__ import annotations

import argparse
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from swarmkit_tpu.ca.certificates import RootCA


def make_handler(root_ca: RootCA):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def do_POST(self):
            try:
                body = json.loads(self.rfile.read(
                    int(self.headers.get("Content-Length", 0))))
                csr = body["certificate_request"].encode()
                subject = body.get("subject", {})
                cn = subject.get("CN", "")
                names = subject.get("names") or [{}]
                role_ou = names[0].get("OU", "")
                org = names[0].get("O", "")
                issued = root_ca.issue_node_certificate(
                    cn, role_ou, org, csr_pem=csr)
                resp = {"success": True,
                        "result": {"certificate":
                                   issued.cert_pem.decode()}}
                code = 200
            except Exception as e:
                resp = {"success": False, "errors": [str(e)]}
                code = 400
            raw = json.dumps(resp).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

    return Handler


def serve(root_ca: RootCA, host: str = "127.0.0.1", port: int = 0):
    """Start in a daemon thread; returns (server, actual_port). Tests and
    embedders call server.shutdown() when done."""
    server = ThreadingHTTPServer((host, port), make_handler(root_ca))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, server.server_address[1]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="external-ca-example",
        description="CFSSL-protocol signer for swarmkit external-CA mode")
    p.add_argument("--ca-cert", required=True)
    p.add_argument("--ca-key", required=True)
    p.add_argument("--listen", default="127.0.0.1:8888")
    args = p.parse_args(argv)
    root = RootCA(open(args.ca_cert, "rb").read(),
                  open(args.ca_key, "rb").read())
    host, port = args.listen.rsplit(":", 1)
    server, port = serve(root, host, int(port))
    print(f"external CA signing on {host}:{port}")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

// Native WAL frame codec: batch framing + whole-segment validated scans.
//
// The reference keeps its WAL hot path in Go (coreos/etcd/wal encode/decode
// with CRC); this is the equivalent native component for the rebuild's
// host-side runtime.  Frame layout matches swarmkit_tpu/raft/storage.py
// (_FRAME = "<II": u32 body length, u32 crc32(body), then the body).
//
// Exposed C ABI (driven from Python via ctypes — see native/__init__.py):
//   wal_frame_size(lens, n)                -> total framed bytes
//   wal_frame(bodies, lens, n, out)        -> bytes written
//   wal_scan(blob, len, offs, lens, max)   -> record count; status via
//                                             wal_scan_status (0 ok,
//                                             1 torn tail dropped,
//                                             2 corrupt mid-stream)

#include <cstdint>
#include <cstring>

namespace {

// slice-by-8 CRC-32 (IEEE 802.3), identical results to zlib.crc32
uint32_t crc_table[8][256];
bool crc_ready = false;

void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc_table[0][i] = c;
    }
    for (int t = 1; t < 8; t++)
        for (uint32_t i = 0; i < 256; i++)
            crc_table[t][i] = crc_table[0][crc_table[t - 1][i] & 0xFF]
                              ^ (crc_table[t - 1][i] >> 8);
    crc_ready = true;
}

uint32_t crc32(const uint8_t* data, uint64_t len) {
    if (!crc_ready) crc_init();
    uint32_t c = 0xFFFFFFFFu;
    while (len >= 8) {
        uint32_t lo, hi;
        memcpy(&lo, data, 4);
        memcpy(&hi, data + 4, 4);
        lo ^= c;
        c = crc_table[7][lo & 0xFF] ^ crc_table[6][(lo >> 8) & 0xFF]
          ^ crc_table[5][(lo >> 16) & 0xFF] ^ crc_table[4][lo >> 24]
          ^ crc_table[3][hi & 0xFF] ^ crc_table[2][(hi >> 8) & 0xFF]
          ^ crc_table[1][(hi >> 16) & 0xFF] ^ crc_table[0][hi >> 24];
        data += 8;
        len -= 8;
    }
    for (uint64_t i = 0; i < len; i++)
        c = crc_table[0][(c ^ data[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

const uint64_t HDR = 8;  // u32 length + u32 crc

void put_u32(uint8_t* p, uint32_t v) {
    p[0] = (uint8_t)(v); p[1] = (uint8_t)(v >> 8);
    p[2] = (uint8_t)(v >> 16); p[3] = (uint8_t)(v >> 24);
}

uint32_t get_u32(const uint8_t* p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8)
         | ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}

thread_local int g_scan_status = 0;
thread_local uint64_t g_scan_consumed = 0;

}  // namespace

extern "C" {

uint64_t wal_frame_size(const uint64_t* lens, uint64_t n) {
    uint64_t total = 0;
    for (uint64_t i = 0; i < n; i++) total += HDR + lens[i];
    return total;
}

// bodies: concatenated record bodies; lens: per-record lengths.
uint64_t wal_frame(const uint8_t* bodies, const uint64_t* lens, uint64_t n,
                   uint8_t* out) {
    uint64_t in_off = 0, out_off = 0;
    for (uint64_t i = 0; i < n; i++) {
        const uint8_t* body = bodies + in_off;
        put_u32(out + out_off, (uint32_t)lens[i]);
        put_u32(out + out_off + 4, crc32(body, lens[i]));
        memcpy(out + out_off + HDR, body, lens[i]);
        in_off += lens[i];
        out_off += HDR + lens[i];
    }
    return out_off;
}

int wal_scan_status() { return g_scan_status; }

// Bytes consumed by the last wal_scan — lets callers resume a chunked scan
// without pre-allocating worst-case offset arrays.
uint64_t wal_scan_consumed() { return g_scan_consumed; }

// Scans blob, validating CRCs.  Fills offs/lens with body positions.
// Torn frames at the tail are dropped (status 1); a CRC mismatch that is
// NOT the final record is corruption (status 2, scan stops there).
uint64_t wal_scan(const uint8_t* blob, uint64_t len,
                  uint64_t* offs, uint64_t* lens, uint64_t max_records) {
    uint64_t off = 0, count = 0;
    g_scan_status = 0;
    g_scan_consumed = 0;
    while (off < len && count < max_records) {
        if (off + HDR > len) { g_scan_status = 1; break; }
        uint32_t body_len = get_u32(blob + off);
        uint32_t crc = get_u32(blob + off + 4);
        if (off + HDR + body_len > len) { g_scan_status = 1; break; }
        if (crc32(blob + off + HDR, body_len) != crc) {
            // corrupt tail == torn; corrupt mid-stream is fatal
            g_scan_status = (off + HDR + body_len >= len) ? 1 : 2;
            break;
        }
        offs[count] = off + HDR;
        lens[count] = body_len;
        count++;
        off += HDR + body_len;
    }
    g_scan_consumed = off;
    return count;
}

}  // extern "C"

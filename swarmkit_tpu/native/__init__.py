"""Native (C++) runtime components, loaded via ctypes with a pure-Python
fallback.

The reference's WAL hot path lives in compiled Go (coreos/etcd/wal); here
the equivalent is wal_codec.cpp, compiled on first use with g++ into a
cached shared object.  ``wal_codec()`` returns the module-level codec —
native when the toolchain is available, Python otherwise — with one
interface:

    frame(bodies: list[bytes]) -> bytes         # batch-frame records
    scan(blob: bytes) -> (list[bytes], status)  # validated record bodies
        status: 0 clean, 1 torn tail dropped, 2 corrupt mid-stream
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import tempfile
import threading
import zlib
from typing import Optional

log = logging.getLogger("swarmkit_tpu.native")

_FRAME = struct.Struct("<II")

STATUS_OK = 0
STATUS_TORN_TAIL = 1
STATUS_CORRUPT = 2


class PyWalCodec:
    """Pure-Python fallback; semantics identical to wal_codec.cpp."""

    name = "python"

    def frame(self, bodies: list[bytes]) -> bytes:
        out = bytearray()
        for body in bodies:
            out += _FRAME.pack(len(body), zlib.crc32(body)) + body
        return bytes(out)

    def scan(self, blob: bytes) -> tuple[list[bytes], int]:
        records: list[bytes] = []
        off = 0
        n = len(blob)
        while off < n:
            if off + _FRAME.size > n:
                return records, STATUS_TORN_TAIL
            length, crc = _FRAME.unpack_from(blob, off)
            body = blob[off + _FRAME.size: off + _FRAME.size + length]
            if len(body) < length:
                return records, STATUS_TORN_TAIL
            if zlib.crc32(body) != crc:
                if off + _FRAME.size + length >= n:
                    return records, STATUS_TORN_TAIL
                return records, STATUS_CORRUPT
            records.append(body)
            off += _FRAME.size + length
        return records, STATUS_OK


class NativeWalCodec:
    name = "native"

    def __init__(self, lib: ctypes.CDLL) -> None:
        self._lib = lib
        lib.wal_frame_size.restype = ctypes.c_uint64
        lib.wal_frame_size.argtypes = [ctypes.POINTER(ctypes.c_uint64),
                                       ctypes.c_uint64]
        lib.wal_frame.restype = ctypes.c_uint64
        lib.wal_frame.argtypes = [ctypes.c_char_p,
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.c_uint64, ctypes.c_char_p]
        lib.wal_scan.restype = ctypes.c_uint64
        lib.wal_scan.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                 ctypes.POINTER(ctypes.c_uint64),
                                 ctypes.POINTER(ctypes.c_uint64),
                                 ctypes.c_uint64]
        lib.wal_scan_status.restype = ctypes.c_int
        lib.wal_scan_consumed.restype = ctypes.c_uint64

    def frame(self, bodies: list[bytes]) -> bytes:
        n = len(bodies)
        lens = (ctypes.c_uint64 * n)(*[len(b) for b in bodies])
        concat = b"".join(bodies)
        total = self._lib.wal_frame_size(lens, n)
        out = ctypes.create_string_buffer(total)
        written = self._lib.wal_frame(concat, lens, n, out)
        return out.raw[:written]

    # bounded per-pass offset buffers; chunked resume via wal_scan_consumed
    # avoids worst-case (len/8) allocations on huge segments
    _SCAN_BATCH = 1 << 16

    def scan(self, blob: bytes) -> tuple[list[bytes], int]:
        batch = min(self._SCAN_BATCH, max(1, len(blob) // _FRAME.size))
        offs = (ctypes.c_uint64 * batch)()
        lens = (ctypes.c_uint64 * batch)()
        records: list[bytes] = []
        base = 0
        view = blob
        while True:
            count = self._lib.wal_scan(view, len(view), offs, lens, batch)
            status = self._lib.wal_scan_status()
            records.extend(view[offs[i]: offs[i] + lens[i]]
                           for i in range(count))
            consumed = self._lib.wal_scan_consumed()
            if status != STATUS_OK or consumed >= len(view) or count == 0:
                return records, status
            base += consumed
            view = blob[base:]


_codec = None
_codec_lock = __import__("threading").Lock()


def prebuild_in_background() -> None:
    """Kick the (one-time, up to ~1 s) g++ compile off the event loop —
    called at storage-module import so the first WAL write never blocks a
    raft tick on a cold cache."""
    import threading

    threading.Thread(target=wal_codec, daemon=True).start()


def _build_native() -> Optional[NativeWalCodec]:
    src = os.path.join(os.path.dirname(__file__), "wal_codec.cpp")
    cache_dir = os.path.join(tempfile.gettempdir(), "swarmkit_tpu_native")
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, "wal_codec.so")
    # Unique per builder: the prebuild thread and an import-time caller can
    # both land here in one process, so a pid-keyed temp name would collide.
    tmp_so = so_path + f".build-{os.getpid()}-{threading.get_ident()}"
    for attempt in range(2):
        try:
            if not os.path.exists(so_path) \
                    or os.path.getmtime(so_path) < os.path.getmtime(src):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", tmp_so, src],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp_so, so_path)
            return NativeWalCodec(ctypes.CDLL(so_path))
        except Exception as e:
            # A concurrent builder may have replaced so_path mid-load; one
            # retry picks up whichever build won.
            if attempt == 0 and os.path.exists(so_path):
                continue
            log.info("native wal codec unavailable (%s); using python", e)
            return None
    return None


def wal_codec():
    """The process-wide codec (native if buildable); thread-safe."""
    global _codec
    if _codec is None:
        with _codec_lock:
            if _codec is None:
                if os.environ.get("SWARMKIT_TPU_NO_NATIVE"):
                    _codec = PyWalCodec()
                else:
                    _codec = _build_native() or PyWalCodec()
    return _codec

"""Query combinators (reference: manager/state/store/by.go).

A ``By`` resolves against a table's secondary indexes; ``Or`` unions.
Index names here must match those registered in memory.py's TABLE_INDEXES.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable


class By:
    pass


@dataclass(frozen=True)
class All(By):
    pass


@dataclass(frozen=True)
class ByID(By):
    id: str


@dataclass(frozen=True)
class ByIDPrefix(By):
    prefix: str


@dataclass(frozen=True)
class ByName(By):
    name: str


@dataclass(frozen=True)
class ByNamePrefix(By):
    prefix: str


@dataclass(frozen=True)
class ByService(By):
    service_id: str


@dataclass(frozen=True)
class ByNode(By):
    node_id: str


@dataclass(frozen=True)
class BySlot(By):
    service_id: str
    slot: int


@dataclass(frozen=True)
class ByDesiredState(By):
    state: int


@dataclass(frozen=True)
class ByTaskState(By):
    state: int


@dataclass(frozen=True)
class ByRole(By):
    role: int


@dataclass(frozen=True)
class ByMembership(By):
    membership: int


@dataclass(frozen=True)
class ByReferencedSecret(By):
    secret_id: str


@dataclass(frozen=True)
class ByReferencedConfig(By):
    config_id: str


class Or(By):
    def __init__(self, *bys: By) -> None:
        self.bys = bys


@dataclass(frozen=True)
class Custom(By):
    """Linear-scan predicate escape hatch (no reference analog; convenience)."""

    predicate: Callable

"""MemoryStore: transactional, watchable, raft-replicated object store.

Reference: manager/state/store/memory.go (979 LoC + per-object tables).
Differences from the reference are deliberate TPU-era simplifications:
- tables are Python dicts + maintained secondary-index dicts instead of
  go-memdb radix trees (single-threaded asyncio ⇒ no lock hierarchy);
- the Proposer seam (manager/state/state.go Proposer; mock at
  manager/state/testutils/mock_proposer.go) is an async protocol so the
  leader's ``update`` awaits the raft commit exactly like the reference
  blocks on the wait channel (raft.go:1826-1857).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field as dc_field
from typing import Any, Callable, Iterable, Optional

from swarmkit_tpu.api.objects import OBJECT_KINDS, kind_of
from swarmkit_tpu.api.raft_msgs import StoreAction, StoreActionKind, StoreSnapshot
from swarmkit_tpu.api.types import Meta, Version
from swarmkit_tpu.store import by as by_mod
from swarmkit_tpu.store.errors import (
    ErrExist, ErrInvalidFindBy, ErrNameConflict, ErrNotExist,
    ErrSequenceConflict, ErrTxTooLarge,
)
from swarmkit_tpu.metrics import catalog as obs_catalog
from swarmkit_tpu.metrics import registry as obs_registry
from swarmkit_tpu.utils import metrics
from swarmkit_tpu.watch.queue import Queue

log = logging.getLogger("swarmkit_tpu.store")

# reference: manager/state/store/memory.go:45-48
MAX_CHANGES_PER_TRANSACTION = 200
MAX_TRANSACTION_BYTES = 1.5 * 1024 * 1024


# --------------------------------------------------------------------------
# events

@dataclass
class Event:
    action: str          # "create" | "update" | "remove"
    kind: str            # object kind
    object: Any
    old_object: Any = None

    def matches(self, kind: Optional[str] = None, action: Optional[str] = None
                ) -> bool:
        return ((kind is None or self.kind == kind)
                and (action is None or self.action == action))


@dataclass
class EventCommit:
    version: int = 0


def match(kind: Optional[str] = None, action: Optional[str] = None):
    """Watch matcher factory."""

    def _m(ev) -> bool:
        return isinstance(ev, Event) and ev.matches(kind, action)

    return _m


def match_commit(ev) -> bool:
    return isinstance(ev, EventCommit)


# --------------------------------------------------------------------------
# secondary index extraction (replaces storeobject codegen indexers)

def _name_of(obj) -> str:
    ann = getattr(obj, "annotations", None)
    if ann is not None and ann.name:
        return ann.name
    # nodes are findable by hostname (reference: store/nodes.go hostname index)
    desc = getattr(obj, "description", None)
    if desc is not None and desc.hostname:
        return desc.hostname
    return ""


def _task_indexes(t) -> dict[str, list[str]]:
    idx = {
        "service": [t.service_id] if t.service_id else [],
        "node": [t.node_id] if t.node_id else [],
        "slot": [f"{t.service_id}:{t.slot}"] if t.service_id else [],
        "desired_state": [str(int(t.desired_state))],
        "task_state": [str(int(t.status.state))],
    }
    secrets, configs = [], []
    if t.spec.container is not None:
        secrets = [r.secret_id for r in t.spec.container.secrets]
        configs = [r.config_id for r in t.spec.container.configs]
    idx["secret_ref"] = secrets
    idx["config_ref"] = configs
    return idx


def _node_indexes(n) -> dict[str, list[str]]:
    return {
        "role": [str(int(n.role))],
        "membership": [str(int(n.spec.membership))],
    }


_EXTRA_INDEXES: dict[str, Callable] = {
    "task": _task_indexes,
    "node": _node_indexes,
}

# kinds whose name index is unique (tasks are not named-unique)
_UNIQUE_NAME_KINDS = {"node", "service", "network", "cluster", "secret",
                      "config", "extension", "resource"}


class _Table:
    def __init__(self, kind: str) -> None:
        self.kind = kind
        self.objects: dict[str, Any] = {}
        # index name -> key -> set of ids
        self.indexes: dict[str, dict[str, set[str]]] = {}

    def _index_entries(self, obj) -> dict[str, list[str]]:
        entries = {"name": [_name_of(obj)] if _name_of(obj) else []}
        extra = _EXTRA_INDEXES.get(self.kind)
        if extra:
            entries.update(extra(obj))
        return entries

    def _index_add(self, obj) -> None:
        for idx, keys in self._index_entries(obj).items():
            table = self.indexes.setdefault(idx, {})
            for k in keys:
                table.setdefault(k, set()).add(obj.id)

    def _index_remove(self, obj) -> None:
        for idx, keys in self._index_entries(obj).items():
            table = self.indexes.get(idx, {})
            for k in keys:
                ids = table.get(k)
                if ids:
                    ids.discard(obj.id)
                    if not ids:
                        del table[k]

    def put(self, obj) -> None:
        old = self.objects.get(obj.id)
        if old is not None:
            self._index_remove(old)
        self.objects[obj.id] = obj
        self._index_add(obj)

    def remove(self, id: str) -> None:
        old = self.objects.pop(id, None)
        if old is not None:
            self._index_remove(old)

    def lookup(self, index: str, key: str) -> set[str]:
        return self.indexes.get(index, {}).get(key, set())

    def name_owner(self, name: str) -> Optional[str]:
        ids = self.lookup("name", name)
        return next(iter(ids)) if ids else None


# --------------------------------------------------------------------------
# proposer seam

class Proposer:
    """reference: manager/state/state.go Proposer interface."""

    async def propose_value(self, actions: list[StoreAction],
                            apply_cb: Callable[[int], None]) -> None:
        """Replicate ``actions``; call ``apply_cb(applied_index)`` exactly at
        the point the entry commits locally, then return."""
        raise NotImplementedError

    def get_version(self) -> int:
        raise NotImplementedError

    def changes_between(self, frm: int, to: int) -> list[tuple[int, list[StoreAction]]]:
        raise NotImplementedError


class NopProposer(Proposer):
    """Local-only versioning (reference: mock_proposer.go)."""

    def __init__(self) -> None:
        self._version = 0
        self.proposed: list[list[StoreAction]] = []

    async def propose_value(self, actions, apply_cb) -> None:
        self._version += 1
        self.proposed.append(actions)
        apply_cb(self._version)

    def get_version(self) -> int:
        return self._version

    def changes_between(self, frm, to):
        return []


# --------------------------------------------------------------------------
# transactions

_REMOVED = object()


class ReadTx:
    def __init__(self, store: "MemoryStore") -> None:
        self._store = store

    def get(self, kind: str, id: str):
        obj = self._store._tables[kind].objects.get(id)
        return obj.copy() if obj is not None else None

    def find(self, kind: str, by=by_mod.All()) -> list:
        ids = self._store._resolve(kind, by)
        table = self._store._tables[kind].objects
        return [table[i].copy() for i in sorted(ids) if i in table]


class Tx(ReadTx):
    """Write transaction: buffered overlay + changelist."""

    def __init__(self, store: "MemoryStore") -> None:
        super().__init__(store)
        self._overlay: dict[tuple[str, str], Any] = {}
        self.changelist: list[Event] = []
        self._now = store._now()

    # -- reads see uncommitted writes ----------------------------------
    def get(self, kind: str, id: str):
        ov = self._overlay.get((kind, id))
        if ov is _REMOVED:
            return None
        if ov is not None:
            return ov.copy()
        return super().get(kind, id)

    def find(self, kind: str, by=by_mod.All()) -> list:
        base_ids = set(self._store._resolve(kind, by))
        out = {}
        table = self._store._tables[kind].objects
        for i in base_ids:
            if (kind, i) not in self._overlay and i in table:
                out[i] = table[i].copy()
        for (k, i), obj in self._overlay.items():
            if k != kind or obj is _REMOVED:
                continue
            if _match_object(by, kind, obj):
                out[i] = obj.copy()
        return [out[i] for i in sorted(out)]

    # -- writes ---------------------------------------------------------
    def _lookup_current(self, kind: str, id: str):
        ov = self._overlay.get((kind, id))
        if ov is _REMOVED:
            return None
        if ov is not None:
            return ov
        return self._store._tables[kind].objects.get(id)

    def _check_name(self, kind: str, obj) -> None:
        if kind not in _UNIQUE_NAME_KINDS:
            return
        name = _name_of(obj)
        if not name:
            return
        owner = self._store._tables[kind].name_owner(name)
        if owner is not None and owner != obj.id \
                and self._overlay.get((kind, owner)) is not _REMOVED:
            raise ErrNameConflict(f"name {name!r} is in use by {kind} {owner}")
        for (k, i), other in self._overlay.items():
            if k == kind and i != obj.id and other is not _REMOVED \
                    and _name_of(other) == name:
                raise ErrNameConflict(f"name {name!r} is in use by {kind} {i}")

    def create(self, obj) -> None:
        kind = kind_of(obj)
        if self._lookup_current(kind, obj.id) is not None:
            raise ErrExist(f"{kind} {obj.id} already exists")
        self._check_name(kind, obj)
        obj = obj.copy()
        obj.meta.created_at = obj.meta.updated_at = self._now
        self._overlay[(kind, obj.id)] = obj
        self.changelist.append(Event("create", kind, obj))

    def update(self, obj) -> None:
        kind = kind_of(obj)
        current = self._lookup_current(kind, obj.id)
        if current is None:
            raise ErrNotExist(f"{kind} {obj.id} does not exist")
        # reference memory.go:582-585 sequence conflict check
        if obj.meta.version.index != current.meta.version.index:
            raise ErrSequenceConflict(
                f"{kind} {obj.id}: update at version "
                f"{obj.meta.version.index}, stored {current.meta.version.index}")
        self._check_name(kind, obj)
        obj = obj.copy()
        obj.meta.created_at = current.meta.created_at
        obj.meta.updated_at = self._now
        old = current.copy()
        self._overlay[(kind, obj.id)] = obj
        self.changelist.append(Event("update", kind, obj, old))

    def delete(self, kind: str, id: str) -> None:
        current = self._lookup_current(kind, id)
        if current is None:
            raise ErrNotExist(f"{kind} {id} does not exist")
        self._overlay[(kind, id)] = _REMOVED
        self.changelist.append(Event("remove", kind, current.copy()))


def _match_object(by, kind: str, obj) -> bool:
    """Evaluate a By directly against an object (overlay reads)."""
    if isinstance(by, by_mod.All):
        return True
    if isinstance(by, by_mod.Or):
        return any(_match_object(b, kind, obj) for b in by.bys)
    if isinstance(by, by_mod.ByID):
        return obj.id == by.id
    if isinstance(by, by_mod.ByIDPrefix):
        return obj.id.startswith(by.prefix)
    if isinstance(by, by_mod.ByName):
        return _name_of(obj) == by.name
    if isinstance(by, by_mod.ByNamePrefix):
        return _name_of(obj).startswith(by.prefix)
    if isinstance(by, by_mod.Custom):
        return by.predicate(obj)
    extra = _EXTRA_INDEXES.get(kind)
    entries = extra(obj) if extra else {}
    if isinstance(by, by_mod.ByService):
        return by.service_id in entries.get("service", [])
    if isinstance(by, by_mod.ByNode):
        return by.node_id in entries.get("node", [])
    if isinstance(by, by_mod.BySlot):
        return f"{by.service_id}:{by.slot}" in entries.get("slot", [])
    if isinstance(by, by_mod.ByDesiredState):
        return str(int(by.state)) in entries.get("desired_state", [])
    if isinstance(by, by_mod.ByTaskState):
        return str(int(by.state)) in entries.get("task_state", [])
    if isinstance(by, by_mod.ByRole):
        return str(int(by.role)) in entries.get("role", [])
    if isinstance(by, by_mod.ByMembership):
        return str(int(by.membership)) in entries.get("membership", [])
    if isinstance(by, by_mod.ByReferencedSecret):
        return by.secret_id in entries.get("secret_ref", [])
    if isinstance(by, by_mod.ByReferencedConfig):
        return by.config_id in entries.get("config_ref", [])
    raise ErrInvalidFindBy(f"unsupported By {type(by).__name__} for {kind}")


# --------------------------------------------------------------------------
# the store

class MemoryStore:
    # reference: WedgeTimeout memory.go:79 (30s there). Here it must sit
    # BELOW the default proposal timeout (node.py propose_value timeout=30):
    # the stuck write is popped from _in_flight when its proposal times out,
    # so the watchdog can only observe the stall while the await is pending.
    WEDGE_TIMEOUT = 15.0

    def __init__(self, proposer: Optional[Proposer] = None,
                 clock: Optional[Callable[[], float]] = None,
                 metrics_registry=None, obs=None, coalesce=None) -> None:
        self._tables: dict[str, _Table] = {k: _Table(k) for k in OBJECT_KINDS}
        self._proposer = proposer
        self._clock = clock or time.time
        self.queue = Queue()
        self._local_version = 0
        # bumped by restore(): bulk rebuilds publish no per-object events,
        # so incremental consumers (metrics collector) resync when they
        # see the generation move
        self.restore_generation = 0
        self._in_flight: dict[int, float] = {}  # update id -> start time
        self._in_flight_seq = 0
        # Serializes write transactions ACROSS the proposal round-trip
        # (reference: memstore's updateLock is held through proposeValue —
        # the very lock timedMutex/Wedged() watches).  Without it, a txn
        # whose callback read state at version v can commit after a
        # concurrent writer's v+1 and silently resurrect fields its stale
        # full-object copy carried (observed: a dispatcher status write
        # undoing a just-committed node demotion).
        self._write_lock = asyncio.Lock()
        self.metrics = metrics_registry or metrics.REGISTRY
        self.obs = obs or obs_registry.DEFAULT
        self._m_commits = obs_catalog.get(self.obs,
                                          "swarm_store_commits_total")
        # Coalescing proposal pipeline (store/pipeline.py): None = the
        # sequential one-round-trip-per-write path.
        self._pipeline = None
        if coalesce is not None:
            self.set_coalescing(coalesce)

    # -- coalescing mode -------------------------------------------------
    def set_coalescing(self, config) -> None:
        """Enable the batched proposal pipeline (store/pipeline.py).
        ``config`` is a CoalesceConfig (or True for defaults)."""
        from swarmkit_tpu.store.pipeline import CoalesceConfig, ProposalPipeline
        if config is True:
            config = CoalesceConfig()
        self._pipeline = ProposalPipeline(self, config)

    async def stop_coalescing(self) -> None:
        """Drain the pipeline and fall back to the sequential path."""
        pipeline, self._pipeline = self._pipeline, None
        if pipeline is not None:
            await pipeline.stop()

    def coalescing(self) -> bool:
        return self._pipeline is not None and self._proposer is not None

    def _timed(self, name: str):
        return metrics.timed(name, registry=self.metrics)

    async def propose_in_flight(self, actions, cb) -> None:
        """Propose with wedge bookkeeping — ALL write paths (update and
        Batch flushes) must go through here so a stalled proposal marks the
        store wedged (reference: timedMutex covers every store write)."""
        self._in_flight_seq += 1
        fid = self._in_flight_seq
        self._in_flight[fid] = self._now()
        try:
            await self._proposer.propose_value(actions, cb)
        finally:
            self._in_flight.pop(fid, None)

    def _now(self) -> float:
        return self._clock()

    def set_proposer(self, proposer: Optional[Proposer]) -> None:
        self._proposer = proposer

    # -- reads -----------------------------------------------------------
    def read_tx(self) -> ReadTx:
        return ReadTx(self)

    def view(self, cb: Callable[[ReadTx], Any]) -> Any:
        with self._timed(metrics.STORE_READ_TX_LATENCY):
            self._m_commits.labels(kind="read").inc()
            return cb(ReadTx(self))

    def get(self, kind: str, id: str):
        return ReadTx(self).get(kind, id)

    def find(self, kind: str, by=by_mod.All()) -> list:
        return ReadTx(self).find(kind, by)

    def _resolve(self, kind: str, by) -> set[str]:
        t = self._tables[kind]
        if isinstance(by, by_mod.All):
            return set(t.objects.keys())
        if isinstance(by, by_mod.Or):
            out: set[str] = set()
            for b in by.bys:
                out |= self._resolve(kind, b)
            return out
        if isinstance(by, by_mod.ByID):
            return {by.id} if by.id in t.objects else set()
        if isinstance(by, by_mod.ByIDPrefix):
            return {i for i in t.objects if i.startswith(by.prefix)}
        if isinstance(by, by_mod.ByName):
            return set(t.lookup("name", by.name))
        if isinstance(by, by_mod.ByNamePrefix):
            return {i for ids in (v for k, v in t.indexes.get("name", {}).items()
                                  if k.startswith(by.prefix)) for i in ids}
        if isinstance(by, by_mod.ByService):
            return set(t.lookup("service", by.service_id))
        if isinstance(by, by_mod.ByNode):
            return set(t.lookup("node", by.node_id))
        if isinstance(by, by_mod.BySlot):
            return set(t.lookup("slot", f"{by.service_id}:{by.slot}"))
        if isinstance(by, by_mod.ByDesiredState):
            return set(t.lookup("desired_state", str(int(by.state))))
        if isinstance(by, by_mod.ByTaskState):
            return set(t.lookup("task_state", str(int(by.state))))
        if isinstance(by, by_mod.ByRole):
            return set(t.lookup("role", str(int(by.role))))
        if isinstance(by, by_mod.ByMembership):
            return set(t.lookup("membership", str(int(by.membership))))
        if isinstance(by, by_mod.ByReferencedSecret):
            return set(t.lookup("secret_ref", by.secret_id))
        if isinstance(by, by_mod.ByReferencedConfig):
            return set(t.lookup("config_ref", by.config_id))
        if isinstance(by, by_mod.Custom):
            return {i for i, o in t.objects.items() if by.predicate(o)}
        raise ErrInvalidFindBy(f"unsupported By: {type(by).__name__}")

    # -- writes ----------------------------------------------------------
    async def update(self, cb: Callable[[Tx], Any]) -> Any:
        """Run a write transaction; replicate via the proposer (if any) and
        apply + publish on commit (reference memory.go:319-377).  The write
        lock is held from callback through commit so the callback's reads
        stay valid until the txn lands.

        In coalescing mode (``set_coalescing``) the lock covers only the
        synchronous callback + enqueue; the commit is awaited OUTSIDE the
        lock so concurrent writers pack into one raft proposal.  The
        pipeline's speculative overlay (seeded into each new txn) plays
        the lock's stale-read-prevention role across the await."""
        async with self._write_lock:
            tx = Tx(self)
            if self.coalescing():
                self._pipeline.seed(tx)
            result = cb(tx)
            if not tx.changelist:
                return result
            if len(tx.changelist) > MAX_CHANGES_PER_TRANSACTION:
                raise ErrTxTooLarge(
                    f"{len(tx.changelist)} changes > "
                    f"{MAX_CHANGES_PER_TRANSACTION}")
            actions = [StoreAction.make(_ACTION_KIND[ev.action], ev.object)
                       for ev in tx.changelist]
            size = sum(len(repr(a.target)) for a in actions)
            if size > MAX_TRANSACTION_BYTES:
                raise ErrTxTooLarge(f"transaction weighs ~{size} bytes")

            if self.coalescing():
                fut = self._pipeline.submit(tx.changelist, size)
            else:
                with self._timed(metrics.STORE_WRITE_TX_LATENCY):
                    if self._proposer is not None:
                        await self.propose_in_flight(
                            actions,
                            lambda index: self._commit(tx.changelist, index))
                    else:
                        self._local_version += 1
                        self._commit(tx.changelist, self._local_version)
                self._m_commits.labels(kind="write").inc()
                return result

        # coalescing: await the packed commit OUTSIDE the write lock
        with self._timed(metrics.STORE_WRITE_TX_LATENCY):
            await fut
        self._m_commits.labels(kind="write").inc()
        return result

    def wedged(self) -> bool:
        """True when any write has been stuck in flight longer than
        WEDGE_TIMEOUT (reference: timedMutex + Wedged() memory.go:117-144,
        :972 — there it is a mutex held too long; in the asyncio build the
        analogous stall is a proposal that never commits)."""
        if not self._in_flight:
            return False
        now = self._now()
        return any(now - t0 > self.WEDGE_TIMEOUT
                   for t0 in self._in_flight.values())

    def _commit(self, changelist: list[Event], version: int) -> None:
        for ev in changelist:
            ev.object.meta.version = Version(index=version)
            table = self._tables[ev.kind]
            if ev.action == "remove":
                table.remove(ev.object.id)
            else:
                table.put(ev.object.copy())
        self._local_version = max(self._local_version, version)
        for ev in changelist:
            self.queue.publish(ev)
        self.queue.publish(EventCommit(version=version))

    def apply_store_actions(self, actions: list[StoreAction], version: int
                            ) -> None:
        """Follower/replay path (reference memory.go:278 ApplyStoreActions)."""
        changelist = []
        now = self._now()
        for a in actions:
            obj = a.object()
            if a.action == StoreActionKind.CREATE:
                obj.meta.created_at = obj.meta.updated_at = now
                changelist.append(Event("create", a.kind, obj))
            elif a.action == StoreActionKind.UPDATE:
                old = self._tables[a.kind].objects.get(obj.id)
                obj.meta.updated_at = now
                changelist.append(Event("update", a.kind, obj,
                                        old.copy() if old else None))
            elif a.action == StoreActionKind.REMOVE:
                changelist.append(Event("remove", a.kind, obj))
        self._commit(changelist, version)

    def batch(self) -> "Batch":
        return Batch(self)

    # -- watch -----------------------------------------------------------
    def watch(self, *matchers, limit: int = 0):
        return self.queue.watch(*matchers, limit=limit)

    def view_and_watch(self, cb: Callable[[ReadTx], Any], *matchers):
        """Atomic snapshot + subscription (reference memory.go:840).
        Safe because we never await between the view and the watch."""
        watcher = self.queue.watch(*matchers)
        result = cb(ReadTx(self))
        return result, watcher

    # -- snapshot --------------------------------------------------------
    def save(self) -> StoreSnapshot:
        return StoreSnapshot(objects={
            kind: [o.to_dict() for _, o in sorted(t.objects.items())]
            for kind, t in self._tables.items()})

    def restore(self, snap: StoreSnapshot, version: int = 0) -> None:
        self._tables = {k: _Table(k) for k in OBJECT_KINDS}
        for kind, objs in snap.objects.items():
            cls = OBJECT_KINDS[kind]
            for data in objs:
                self._tables[kind].put(cls.from_dict(data))
        self._local_version = max(self._local_version, version)
        self.restore_generation += 1

    @property
    def version(self) -> int:
        if self._proposer is not None:
            return self._proposer.get_version()
        return self._local_version


_ACTION_KIND = {
    "create": StoreActionKind.CREATE,
    "update": StoreActionKind.UPDATE,
    "remove": StoreActionKind.REMOVE,
}


class Batch:
    """Split many small updates into bounded transactions
    (reference memory.go:497 Batch; MaxChangesPerTransaction splitting)."""

    def __init__(self, store: MemoryStore) -> None:
        self._store = store
        self._pending: list[Event] = []
        self.applied = 0
        self._holds_lock = False
        # coalescing mode: commit futures of entries already enqueued on
        # the pipeline (each callback becomes one FIFO entry, packed with
        # every other concurrent writer into one raft proposal)
        self._futures: list[tuple[asyncio.Future, int]] = []

    async def _acquire_segment(self) -> None:
        # The write lock is held from a segment's FIRST callback until that
        # segment flushes (reference: Batch keeps the store's updateLock
        # across each MaxChangesPerTransaction sub-batch), so no foreign
        # commit can invalidate what the callbacks read.
        if not self._holds_lock:
            await self._store._write_lock.acquire()
            self._holds_lock = True

    def _release_segment(self) -> None:
        if self._holds_lock:
            self._holds_lock = False
            self._store._write_lock.release()

    async def update(self, cb: Callable[[Tx], Any]) -> Any:
        if self._store.coalescing():
            return await self._update_coalescing(cb)
        await self._acquire_segment()
        try:
            tx = Tx(self._store)
            # seed overlay with pending (batched txs see each other's writes)
            for ev in self._pending:
                key = (ev.kind, ev.object.id)
                tx._overlay[key] = (_REMOVED if ev.action == "remove"
                                    else ev.object)
            base = len(tx.changelist)
            result = cb(tx)
            self._pending.extend(tx.changelist[base:])
        except BaseException:
            # A failed callback must not leave the store-wide lock held by
            # an abandoned batch (most call sites don't commit() in a
            # finally).  Earlier callbacks' changes are complete txns, so
            # flush them — which also releases the lock — then re-raise
            # the CALLBACK's exception; callers that catch per-callback
            # errors and continue (dispatcher, scheduler) must see the
            # error type they expect, so a flush failure here is logged,
            # never allowed to replace it.
            try:
                while self._pending:
                    await self._flush()
            except Exception:
                log.exception("batch flush failed while unwinding a "
                              "callback error")
                self._pending.clear()
            finally:
                self._release_segment()
            raise
        if len(self._pending) >= MAX_CHANGES_PER_TRANSACTION:
            await self._flush()
        return result

    async def _update_coalescing(self, cb: Callable[[Tx], Any]) -> Any:
        """Coalescing-mode callback: enqueue this callback's changes as
        pipeline entries (visible to every later txn via the speculative
        overlay — no segment lock held across awaits) and remember the
        commit futures for ``commit()``."""
        store = self._store
        async with store._write_lock:
            tx = Tx(store)
            store._pipeline.seed(tx)
            result = cb(tx)
            events = tx.changelist
            # split oversized callbacks at the same per-txn boundary the
            # sequential path uses
            for i in range(0, len(events), MAX_CHANGES_PER_TRANSACTION):
                chunk = events[i:i + MAX_CHANGES_PER_TRANSACTION]
                size = sum(len(repr(StoreAction.make(
                    _ACTION_KIND[ev.action], ev.object).target))
                    for ev in chunk)
                if size > MAX_TRANSACTION_BYTES:
                    raise ErrTxTooLarge(f"transaction weighs ~{size} bytes")
                self._futures.append(
                    (store._pipeline.submit(chunk, size), len(chunk)))
        return result

    async def _flush(self) -> None:
        try:
            if self._pending:
                await self._acquire_segment()  # no-op when already held
                with self._store._timed(metrics.STORE_BATCH_LATENCY):
                    await self._flush_timed()
        except BaseException:
            self._release_segment()
            raise
        # Keep the lock while changes built under it are still queued
        # (one callback can add >1 chunk); release only once drained, or
        # foreign commits could interleave with the stale remainder.
        if not self._pending:
            self._release_segment()

    async def _flush_timed(self) -> None:
        chunk, self._pending = (
            self._pending[:MAX_CHANGES_PER_TRANSACTION],
            self._pending[MAX_CHANGES_PER_TRANSACTION:])
        store = self._store
        actions = [StoreAction.make(_ACTION_KIND[ev.action], ev.object)
                   for ev in chunk]
        if store._proposer is not None:
            await store.propose_in_flight(
                actions, lambda index: store._commit(chunk, index))
        else:
            store._local_version += 1
            store._commit(chunk, store._local_version)
        self.applied += len(chunk)

    async def commit(self) -> int:
        if self._futures:
            # coalescing mode: wait for every enqueued entry; surface the
            # first failure (callers' retry paths handle it) after all
            # settled so no future is left un-awaited
            futures, self._futures = self._futures, []
            results = await asyncio.gather(
                *(f for f, _ in futures), return_exceptions=True)
            first_err = None
            for (_, n), res in zip(futures, results):
                if isinstance(res, BaseException):
                    first_err = first_err or res
                else:
                    self.applied += n
            self._store._m_commits.labels(kind="batch").inc()
            if first_err is not None:
                raise first_err
            return self.applied
        try:
            while self._pending:
                await self._flush()
        finally:
            self._release_segment()
        self._store._m_commits.labels(kind="batch").inc()
        return self.applied

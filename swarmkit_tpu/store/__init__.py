from swarmkit_tpu.store.errors import (
    StoreError, ErrExist, ErrNotExist, ErrNameConflict, ErrSequenceConflict,
    ErrInvalidFindBy, ErrTxTooLarge,
)
from swarmkit_tpu.store.by import (
    All, ByID, ByIDPrefix, ByName, ByNamePrefix, ByService, ByNode, BySlot,
    ByDesiredState, ByTaskState, ByRole, ByMembership, ByReferencedSecret,
    ByReferencedConfig, Or, Custom,
)
from swarmkit_tpu.store.memory import (
    MemoryStore, Event, EventCommit, Proposer, NopProposer, Batch,
    MAX_CHANGES_PER_TRANSACTION, MAX_TRANSACTION_BYTES,
)

__all__ = [
    "StoreError", "ErrExist", "ErrNotExist", "ErrNameConflict",
    "ErrSequenceConflict", "ErrInvalidFindBy", "ErrTxTooLarge",
    "All", "ByID", "ByIDPrefix", "ByName", "ByNamePrefix", "ByService",
    "ByNode", "BySlot", "ByDesiredState", "ByTaskState", "ByRole",
    "ByMembership", "ByReferencedSecret", "ByReferencedConfig", "Or", "Custom",
    "MemoryStore", "Event", "EventCommit", "Proposer", "NopProposer", "Batch",
    "MAX_CHANGES_PER_TRANSACTION", "MAX_TRANSACTION_BYTES",
]

"""Store error types (reference: manager/state/store/memory.go:51-77)."""


class StoreError(Exception):
    pass


class ErrExist(StoreError):
    pass


class ErrNotExist(StoreError):
    pass


class ErrNameConflict(StoreError):
    pass


class ErrSequenceConflict(StoreError):
    """Update out of sequence: object version does not match stored version."""


class ErrInvalidFindBy(StoreError):
    pass


class ErrTxTooLarge(StoreError):
    """Transaction exceeds MAX_CHANGES_PER_TRANSACTION / MAX_TRANSACTION_BYTES."""

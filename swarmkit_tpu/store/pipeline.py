"""Coalescing proposal pipeline: many store txns per raft round.

The sequential write path (memory.py ``update``) holds the store write
lock from the txn callback through the raft commit, so end-to-end
throughput is capped at one consensus round-trip per store write
(swarm-bench: ~117 proposals/s at p50 7.5 ms on the 3-manager config).
This module adds the classic batching/pipelining lever (arXiv:1905.10786
§4; Multi-Paxos batching in arXiv:2004.05074): concurrent ``update``
calls — and every callback of an explicit ``store.batch()`` block — are
enqueued as FIFO entries, packed into ONE concatenated-actions
``InternalRaftRequest`` (no wire change: the follower's
``apply_store_actions`` already iterates an action list), and committed
by one fused dense-propose device tick.  Per-caller futures resolve when
the entry commits.

Correctness model:

- **FIFO apply order.** Entries are enqueued under the store write lock
  in callback-execution order and applied by ``_commit`` in exactly that
  order; chunks flush serially.
- **Speculative reads.** While entries are queued or in flight, new txn
  callbacks read THROUGH them (``seed`` overlays the pending events onto
  the txn), so a later txn composes on the earlier one instead of
  resurrecting pre-batch state — the same stale-read hazard the
  sequential path's long-held lock prevents.
- **Provisional versions.** Enqueued objects get a provisional
  ``meta.version`` stamp strictly above the committed version, so a
  writer holding a stale pre-batch copy still fails the
  ``ErrSequenceConflict`` check exactly as it would against a committed
  newer version.  ``_commit`` overwrites the stamp with the real raft
  index; a caller that cached a provisional version across the commit
  sees a spurious (safe) conflict and retries.
- **Never double-apply.** Local application happens ONLY inside the
  proposal's commit callback.  If the proposal errors after the entry
  nonetheless commits (timeout race), the raft node's replay path
  (``_wait.trigger`` returning False) applies it — identical to the
  sequential path's semantics; the caller's retry observes the result
  (e.g. create → ErrExist).
- **Unwinding.** On proposal failure (``ErrLostLeadership`` et al.) ALL
  queued entries fail with the same error — their speculative base is
  gone — the overlay is cleared and the epoch bumped; callers re-propose
  via their existing retry paths.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field as dc_field
from typing import TYPE_CHECKING, Optional

from swarmkit_tpu.metrics import catalog as obs_catalog

if TYPE_CHECKING:  # pragma: no cover
    from swarmkit_tpu.store.memory import Event, MemoryStore

log = logging.getLogger("swarmkit_tpu.store.cpl")

# Locked two-way to the catalog by metrics_lint check #12.
METRIC_NAMES: dict[str, tuple[str, ...]] = {
    "swarm_cpl_proposals_total": ("outcome",),
    "swarm_cpl_txns_total": ("outcome",),
    "swarm_cpl_batch_entries": (),
    "swarm_cpl_queue_depth": (),
}
SAMPLE_LABELS: dict[str, str] = {"outcome": "committed"}


@dataclass
class CoalesceConfig:
    """Knobs for the coalescing window.

    ``window`` seconds of gathering after the first enqueue before a
    flush (0 = one event-loop pass, which already packs every
    concurrently-submitted txn); ``max_entries`` txns per proposal;
    ``max_bytes`` approximate payload budget per proposal (same
    ``repr``-size heuristic as the per-txn cap, kept at the raft
    ``max_proposal_bytes`` so a packed request never trips
    ``ErrProposalTooLarge``)."""

    window: float = 0.0
    max_entries: int = 256
    max_bytes: float = 1.5 * 1024 * 1024


@dataclass
class _Entry:
    events: list         # the txn's changelist, FIFO
    size: int            # repr-size of the encoded actions
    future: asyncio.Future = dc_field(repr=False, default=None)


class ProposalPipeline:
    """FIFO coalescer in front of ``MemoryStore.propose_in_flight``."""

    def __init__(self, store: "MemoryStore",
                 config: Optional[CoalesceConfig] = None) -> None:
        self._store = store
        self.config = config or CoalesceConfig()
        self._pending: list[_Entry] = []
        self._inflight: list = []      # events of the chunk being proposed
        self._task: Optional[asyncio.Task] = None
        self.epoch = 0                 # bumped on every fail-all unwind
        obs = store.obs
        self._m_proposals = obs_catalog.get(obs, "swarm_cpl_proposals_total")
        self._m_txns = obs_catalog.get(obs, "swarm_cpl_txns_total")
        self._m_entries = obs_catalog.get(obs, "swarm_cpl_batch_entries")
        self._m_depth = obs_catalog.get(obs, "swarm_cpl_queue_depth")

    # -- txn-side API (called under the store write lock) ---------------
    def seed(self, tx) -> None:
        """Overlay in-flight + queued speculative writes onto a new txn,
        FIFO, so its reads compose on the pipeline's tail state."""
        from swarmkit_tpu.store.memory import _REMOVED

        for ev in self._speculative_events():
            tx._overlay[(ev.kind, ev.object.id)] = (
                _REMOVED if ev.action == "remove" else ev.object)

    def _speculative_events(self):
        yield from self._inflight
        for entry in self._pending:
            yield from entry.events

    def _provisional_base(self) -> int:
        base = self._store._local_version
        for ev in self._speculative_events():
            if ev.action != "remove":
                base = max(base, ev.object.meta.version.index)
        return base

    def submit(self, events: list, size: int) -> asyncio.Future:
        """Enqueue a txn's changelist; returns the commit future.  Must
        be called with no intervening await after the txn callback ran
        (single-threaded asyncio keeps the read snapshot valid)."""
        from swarmkit_tpu.api.types import Version

        stamp = self._provisional_base() + 1
        for ev in events:
            if ev.action != "remove":
                ev.object.meta.version = Version(index=stamp)
        entry = _Entry(events=events, size=size,
                       future=asyncio.get_running_loop().create_future())
        self._pending.append(entry)
        self._m_depth.set(len(self._pending))
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="store-cpl-flusher")
        return entry.future

    # -- flusher --------------------------------------------------------
    async def _run(self) -> None:
        try:
            while self._pending:
                if self.config.window > 0 \
                        and len(self._pending) < self.config.max_entries:
                    await asyncio.sleep(self.config.window)
                else:
                    # one event-loop pass: every already-runnable caller
                    # enqueues before we wake
                    await asyncio.sleep(0)
                while self._pending:
                    await self._flush_chunk()
        except asyncio.CancelledError:  # store shutdown
            self._fail_all(asyncio.CancelledError("pipeline stopped"))
            raise
        except Exception:
            log.exception("proposal pipeline flusher died")
            self._fail_all(RuntimeError("proposal pipeline flusher died"))

    def _take_chunk(self) -> list[_Entry]:
        cfg, chunk, size = self.config, [], 0
        while self._pending and len(chunk) < cfg.max_entries:
            nxt = self._pending[0]
            if chunk and size + nxt.size > cfg.max_bytes:
                break
            chunk.append(self._pending.pop(0))
            size += nxt.size
        return chunk

    async def _flush_chunk(self) -> None:
        from swarmkit_tpu.api.raft_msgs import StoreAction
        from swarmkit_tpu.store.memory import _ACTION_KIND

        chunk = self._take_chunk()
        if not chunk:
            return
        events = [ev for e in chunk for ev in e.events]
        actions = [StoreAction.make(_ACTION_KIND[ev.action], ev.object)
                   for ev in events]
        self._inflight = events
        self._m_depth.set(len(self._pending))
        store = self._store

        def on_commit(index: int) -> None:
            store._commit(events, index)

        try:
            await store.propose_in_flight(actions, on_commit)
        except BaseException as err:
            self._inflight = []
            for e in chunk:
                if not e.future.done():
                    e.future.set_exception(err)
                self._m_txns.labels(outcome="failed").inc()
            self._m_proposals.labels(outcome="failed").inc()
            self._fail_all(err)
            return
        self._inflight = []
        self._m_proposals.labels(outcome="committed").inc()
        self._m_entries.observe(len(chunk))
        for e in chunk:
            if not e.future.done():
                e.future.set_result(None)
            self._m_txns.labels(outcome="committed").inc()

    def _fail_all(self, err: BaseException) -> None:
        """Queued entries composed on a base that just failed — fail them
        all; callers re-propose through their normal retry paths."""
        self.epoch += 1
        pending, self._pending = self._pending, []
        self._inflight = []
        for e in pending:
            if not e.future.done():
                e.future.set_exception(err)
            self._m_txns.labels(outcome="failed").inc()
        self._m_depth.set(0)

    # -- lifecycle ------------------------------------------------------
    async def drain(self) -> None:
        """Wait for everything queued right now to commit or fail."""
        futs = [e.future for e in self._pending]
        if futs:
            await asyncio.gather(*futs, return_exceptions=True)

    async def stop(self) -> None:
        await self.drain()
        task, self._task = self._task, None
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

"""Device-mesh raft transport: message exchange through sharded mailbox
arrays (Transport impl #3 from SURVEY.md §2.7).

Behavioral reference: manager/state/raft/transport/transport.go:26-45,125 —
the ``Transport`` seam with non-blocking ``Send``, bounded per-peer queues
(drop on full, peer.go:82-89), unreachable/snapshot status reporting, and
per-peer activity tracking. The reference moves messages over per-peer gRPC
streams; this implementation moves them through a device-resident mailbox:

- ``Send`` serializes the message (swarmkit_tpu.raft.wire) and packs it into
  a bounded per-edge slot of a [senders, receivers, K, W] uint32 mailbox.
- Delivery is one jitted exchange program over a `jax.sharding.Mesh` along
  the node-row axis: input sharded by SENDER row, output sharded by RECEIVER
  row, so the sender->receiver transpose lowers to an XLA all-to-all across
  the mesh (asserted by tests/test_device_transport.py's HLO check). Drop /
  partition / crash faults are boolean masks applied on device.
- Delivered payloads are decoded back into Message objects and stepped into
  the receiving node, mirroring ProcessRaftMessage (raft.go:1397).

Mailbox shapes are bucketed (K in 4/16/64 slots, W in 64..65536 words) so
the exchange compiles a handful of times total; a message wider than the
largest bucket (256 KiB) is undeliverable and reported unreachable — the
analog of the reference's 4 MiB gRPC cap (peer.go:24).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

import numpy as np

from swarmkit_tpu.metrics import catalog as obs_catalog
from swarmkit_tpu.metrics import registry as obs_registry
from swarmkit_tpu.parallel import MANAGER_AXIS, row_mesh
from swarmkit_tpu.raft.messages import Message, MsgType
from swarmkit_tpu.raft.transport import Network, PeerRemoved, RaftHandlers
from swarmkit_tpu.raft.wire import decode_message, encode_message

log = logging.getLogger("swarmkit_tpu.transport.device_mesh")

K_BUCKETS = (4, 16, 64)          # mailbox depth (messages per edge per flush)
W_BUCKETS = (64, 1024, 16384, 65536)  # uint32 words per message slot


def _bucket(buckets, need):
    for b in buckets:
        if need <= b:
            return b
    return None


class DeviceMeshNet(Network):
    """Shared device mailbox wire for a cluster of DeviceMeshTransports.

    Extends the in-process Network (same fault-injection and registration
    API, so test harnesses drive partitions/drops identically); raft
    messages go through the device exchange instead of per-peer queues.
    """

    wire_name = "device"

    def __init__(self, seed: int = 0, rows: int = 8, mesh=None,
                 obs: Optional[obs_registry.MetricsRegistry] = None) -> None:
        super().__init__(seed=seed)
        self.rows = rows
        self._mesh = mesh  # built lazily so tests control jax init order
        self._row_of: dict[str, int] = {}
        # (frm_row, to_row) -> list of (raw, msg, transport, to_raft_id,
        #                               frm_addr, to_addr, ready_at)
        # ready_at: clock time before which an injected delay holds the
        # message back from the exchange (0.0 = deliver on next flush)
        self._staged: dict[tuple[int, int], list] = {}
        self._event: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._delay_task: Optional[asyncio.Task] = None
        self._exchange_cache: dict = {}
        self.device_flushes = 0
        self.device_messages = 0
        # Optional flightrec/clock.py ClockSync: every device exchange is
        # a host<->device boundary, so each flush records one sync point
        # on the (device_flushes, host_ns) axes — this wire has no sim
        # tick, the flush counter is its monotone device-time analog.
        self.clock_sync = None
        self.obs = obs or obs_registry.DEFAULT
        obs_catalog.get(self.obs, "swarm_transport_mailbox_depth") \
            .set_function(lambda: float(
                sum(len(q) for q in self._staged.values())))
        self._m_flushes = obs_catalog.get(
            self.obs, "swarm_transport_device_flushes_total")
        self._m_messages = obs_catalog.get(
            self.obs, "swarm_transport_device_messages_total")
        self._m_exchange = obs_catalog.get(
            self.obs, "swarm_transport_exchange_seconds")

    # -- rows --------------------------------------------------------------
    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = row_mesh(self.rows)
        return self._mesh

    def row_for(self, addr: str) -> int:
        r = self._row_of.get(addr)
        if r is None:
            if len(self._row_of) >= self.rows:
                # Reclaim rows of addresses that are gone from the wire
                # (membership churn must not exhaust the mailbox).
                for gone in [a for a in self._row_of
                             if a not in self._servers and a != addr]:
                    free = self._row_of.pop(gone)
                    self._row_of[addr] = free
                    return free
                raise RuntimeError(
                    f"device mesh rows exhausted ({self.rows}); "
                    "grow `rows` for larger clusters")
            r = len(self._row_of)
            self._row_of[addr] = r
        return r

    # -- staging (called from DeviceMeshTransport.send) --------------------
    def stage(self, tr: "DeviceMeshTransport", to_raft_id: int, to_addr: str,
              m: Message) -> bool:
        try:
            frm, to = self.row_for(tr.local_addr), self.row_for(to_addr)
        except RuntimeError:
            return False  # no row available: drop; send() reports status
        q = self._staged.setdefault((frm, to), [])
        if len(q) >= K_BUCKETS[-1]:
            return False  # mailbox full: drop (reference peer.go:82-89)
        delay = self.delay_for(tr.local_addr, to_addr)
        ready_at = (tr.clock.now() or 0.0) + delay if delay > 0 else 0.0
        q.append((encode_message(m), m, tr, to_raft_id, tr.local_addr,
                  to_addr, ready_at))
        self._ensure_pump()
        self._event.set()
        return True

    def _ensure_pump(self) -> None:
        if self._task is None or self._task.done():
            self._event = asyncio.Event()
            self._task = asyncio.get_running_loop().create_task(self._pump())

    async def _pump(self) -> None:
        while True:
            await self._event.wait()
            self._event.clear()
            try:
                await self._flush()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("device mailbox flush failed")

    def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._delay_task is not None:
            self._delay_task.cancel()
            self._delay_task = None

    def crash_restart(self, addr: str) -> None:
        """A process bounce at `addr`: everything staged to or from it in
        the mailbox dies with the old incarnation."""
        for key, q in list(self._staged.items()):
            q[:] = [e for e in q if addr not in (e[4], e[5])]
            if not q:
                del self._staged[key]

    def _arm_delay_wake(self, ready_at: float, clock) -> None:
        """Re-trigger a flush once the earliest held-back message matures.
        Uses the transports' (possibly fake) clock so delayed delivery is
        deterministic under test harness ticks."""
        if self._delay_task is not None and not self._delay_task.done():
            return  # the pending wake's flush re-arms for later messages

        async def wake():
            dt = ready_at - (clock.now() or 0.0)
            if dt > 0:
                await clock.sleep(dt)
            if self._event is not None:
                self._event.set()

        self._delay_task = asyncio.get_running_loop().create_task(wake())

    # -- the device exchange ----------------------------------------------
    def _exchange_fn(self, kb: int, wb: int):
        key = (kb, wb)
        fn = self._exchange_cache.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            shard = NamedSharding(self.mesh, P(MANAGER_AXIS))

            def exchange(words, lens, keep):
                # Deliver: receiver-major views of the mailbox, with
                # per-message fault masks applied on device. The axis swap
                # under sender->receiver resharding is the collective.
                lens = jnp.where(keep, lens, 0)
                return (jnp.swapaxes(words, 0, 1),
                        jnp.swapaxes(lens, 0, 1))

            fn = jax.jit(exchange, in_shardings=(shard, shard, shard),
                         out_shardings=(shard, shard))
            self._exchange_cache[key] = fn
        return fn

    async def _flush(self) -> None:
        staged, self._staged = self._staged, {}
        if not staged:
            return
        rows = self.rows
        oversize = []        # (tr, raft_id, msg): larger than any bucket
        blocked_cb = []      # (tr, raft_id, msg): masked edges -> unreachable
        packed = []          # (frm, to, _, raw, msg, tr, raft_id, to_addr,
                             #  deliverable) — slot index assigned per group
        deferred = []        # injected delay: not yet mature, re-stage
        for (frm, to), q in staged.items():
            for entry in q:
                raw, m, tr, rid, frm_addr, to_addr, ready_at = entry
                if ready_at > 0 and (tr.clock.now() or 0.0) < ready_at:
                    deferred.append(((frm, to), entry))
                    continue
                words = (len(raw) + 3) // 4
                if words > W_BUCKETS[-1]:
                    oversize.append((tr, rid, m))
                    continue
                # Fault decisions are made here (host owns topology + rng for
                # determinism) but APPLIED on device via the keep mask: every
                # message is packed into the mailbox; masked slots come back
                # with length 0 from the exchange program.
                deliverable = True
                if self._blocked(frm_addr, to_addr):
                    deliverable = False
                    blocked_cb.append((tr, rid, m))
                elif self.lossy(frm_addr, to_addr):
                    deliverable = False  # silent loss: raft retries
                    self.dropped += 1
                packed.append((frm, to, 0, raw, m, tr, rid, to_addr,
                               deliverable))

        if deferred:
            for key, entry in deferred:
                self._staged.setdefault(key, []).append(entry)
            earliest = min(e[6] for _, e in deferred)
            self._arm_delay_wake(earliest, deferred[0][1][2].clock)

        for tr, rid, m in oversize:
            tr.peer_failed(rid, m)

        # Narrow and wide messages go through SEPARATE exchanges so the
        # depth bucket of a busy edge never cross-multiplies with the width
        # bucket of a snapshot (8*8*64 slots * 64Ki words would be 1 GiB of
        # zeros for a few KB of payload).
        narrow = [e for e in packed if (len(e[3]) + 3) // 4 <= W_BUCKETS[1]]
        wide = [e for e in packed if (len(e[3]) + 3) // 4 > W_BUCKETS[1]]
        for group in (narrow, wide):
            if group:
                await self._flush_group(group)

        # Unreachable reports fire after the exchange (the reference's RPC
        # error path, peer.go:261).
        for tr, rid, m in blocked_cb:
            tr.peer_failed(rid, m)

    async def _flush_group(self, packed) -> None:
        rows = self.rows
        max_words = max((len(e[3]) + 3) // 4 for e in packed)
        # re-number slots per edge within this group
        slot_of: dict[tuple[int, int], int] = {}
        entries = []
        for frm, to, _, raw, m, tr, rid, to_addr, deliverable in packed:
            k = slot_of.get((frm, to), 0)
            slot_of[(frm, to)] = k + 1
            entries.append((frm, to, k, raw, m, tr, rid, to_addr,
                            deliverable))
        kb = _bucket(K_BUCKETS, max(k for _, _, k, *_ in entries) + 1)
        wb = _bucket(W_BUCKETS, max_words)
        words = np.zeros((rows, rows, kb, wb), np.uint32)
        lens = np.zeros((rows, rows, kb), np.int32)
        keep = np.zeros((rows, rows, kb), bool)
        for frm, to, k, raw, m, tr, rid, to_addr, deliverable in entries:
            pad = (-len(raw)) % 4
            buf = np.frombuffer(raw + b"\0" * pad, np.uint32)
            words[frm, to, k, :len(buf)] = buf
            lens[frm, to, k] = len(raw)
            keep[frm, to, k] = deliverable
        t0 = time.perf_counter()
        d_words, d_lens = self._exchange_fn(kb, wb)(words, lens, keep)
        d_words = np.asarray(d_words)
        d_lens = np.asarray(d_lens)
        self._m_exchange.observe(time.perf_counter() - t0)
        self.device_flushes += 1
        if self.clock_sync is not None:
            # np.asarray above blocked on the exchange, so "now" really
            # is when the device finished flush #device_flushes
            self.clock_sync.add(self.device_flushes)
        self.device_messages += len(entries)
        self._m_flushes.inc()
        self._m_messages.inc(len(entries))

        for frm, to, k, raw, m, tr, rid, to_addr, deliverable in entries:
            nbytes = int(d_lens[to, frm, k])
            if nbytes <= 0:
                continue  # masked out on device
            payload = d_words[to, frm, k].tobytes()[:nbytes]
            await self._deliver(tr, rid, to_addr, payload, m)

    async def _deliver(self, tr: "DeviceMeshTransport", raft_id: int,
                       to_addr: str, payload: bytes, m: Message) -> None:
        server = self._servers.get(to_addr)
        if server is None:
            tr.peer_failed(raft_id, m)
            return
        try:
            msg = decode_message(payload)
            await server.process_raft_message(msg)
            self.delivered += 1
            tr.peer_delivered(raft_id, m)
        except PeerRemoved:
            tr.handlers.node_removed()
        except Exception as e:
            from swarmkit_tpu.raft.transport import Unreachable
            if not isinstance(e, Unreachable):
                log.warning("device-mesh delivery %s -> %s failed: %r",
                            tr.local_addr, to_addr, e)
            tr.peer_failed(raft_id, m)


class DeviceMeshTransport:
    """Transport-seam implementation backed by a DeviceMeshNet.

    Same interface as swarmkit_tpu.raft.transport.Transport (the seam from
    transport.go:47): non-blocking send, add/remove/update peer, activity
    tracking, unreachable + snapshot status callbacks into RaftHandlers.
    """

    def __init__(self, network: DeviceMeshNet, handlers: RaftHandlers,
                 local_addr: str, clock) -> None:
        assert isinstance(network, DeviceMeshNet), \
            "DeviceMeshTransport requires a DeviceMeshNet wire"
        self.network = network
        self.handlers = handlers
        self.local_addr = local_addr
        self.clock = clock
        self._peers: dict[int, str] = {}
        self._active_since: dict[int, float] = {}
        self._fail_counts: dict[int, int] = {}   # consecutive failures
        self.stopped = False
        network.row_for(local_addr)

    # -- peer management ---------------------------------------------------
    def add_peer(self, raft_id: int, addr: str) -> None:
        if self._peers.get(raft_id) != addr:
            self._peers[raft_id] = addr
            self._active_since.pop(raft_id, None)

    def remove_peer(self, raft_id: int) -> None:
        self._peers.pop(raft_id, None)
        self._active_since.pop(raft_id, None)

    def update_peer(self, raft_id: int, addr: str) -> None:
        self.add_peer(raft_id, addr)

    def peer_ids(self) -> list[int]:
        return list(self._peers)

    # -- send path ---------------------------------------------------------
    def send(self, m: Message) -> None:
        """Non-blocking send (reference: Send transport.go:125)."""
        if self.stopped:
            return
        if self.handlers.is_id_removed(m.to):
            return
        addr = self._peers.get(m.to)
        if addr is None:
            self.handlers.report_unreachable(m.to)
            if m.type == MsgType.SNAP:
                self.handlers.report_snapshot(m.to, False)
            return
        if not self.network.stage(self, m.to, addr, m):
            if m.type == MsgType.SNAP:
                self.handlers.report_snapshot(m.to, False)

    # -- callbacks from the net after the device exchange ------------------
    def peer_delivered(self, raft_id: int, m: Message) -> None:
        self._fail_counts.pop(raft_id, None)
        if raft_id not in self._active_since:
            self._active_since[raft_id] = self.clock.now() or 1e-9
        if m.type == MsgType.SNAP:
            self.handlers.report_snapshot(raft_id, True)

    def peer_failed(self, raft_id: int, m: Message) -> None:
        self._active_since.pop(raft_id, None)
        failures = self._fail_counts.get(raft_id, 0) + 1
        self._fail_counts[raft_id] = failures
        if m.type == MsgType.SNAP:
            self.handlers.report_snapshot(raft_id, False)
        self.handlers.report_unreachable(raft_id, failures)

    # -- views -------------------------------------------------------------
    def longest_active(self) -> Optional[int]:
        best = None
        for rid, since in self._active_since.items():
            if since <= 0:
                continue
            if best is None or since < self._active_since[best]:
                best = rid
        return best

    def active_count(self) -> int:
        return sum(1 for s in self._active_since.values() if s > 0)

    def stop(self) -> None:
        self.stopped = True
        self._peers = {}
        self._active_since = {}
        self._fail_counts = {}

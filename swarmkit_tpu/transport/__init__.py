"""Transport implementations behind the raft Transport seam.

Impl #1 (in-process asyncio wire) lives in swarmkit_tpu.raft.transport;
impl #2 (cross-process gRPC) in swarmkit_tpu.raft.grpc_transport; impl #3
(device-mesh mailbox exchange) here.
"""

from swarmkit_tpu.transport.device_mesh import (  # noqa: F401
    DeviceMeshNet, DeviceMeshTransport,
)

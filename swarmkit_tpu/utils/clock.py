"""Injectable time source for deterministic tests.

The reference injects a fakeclock.FakeClock into the raft node
(manager/state/raft/raft.go:187-190) and pumps it from tests
(manager/state/raft/testutils/testutils.go).  We reproduce that seam for the
asyncio control plane: every component takes a ``Clock``; tests use
``FakeClock`` and call ``advance()`` to fire timers deterministically.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import time as _time
from typing import Optional


class Clock:
    """Abstract time source."""

    def now(self) -> float:
        raise NotImplementedError

    async def sleep(self, delay: float) -> None:
        raise NotImplementedError

    def ticker(self, interval: float) -> "Ticker":
        return Ticker(self, interval)


class SystemClock(Clock):
    def now(self) -> float:
        return _time.monotonic()

    async def sleep(self, delay: float) -> None:
        await asyncio.sleep(delay)


class FakeClock(Clock):
    """Deterministic clock: time moves only via ``advance()``.

    ``advance`` wakes every sleeper whose deadline has passed and yields to
    the event loop so woken tasks run before it returns.
    """

    def __init__(self, start: float = 0.0) -> None:
        self._now = start
        self._sleepers: list[tuple[float, int, asyncio.Future]] = []
        self._seq = itertools.count()

    def now(self) -> float:
        return self._now

    async def sleep(self, delay: float) -> None:
        if delay <= 0:
            await asyncio.sleep(0)
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        heapq.heappush(self._sleepers, (self._now + delay, next(self._seq), fut))
        await fut

    def sleeper_count(self) -> int:
        return len(self._sleepers)

    async def advance(self, delta: float) -> None:
        """Move time forward, firing due sleepers in deadline order."""
        target = self._now + delta
        while self._sleepers and self._sleepers[0][0] <= target:
            deadline, _, fut = heapq.heappop(self._sleepers)
            self._now = max(self._now, deadline)
            if not fut.done():
                fut.set_result(None)
            # Let the woken task (and anything it schedules) run.
            for _ in range(4):
                await asyncio.sleep(0)
        self._now = target
        for _ in range(4):
            await asyncio.sleep(0)


class Ticker:
    """Periodic timer built on a Clock; async-iterable."""

    def __init__(self, clock: Clock, interval: float) -> None:
        self._clock = clock
        self.interval = interval
        self._stopped = False

    def stop(self) -> None:
        self._stopped = True

    def __aiter__(self) -> "Ticker":
        return self

    async def __anext__(self) -> float:
        if self._stopped:
            raise StopAsyncIteration
        await self._clock.sleep(self.interval)
        if self._stopped:
            raise StopAsyncIteration
        return self._clock.now()


async def wait_for(predicate, clock: Optional[Clock] = None, timeout: float = 5.0,
                   interval: float = 0.01):
    """Poll ``predicate`` until truthy or timeout (reference: testutils/poll.go)."""
    clock = clock or SystemClock()
    deadline = clock.now() + timeout
    while True:
        val = predicate()
        if val:
            return val
        if clock.now() >= deadline:
            raise TimeoutError("condition not met within %.2fs" % timeout)
        await clock.sleep(interval)

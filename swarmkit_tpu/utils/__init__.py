from swarmkit_tpu.utils.identity import new_id
from swarmkit_tpu.utils.clock import Clock, SystemClock, FakeClock

__all__ = ["new_id", "Clock", "SystemClock", "FakeClock"]

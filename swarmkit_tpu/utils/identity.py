"""Random object identifiers.

Reference: identity/randomid.go — 128-bit random values rendered in Crockford
base32, fixed length, lowercase.
"""

import os

# Crockford base32 alphabet (lowercased), no i/l/o/u.
_ALPHABET = "0123456789abcdefghjkmnpqrstvwxyz"
_ID_BITS = 128
_ID_LEN = 25  # ceil(128/5)


def new_id() -> str:
    """Return a 25-char Crockford-base32 encoding of 128 random bits."""
    n = int.from_bytes(os.urandom(_ID_BITS // 8), "big")
    chars = []
    for _ in range(_ID_LEN):
        chars.append(_ALPHABET[n & 31])
        n >>= 5
    return "".join(reversed(chars))

"""Hot-path latency timers + a process-wide metric registry.

Reference: the prometheus timers wrapped around the exact same paths —
propose latency (manager/state/raft/raft.go:69-71,1589), snapshot save
latency (manager/state/raft/storage.go:20-29), and store
read/write/batch-transaction durations (manager/state/store/memory.go:81-110).
Metric names are kept reference-compatible so dashboards translate 1:1.

Timers keep a bounded reservoir of recent observations for percentile
queries (`swarmctl metrics` surfaces p50/p90/p99) plus exact count/sum.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

RESERVOIR = 2048

# reference-compatible metric names
RAFT_PROPOSE_LATENCY = "swarm_raft_propose_latency_seconds"
RAFT_SNAPSHOT_LATENCY = "swarm_raft_snapshot_latency_seconds"
STORE_READ_TX_LATENCY = "swarm_store_read_tx_latency_seconds"
STORE_WRITE_TX_LATENCY = "swarm_store_write_tx_latency_seconds"
STORE_BATCH_LATENCY = "swarm_store_batch_latency_seconds"


class Timer:
    __slots__ = ("name", "count", "sum", "_recent", "_i")

    def __init__(self, name: str) -> None:
        self.name = name
        self.count = 0
        self.sum = 0.0
        self._recent: list[float] = []
        self._i = 0

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.sum += seconds
        if len(self._recent) < RESERVOIR:
            self._recent.append(seconds)
        else:  # ring overwrite: keeps the newest window, O(1)
            self._recent[self._i] = seconds
            self._i = (self._i + 1) % RESERVOIR
        return None

    def percentile(self, p: float) -> float:
        """p in [0, 100] over the recent reservoir (0.0 when empty)."""
        if not self._recent:
            return 0.0
        s = sorted(self._recent)
        k = min(len(s) - 1, max(0, round(p / 100 * (len(s) - 1))))
        return s[k]

    def summary(self) -> dict:
        return {"count": self.count, "sum": round(self.sum, 6),
                "p50": self.percentile(50), "p90": self.percentile(90),
                "p99": self.percentile(99)}


class Registry:
    def __init__(self) -> None:
        self._timers: dict[str, Timer] = {}

    def timer(self, name: str) -> Timer:
        t = self._timers.get(name)
        if t is None:
            t = self._timers[name] = Timer(name)
        return t

    def snapshot(self) -> dict[str, dict]:
        return {name: t.summary() for name, t in sorted(self._timers.items())}

    def reset(self) -> None:
        self._timers.clear()


REGISTRY = Registry()


def timer(name: str) -> Timer:
    return REGISTRY.timer(name)


class timed:
    """Context manager: time a block into (registry or REGISTRY)[name]."""

    __slots__ = ("_t", "_clock", "_start")

    def __init__(self, name: str,
                 clock: Optional[Callable[[], float]] = None,
                 registry: Optional[Registry] = None) -> None:
        self._t = (registry or REGISTRY).timer(name)
        self._clock = clock or time.perf_counter
        self._start = 0.0

    def __enter__(self) -> "timed":
        self._start = self._clock()
        return self

    def __exit__(self, *exc) -> None:
        self._t.observe(self._clock() - self._start)

"""Cluster RPC services: dispatcher sessions, CA joins and control-API
forwarding over the same gRPC server the raft transport uses.

Reference: the manager's service registrations at manager/manager.go:526-548
(Dispatcher, CA/NodeCA, Control + the generated RaftProxy wrappers that
forward follower requests to the leader) and the agent's gRPC session
(api/dispatcher.proto).  With this module, swarmd's --join-addr/--join-token
work across real processes: workers join by token, open session/assignment
streams, and report statuses over sockets; control requests hitting a
follower are forwarded to the leader (the raftproxy analog).

Server side: ``add_cluster_services(net, addr, node_ref)`` queues generic
handlers on the GrpcNetwork before the raft server starts.  Client side:
``RemoteManager`` implements the Manager duck type the connection broker
needs (cached is_leader/leader_addr + remote dispatcher/CA/control).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Callable, Optional

import grpc
import msgpack

from swarmkit_tpu.api import TaskStatus, WeightedPeer
from swarmkit_tpu.api.dispatcher_msgs import (
    AssignmentsMessage, HeartbeatResponse, SessionMessage,
)
from swarmkit_tpu.api.types import NodeDescription
from swarmkit_tpu.ca.certificates import MANAGER_ROLE_OU, WORKER_ROLE_OU
from swarmkit_tpu.metrics import trace as obs_trace

log = logging.getLogger("swarmkit_tpu.rpc")

_DISP = "swarmkit.Dispatcher"
_LOGS = "swarmkit.LogBroker"
_CA = "swarmkit.CA"
_CTL = "swarmkit.Control"
_INFO = "swarmkit.Manager"
_WATCH = "swarmkit.Watch"
_RES = "swarmkit.ResourceAllocator"
HEALTH_SVC = "swarmkit.Health"

_IDENT = lambda b: b


class RpcError(Exception):
    pass


# --------------------------------------------------------------------------
# health on the wire (reference: manager/health/health.go served as the gRPC
# health-checking protocol, manager.go:526; consumed by the raft transport's
# peer probing and by `swarmctl`-style liveness checks)

def health_handlers(check: Callable[[str], int]) -> list:
    """Generic handlers serving the health Check RPC from `check(service)`,
    a callable returning a HealthStatus int (manager/health.py). The raft
    listener registers these so every manager answers health probes on the
    same port its raft service lives on."""

    async def check_rpc(request: bytes, context) -> bytes:
        service = msgpack.unpackb(request) if request else ""
        try:
            status = int(check(service))
        except Exception:          # a crashing backend reads as NOT_SERVING
            status = 2
        return msgpack.packb(status)

    return [grpc.method_handlers_generic_handler(HEALTH_SVC, {
        "Check": grpc.unary_unary_rpc_method_handler(
            check_rpc, request_deserializer=_IDENT,
            response_serializer=_IDENT)})]


async def check_health(channel: grpc.aio.Channel, service: str = "",
                       timeout: float = 2.0) -> int:
    """Client side of the health protocol: returns the HealthStatus int
    (1 = SERVING). Raises RpcError when the endpoint is unreachable."""
    call = channel.unary_unary(f"/{HEALTH_SVC}/Check",
                               request_serializer=_IDENT,
                               response_deserializer=_IDENT)
    try:
        raw = await asyncio.wait_for(call(msgpack.packb(service)),
                                     timeout=timeout)
    except (grpc.aio.AioRpcError, asyncio.TimeoutError) as e:
        raise RpcError(f"health check failed: {e!r}")
    return msgpack.unpackb(raw)


# --------------------------------------------------------------------------
# metrics on the wire: the /metrics scrape endpoint, served next to the
# health service on the manager's raft listener (the text analog of the
# reference's prometheus handler on the control socket)

METRICS_SVC = "swarmkit.Metrics"


def metrics_handlers(scrape: Callable[[], str]) -> list:
    """Generic handlers serving ``Scrape`` from `scrape()`, a callable
    returning the Prometheus text exposition (Manager.metrics_text)."""

    async def scrape_rpc(request: bytes, context) -> bytes:
        try:
            return scrape().encode()
        except Exception as e:
            await context.abort(grpc.StatusCode.INTERNAL, str(e))

    return [grpc.method_handlers_generic_handler(METRICS_SVC, {
        "Scrape": grpc.unary_unary_rpc_method_handler(
            scrape_rpc, request_deserializer=_IDENT,
            response_serializer=_IDENT)})]


async def scrape_metrics(channel: grpc.aio.Channel,
                         timeout: float = 2.0) -> str:
    """Client side: fetch a manager's metrics text over its raft listener.
    Raises RpcError when the endpoint is unreachable."""
    call = channel.unary_unary(f"/{METRICS_SVC}/Scrape",
                               request_serializer=_IDENT,
                               response_deserializer=_IDENT)
    try:
        raw = await asyncio.wait_for(call(b""), timeout=timeout)
    except (grpc.aio.AioRpcError, asyncio.TimeoutError) as e:
        raise RpcError(f"metrics scrape failed: {e!r}")
    return raw.decode()


# --------------------------------------------------------------------------
# server

class ClusterService:
    """Hosts the manager-side services for one swarmd process.

    ``node_ref()`` returns the local swarmkit_tpu.node.Node (its running
    manager may come and go with promotions).

    Authorization (reference: the authenticatedwrapper codegen +
    ca/auth.go): when the node has a SecurityConfig, each RPC checks the
    mTLS peer certificate's role OU — dispatcher RPCs admit workers and
    managers, control admits managers, certificate issuance is open (the
    join token authorizes), renewal needs any valid certificate.
    """

    def __init__(self, node_ref: Callable[[], Any]) -> None:
        self.node_ref = node_ref

    # -- helpers ---------------------------------------------------------
    def _manager(self):
        node = self.node_ref()
        m = node._running_manager() if node is not None else None
        if m is None:
            raise RpcError("this node is not a manager")
        return m

    def _security(self):
        node = self.node_ref()
        return getattr(node, "security", None) if node is not None else None

    async def _authorize(self, context, *roles):
        """Role-gate an RPC on the peer certificate; no-op when the node
        runs without TLS identities (in-process tests)."""
        sec = self._security()
        if sec is None:
            return None
        from swarmkit_tpu.ca.auth import PermissionDenied
        from swarmkit_tpu.ca.tlsutil import authorize_peer

        try:
            return authorize_peer(context, sec, *roles)
        except PermissionDenied as e:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))

    async def _bind_identity(self, context, info, node_id: str) -> None:
        """The node_id in a dispatcher payload MUST be the authenticated
        certificate's CN — a worker cert cannot impersonate another node
        (reference: the dispatcher derives the node from the TLS identity,
        dispatcher.go nodeIDFromContext / ca.RemoteNode)."""
        if info is not None and node_id and info.node_id != node_id:
            await context.abort(
                grpc.StatusCode.PERMISSION_DENIED,
                f"certificate identity {info.node_id!r} may not act as "
                f"{node_id!r}")

    def _leader_manager(self):
        m = self._manager()
        if m.is_leader():
            return m
        raise RpcError(f"not-leader:{m.leader_addr}")

    async def _abort(self, context, e: Exception):
        msg = str(e)
        if msg.startswith("not-leader:"):
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, msg)
        await context.abort(grpc.StatusCode.UNAVAILABLE, msg)

    # -- Manager info ----------------------------------------------------
    async def info(self, request: bytes, context) -> bytes:
        node = self.node_ref()
        m = node._running_manager() if node is not None else None
        if m is None:
            return msgpack.packb((False, "", False))
        return msgpack.packb((m.is_leader(), m.leader_addr, True))

    # -- Dispatcher ------------------------------------------------------
    async def session(self, request: bytes, context):
        info = await self._authorize(context, WORKER_ROLE_OU,
                                     MANAGER_ROLE_OU)
        vals = msgpack.unpackb(request)
        # 5th tuple element (optional, newer clients): the caller's span
        # id, so the dispatcher.session span reparents across the wire
        node_id, desc_json, session_id, addr = vals[:4]
        parent_span = vals[4] if len(vals) > 4 else ""
        await self._bind_identity(context, info, node_id)
        description = (NodeDescription.decode(desc_json)
                       if desc_json else None)
        try:
            d = self._leader_manager().dispatcher
            async for msg in d.session(node_id, description,
                                       session_id=session_id, addr=addr,
                                       parent_span=parent_span):
                yield msg.encode()
        except RpcError as e:
            await self._abort(context, e)
        except Exception as e:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    async def assignments(self, request: bytes, context):
        info = await self._authorize(context, WORKER_ROLE_OU,
                                     MANAGER_ROLE_OU)
        node_id, session_id = msgpack.unpackb(request)
        await self._bind_identity(context, info, node_id)
        try:
            d = self._leader_manager().dispatcher
            async for msg in d.assignments(node_id, session_id):
                yield msg.encode()
        except RpcError as e:
            await self._abort(context, e)
        except Exception as e:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    async def heartbeat(self, request: bytes, context) -> bytes:
        info = await self._authorize(context, WORKER_ROLE_OU,
                                     MANAGER_ROLE_OU)
        node_id, session_id = msgpack.unpackb(request)
        await self._bind_identity(context, info, node_id)
        try:
            resp = await self._leader_manager().dispatcher.heartbeat(
                node_id, session_id)
            return msgpack.packb(resp.period)
        except RpcError as e:
            await self._abort(context, e)
        except Exception as e:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    async def update_task_status(self, request: bytes, context) -> bytes:
        info = await self._authorize(context, WORKER_ROLE_OU,
                                     MANAGER_ROLE_OU)
        node_id, session_id, updates = msgpack.unpackb(request)
        await self._bind_identity(context, info, node_id)
        try:
            d = self._leader_manager().dispatcher
            await d.update_task_status(
                node_id, session_id,
                [(tid, TaskStatus.decode(st)) for tid, st in updates])
            return b""
        except RpcError as e:
            await self._abort(context, e)
        except PermissionError as e:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))
        except Exception as e:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    # -- LogBroker (agent + client sides of `service logs`) --------------
    async def listen_subscriptions(self, request: bytes, context):
        info = await self._authorize(context, WORKER_ROLE_OU,
                                     MANAGER_ROLE_OU)
        node_id = msgpack.unpackb(request)
        await self._bind_identity(context, info, node_id)
        try:
            lb = self._leader_manager().logbroker
            async for m in lb.listen_subscriptions(node_id):
                yield msgpack.packb(_pack_submsg(m))
        except RpcError as e:
            await self._abort(context, e)
        except Exception as e:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    async def publish_logs(self, request: bytes, context) -> bytes:
        info = await self._authorize(context, WORKER_ROLE_OU,
                                     MANAGER_ROLE_OU)
        node_id, sub_id, msgs, close = msgpack.unpackb(request)
        await self._bind_identity(context, info, node_id)
        try:
            lb = self._leader_manager().logbroker
            await lb.publish_logs(sub_id, [_unpack_logmsg(m) for m in msgs],
                                  node_id=node_id, close=close)
            return b""
        except RpcError as e:
            await self._abort(context, e)
        except Exception as e:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    async def subscribe_logs(self, request: bytes, context):
        await self._authorize(context, MANAGER_ROLE_OU)
        sel, follow, tail = msgpack.unpackb(request)
        from swarmkit_tpu.manager.logbroker import (
            LogSelector, SubscribeLogsOptions,
        )

        selector = LogSelector(service_ids=list(sel[0]),
                               node_ids=list(sel[1]),
                               task_ids=list(sel[2]))
        try:
            lb = self._leader_manager().logbroker
            async for m in lb.subscribe_logs(
                    selector, SubscribeLogsOptions(follow=follow,
                                                   tail=tail)):
                yield msgpack.packb(_pack_logmsg(m))
        except RpcError as e:
            await self._abort(context, e)
        except Exception as e:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    # -- CA --------------------------------------------------------------
    def _ca(self):
        ca = self._leader_manager().ca_server
        if ca is None:
            raise RpcError("leader has no CA")
        return ca

    async def issue_certificate(self, request: bytes, context) -> bytes:
        csr, token, addr, requested_id = msgpack.unpackb(request)
        try:
            node_id, issued = await self._ca().issue_node_certificate(
                csr, token, addr=addr, requested_node_id=requested_id)
            return msgpack.packb((node_id, issued.cert_pem, issued.key_pem,
                                  self._ca().get_root_ca_certificate()))
        except RpcError as e:
            await self._abort(context, e)
        except Exception as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))

    async def renew_certificate(self, request: bytes, context) -> bytes:
        from swarmkit_tpu.ca.certificates import (
            MANAGER_ROLE_OU, WORKER_ROLE_OU,
        )

        # any valid cluster identity may renew — but only its own cert
        info = await self._authorize(context, WORKER_ROLE_OU,
                                     MANAGER_ROLE_OU)
        node_id, old_cert, csr = msgpack.unpackb(request)
        await self._bind_identity(context, info, node_id)
        try:
            issued = await self._ca().renew_node_certificate(
                node_id, old_cert, csr)
            return msgpack.packb((issued.cert_pem, issued.key_pem,
                                  issued.root_bundle))
        except RpcError as e:
            await self._abort(context, e)
        except Exception as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))

    # -- Control (JSON dispatch, shared with the unix socket) ------------
    async def control(self, request: bytes, context) -> bytes:
        from swarmkit_tpu.cmd.ctl import CtlError, dispatch_control
        from swarmkit_tpu.manager.controlapi import ControlError

        # remote control API is manager-only (reference: controlapi RPCs
        # carry tls_authorization roles=swarm-manager); operators use the
        # local unix socket
        await self._authorize(context, MANAGER_ROLE_OU)
        req = json.loads(request)
        # optional span_id from control_call: dispatch under a span
        # parented to the remote caller so inner spans (raft.propose)
        # nest in one cross-process trace
        parent_span = req.get("span_id", "")
        try:
            c = self._leader_manager().control_api
            with obs_trace.DEFAULT.span("control.dispatch",
                                        parent_id=parent_span or None,
                                        method=req.get("method", "")):
                result = await dispatch_control(c, req.get("method", ""),
                                                req.get("params", {}))
            return json.dumps({"result": result}).encode()
        except RpcError as e:
            await self._abort(context, e)
        except (ControlError, CtlError) as e:
            # typed errors keep their code so remote == local behavior
            return json.dumps({"error": str(e), "code": e.code}).encode()
        except Exception as e:
            return json.dumps({"error": str(e),
                               "code": "internal"}).encode()

    # -- Watch (reference: manager/watchapi/server.go served over gRPC) --
    async def watch(self, request: bytes, context):
        # watch is manager-only, like the reference's watchapi
        # tls_authorization (operators and control loops, not workers)
        await self._authorize(context, MANAGER_ROLE_OU)
        from swarmkit_tpu.manager.watchapi import WatchSelector

        selectors_raw, resume_from, include_old = msgpack.unpackb(request)
        selectors = [WatchSelector(kind=k, id_prefix=p, name=n,
                                   actions=tuple(a))
                     for k, p, n, a in selectors_raw]
        try:
            # any manager serves watches from its replicated store (the
            # reference's watchapi is not leader-only either)
            ws = self._manager().watch_server
            async for msg in ws.watch(selectors=selectors,
                                      resume_from=resume_from,
                                      include_old_object=include_old):
                yield msgpack.packb(_pack_watchmsg(msg))
        except RpcError as e:
            await self._abort(context, e)
        except Exception as e:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    # -- ResourceAllocator (reference: manager/resourceapi/allocator.go) -
    async def attach_network(self, request: bytes, context) -> bytes:
        from swarmkit_tpu.manager.resourceapi import ResourceError

        info = await self._authorize(context, WORKER_ROLE_OU,
                                     MANAGER_ROLE_OU)
        node_id, network_id, container_id = msgpack.unpackb(request)
        await self._bind_identity(context, info, node_id)
        try:
            attachment_id = await self._leader_manager() \
                .resource_api.attach_network(node_id, network_id,
                                             container_id)
            return msgpack.packb(attachment_id)
        except RpcError as e:
            await self._abort(context, e)
        except ResourceError as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except Exception as e:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    async def detach_network(self, request: bytes, context) -> bytes:
        from swarmkit_tpu.manager.resourceapi import ResourceError

        await self._authorize(context, WORKER_ROLE_OU, MANAGER_ROLE_OU)
        (attachment_id,) = msgpack.unpackb(request)
        try:
            await self._leader_manager().resource_api.detach_network(
                attachment_id)
            return b""
        except RpcError as e:
            await self._abort(context, e)
        except ResourceError as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except Exception as e:
            await context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    # -- registration ----------------------------------------------------
    def handlers(self) -> list:
        u = grpc.unary_unary_rpc_method_handler
        s = grpc.unary_stream_rpc_method_handler
        return [
            grpc.method_handlers_generic_handler(_INFO, {
                "Info": u(self.info, request_deserializer=_IDENT,
                          response_serializer=_IDENT)}),
            grpc.method_handlers_generic_handler(_DISP, {
                "Session": s(self.session, request_deserializer=_IDENT,
                             response_serializer=_IDENT),
                "Assignments": s(self.assignments,
                                 request_deserializer=_IDENT,
                                 response_serializer=_IDENT),
                "Heartbeat": u(self.heartbeat, request_deserializer=_IDENT,
                               response_serializer=_IDENT),
                "UpdateTaskStatus": u(self.update_task_status,
                                      request_deserializer=_IDENT,
                                      response_serializer=_IDENT)}),
            grpc.method_handlers_generic_handler(_CA, {
                "IssueNodeCertificate": u(self.issue_certificate,
                                          request_deserializer=_IDENT,
                                          response_serializer=_IDENT),
                "RenewNodeCertificate": u(self.renew_certificate,
                                          request_deserializer=_IDENT,
                                          response_serializer=_IDENT)}),
            grpc.method_handlers_generic_handler(_LOGS, {
                "ListenSubscriptions": s(self.listen_subscriptions,
                                         request_deserializer=_IDENT,
                                         response_serializer=_IDENT),
                "PublishLogs": u(self.publish_logs,
                                 request_deserializer=_IDENT,
                                 response_serializer=_IDENT),
                "SubscribeLogs": s(self.subscribe_logs,
                                   request_deserializer=_IDENT,
                                   response_serializer=_IDENT)}),
            grpc.method_handlers_generic_handler(_CTL, {
                "Call": u(self.control, request_deserializer=_IDENT,
                          response_serializer=_IDENT)}),
            grpc.method_handlers_generic_handler(_WATCH, {
                "Watch": s(self.watch, request_deserializer=_IDENT,
                           response_serializer=_IDENT)}),
            grpc.method_handlers_generic_handler(_RES, {
                "AttachNetwork": u(self.attach_network,
                                   request_deserializer=_IDENT,
                                   response_serializer=_IDENT),
                "DetachNetwork": u(self.detach_network,
                                   request_deserializer=_IDENT,
                                   response_serializer=_IDENT)}),
        ]

    def join_handlers(self) -> list:
        """The subset served on the TLS join port to certificate-less
        joiners: token-gated issuance + leader info for redirects."""
        u = grpc.unary_unary_rpc_method_handler
        return [
            grpc.method_handlers_generic_handler(_CA, {
                "IssueNodeCertificate": u(self.issue_certificate,
                                          request_deserializer=_IDENT,
                                          response_serializer=_IDENT)}),
            grpc.method_handlers_generic_handler(_INFO, {
                "Info": u(self.info, request_deserializer=_IDENT,
                          response_serializer=_IDENT)}),
        ]


# --------------------------------------------------------------------------
# client

def pack_session_request(node_id, description=None, session_id="",
                         addr="") -> bytes:
    """Wire form of a dispatcher session request.  The 5th element is the
    caller's current span id (or ""), carried so the server-side
    dispatcher.session span reparents under the caller's trace instead of
    rooting a fresh tree across the process boundary; pre-span servers
    that unpack only 4 values still work."""
    return msgpack.packb((node_id,
                          description.encode() if description else b"",
                          session_id, addr,
                          obs_trace.current_span_id() or ""))


def _redirectable(e: grpc.aio.AioRpcError) -> Exception:
    details = e.details() or ""
    if details.startswith("not-leader:"):
        return NotLeader(details.split(":", 1)[1])
    return RpcError(f"{e.code().name}: {details}")


class NotLeader(Exception):
    def __init__(self, leader_addr: str) -> None:
        super().__init__(f"not the leader (leader at {leader_addr})")
        self.leader_addr = leader_addr


class RemoteDispatcher:
    """Dispatcher duck type over gRPC (matches manager.dispatcher's
    surface used by agent/session.py)."""

    def __init__(self, channel: grpc.aio.Channel) -> None:
        self._session = channel.unary_stream(
            f"/{_DISP}/Session", request_serializer=_IDENT,
            response_deserializer=_IDENT)
        self._assignments = channel.unary_stream(
            f"/{_DISP}/Assignments", request_serializer=_IDENT,
            response_deserializer=_IDENT)
        self._heartbeat = channel.unary_unary(
            f"/{_DISP}/Heartbeat", request_serializer=_IDENT,
            response_deserializer=_IDENT)
        self._uts = channel.unary_unary(
            f"/{_DISP}/UpdateTaskStatus", request_serializer=_IDENT,
            response_deserializer=_IDENT)

    async def session(self, node_id, description=None, session_id="",
                      addr=""):
        req = pack_session_request(node_id, description, session_id, addr)
        try:
            async for raw in self._session(req):
                yield SessionMessage.decode(raw)
        except grpc.aio.AioRpcError as e:
            raise _redirectable(e)

    async def assignments(self, node_id, session_id):
        req = msgpack.packb((node_id, session_id))
        try:
            async for raw in self._assignments(req):
                yield AssignmentsMessage.decode(raw)
        except grpc.aio.AioRpcError as e:
            raise _redirectable(e)

    async def heartbeat(self, node_id, session_id) -> HeartbeatResponse:
        try:
            raw = await self._heartbeat(msgpack.packb((node_id, session_id)))
        except grpc.aio.AioRpcError as e:
            raise _redirectable(e)
        return HeartbeatResponse(period=msgpack.unpackb(raw))

    async def update_task_status(self, node_id, session_id, updates) -> None:
        req = msgpack.packb((node_id, session_id,
                             [(tid, st.encode()) for tid, st in updates]))
        try:
            await self._uts(req)
        except grpc.aio.AioRpcError as e:
            if e.code() == grpc.StatusCode.PERMISSION_DENIED:
                raise PermissionError(e.details())
            raise _redirectable(e)


class RemoteCA:
    """CAServer duck type over gRPC (surface used by node.py)."""

    def __init__(self, channel: grpc.aio.Channel) -> None:
        self._issue = channel.unary_unary(
            f"/{_CA}/IssueNodeCertificate", request_serializer=_IDENT,
            response_deserializer=_IDENT)
        self._renew = channel.unary_unary(
            f"/{_CA}/RenewNodeCertificate", request_serializer=_IDENT,
            response_deserializer=_IDENT)
        self._root_ca_pem: bytes = b""

    async def issue_node_certificate(self, csr_pem, token, addr="",
                                     requested_node_id=""):
        from swarmkit_tpu.ca import IssuedCertificate

        try:
            raw = await self._issue(msgpack.packb(
                (csr_pem, token, addr, requested_node_id)))
        except grpc.aio.AioRpcError as e:
            raise _redirectable(e)
        node_id, cert_pem, key_pem, root_pem = msgpack.unpackb(raw)
        self._root_ca_pem = root_pem
        # the bundle arrived over the pin-verified TLS channel, so it is
        # authenticated trust (unlike the plaintext bootstrap fetch)
        return node_id, IssuedCertificate(cert_pem=cert_pem,
                                          key_pem=key_pem,
                                          root_bundle=root_pem or b"")

    async def renew_node_certificate(self, node_id, old_cert_pem, csr_pem):
        from swarmkit_tpu.ca import IssuedCertificate

        try:
            raw = await self._renew(msgpack.packb(
                (node_id, old_cert_pem, csr_pem)))
        except grpc.aio.AioRpcError as e:
            raise _redirectable(e)
        parts = msgpack.unpackb(raw)
        cert_pem, key_pem = parts[0], parts[1]
        root_bundle = parts[2] if len(parts) > 2 else b""
        return IssuedCertificate(cert_pem=cert_pem, key_pem=key_pem,
                                 root_bundle=root_bundle or b"")

    def get_root_ca_certificate(self) -> bytes:
        return self._root_ca_pem


async def fetch_root_ca(addr: str, timeout: float = 5.0) -> bytes:
    """Fetch the cluster root CA certificate from a manager's plaintext
    BOOTSTRAP port (addr's port + 1). The returned PEM is UNTRUSTED until
    the caller verifies its digest against the join-token pin (reference:
    GetRemoteCA digest pinning, ca/certificates.go)."""
    host, port = addr.rsplit(":", 1)
    boot_addr = f"{host}:{int(port) + 1}"
    channel = grpc.aio.insecure_channel(boot_addr)
    try:
        call = channel.unary_unary(
            "/swarmkit.Bootstrap/GetRootCACertificate",
            request_serializer=_IDENT, response_deserializer=_IDENT)
        return await asyncio.wait_for(call(b""), timeout=timeout)
    finally:
        await channel.close()


def _pack_submsg(m) -> tuple:
    return (m.id, (list(m.selector.service_ids), list(m.selector.node_ids),
                   list(m.selector.task_ids)), m.close, m.options)


def _unpack_submsg(t):
    from swarmkit_tpu.manager.logbroker import (
        LogSelector, SubscriptionMessage,
    )

    sid, sel, close, options = t
    return SubscriptionMessage(
        id=sid, selector=LogSelector(service_ids=list(sel[0]),
                                     node_ids=list(sel[1]),
                                     task_ids=list(sel[2])),
        close=close, options=dict(options))


def _pack_logmsg(m) -> tuple:
    return (m.context.service_id, m.context.node_id, m.context.task_id,
            m.timestamp, int(m.stream), m.data)


def _unpack_logmsg(t):
    from swarmkit_tpu.manager.logbroker import (
        LogContext, LogMessage, LogStream,
    )

    svc, node, task, ts, stream, data = t
    return LogMessage(context=LogContext(service_id=svc, node_id=node,
                                         task_id=task),
                      timestamp=ts, stream=LogStream(stream), data=data)


def _pack_watchmsg(m) -> tuple:
    from swarmkit_tpu.api.objects import kind_of

    def enc(obj):
        return (kind_of(obj), obj.encode()) if obj is not None else ("", b"")

    return (m.action, m.kind, enc(m.object), enc(m.old_object), m.version)


def _unpack_watchmsg(t):
    from swarmkit_tpu.api.objects import OBJECT_KINDS
    from swarmkit_tpu.manager.watchapi import WatchMessage

    def dec(pair):
        kind, raw = pair
        return OBJECT_KINDS[kind].decode(raw) if kind else None

    action, kind, obj, old, version = t
    return WatchMessage(action=action, kind=kind, object=dec(obj),
                        old_object=dec(old), version=version)


class RemoteWatch:
    """WatchServer duck type over gRPC (reference: watchapi client)."""

    def __init__(self, channel: grpc.aio.Channel) -> None:
        self._watch = channel.unary_stream(
            f"/{_WATCH}/Watch", request_serializer=_IDENT,
            response_deserializer=_IDENT)

    async def watch(self, selectors=None, resume_from=None,
                    include_old_object: bool = False):
        req = msgpack.packb((
            [(s.kind, s.id_prefix, s.name, list(s.actions))
             for s in (selectors or [])],
            resume_from, include_old_object))
        try:
            async for raw in self._watch(req):
                yield _unpack_watchmsg(msgpack.unpackb(raw))
        except grpc.aio.AioRpcError as e:
            raise _redirectable(e)


class RemoteResourceAllocator:
    """ResourceApi duck type over gRPC (reference: resourceapi client used
    by the engine for network attachments)."""

    def __init__(self, channel: grpc.aio.Channel) -> None:
        self._attach = channel.unary_unary(
            f"/{_RES}/AttachNetwork", request_serializer=_IDENT,
            response_deserializer=_IDENT)
        self._detach = channel.unary_unary(
            f"/{_RES}/DetachNetwork", request_serializer=_IDENT,
            response_deserializer=_IDENT)

    async def attach_network(self, node_id: str, network_id: str,
                             container_id: str = "") -> str:
        from swarmkit_tpu.manager.resourceapi import ResourceError

        try:
            raw = await self._attach(msgpack.packb(
                (node_id, network_id, container_id)))
        except grpc.aio.AioRpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                raise ResourceError(e.details())
            raise _redirectable(e)
        return msgpack.unpackb(raw)

    async def detach_network(self, attachment_id: str) -> None:
        from swarmkit_tpu.manager.resourceapi import ResourceError

        try:
            await self._detach(msgpack.packb((attachment_id,)))
        except grpc.aio.AioRpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                raise ResourceError(e.details())
            raise _redirectable(e)


class RemoteLogBroker:
    """LogBroker duck type over gRPC (surface used by agent/logs.py and
    the control socket's subscribe-logs)."""

    def __init__(self, channel: grpc.aio.Channel) -> None:
        self._listen = channel.unary_stream(
            f"/{_LOGS}/ListenSubscriptions", request_serializer=_IDENT,
            response_deserializer=_IDENT)
        self._publish = channel.unary_unary(
            f"/{_LOGS}/PublishLogs", request_serializer=_IDENT,
            response_deserializer=_IDENT)
        self._subscribe = channel.unary_stream(
            f"/{_LOGS}/SubscribeLogs", request_serializer=_IDENT,
            response_deserializer=_IDENT)

    async def listen_subscriptions(self, node_id: str):
        try:
            async for raw in self._listen(msgpack.packb(node_id)):
                yield _unpack_submsg(msgpack.unpackb(raw))
        except grpc.aio.AioRpcError as e:
            raise _redirectable(e)

    async def publish_logs(self, sub_id: str, messages,
                           node_id: str = "", close: bool = False) -> None:
        req = msgpack.packb((node_id, sub_id,
                             [_pack_logmsg(m) for m in messages], close))
        try:
            await self._publish(req)
        except grpc.aio.AioRpcError as e:
            raise _redirectable(e)

    async def subscribe_logs(self, selector, options=None):
        from swarmkit_tpu.manager.logbroker import SubscribeLogsOptions

        options = options or SubscribeLogsOptions()
        req = msgpack.packb(((list(selector.service_ids),
                              list(selector.node_ids),
                              list(selector.task_ids)),
                             options.follow, options.tail))
        try:
            async for raw in self._subscribe(req):
                yield _unpack_logmsg(msgpack.unpackb(raw))
        except grpc.aio.AioRpcError as e:
            raise _redirectable(e)


class RemoteManager:
    """Manager duck type over gRPC for the connection broker: cached
    is_leader/leader_addr (refreshed on use) + remote services.

    Channel security (reference: manager.go client-side mTLS everywhere):
    - with a SecurityConfig (``security_ref``): mutual TLS;
    - certificate-less but holding a join token (``expected_ca_digest``):
      fetch the root CA from the bootstrap port, verify the token's digest
      pin, then server-authenticated TLS — the join dance;
    - neither: plaintext (in-process tests only).
    The channel is rebuilt when the node's security state changes (a joiner
    upgrades pinned -> mTLS once its certificate is issued).
    """

    def __init__(self, addr: str, refresh_interval: float = 1.0,
                 security_ref: Optional[Callable[[], Any]] = None,
                 expected_ca_digest: str = "") -> None:
        self.addr = addr
        self._security_ref = security_ref or (lambda: None)
        self._expected_digest = expected_ca_digest
        self._pinned_root: Optional[bytes] = None
        self._mode: Optional[str] = None
        self._channel: Optional[grpc.aio.Channel] = None
        self._info = None
        self._ctl = None
        self.dispatcher: Optional[RemoteDispatcher] = None
        self.ca_server: Optional[RemoteCA] = None
        self.logbroker: Optional[RemoteLogBroker] = None
        self.watch_server: Optional[RemoteWatch] = None
        self.resource_api: Optional[RemoteResourceAllocator] = None
        self._is_leader = False
        self._leader_addr = ""
        self._has_manager = False
        self._refresh_interval = refresh_interval
        self._last_refresh = 0.0
        self._refresher: Optional[asyncio.Task] = None
        self._running = True
        self._connect_lock: Optional[asyncio.Lock] = None
        self._last_connect_error: str = ""

    async def _connect(self) -> None:
        # refresh loop and in-flight RPCs can race channel rebuilds
        if self._connect_lock is None:
            self._connect_lock = asyncio.Lock()
        async with self._connect_lock:
            await self._connect_locked()

    async def _connect_locked(self) -> None:
        sec = self._security_ref()
        want = ("mtls" if sec is not None
                else "pinned" if self._expected_digest
                else "insecure")
        if self._channel is not None and want == self._mode:
            return
        if self._channel is not None:
            await self._channel.close()
        if want == "insecure":
            channel = grpc.aio.insecure_channel(self.addr)
        else:
            from swarmkit_tpu.ca.tlsutil import (
                channel_credentials, secure_channel_options,
            )

            if want == "pinned":
                if self._pinned_root is None:
                    import hmac

                    from swarmkit_tpu.ca.certificates import split_bundle

                    root_pem = await fetch_root_ca(self.addr)
                    # compare against the raw digest (the caller passes the
                    # SWMTKN's pin component, not the whole token).  The
                    # served trust may be an old+new BUNDLE mid-rotation —
                    # trust ONLY the member matching the pin, never the
                    # whole unauthenticated bundle.
                    pin = next(
                        (c for c, d in split_bundle(root_pem)
                         if hmac.compare_digest(d, self._expected_digest)),
                        None)
                    if pin is None:
                        raise RpcError(
                            "remote CA digest does not match the join "
                            "token pin — refusing to join (possible MITM)")
                    self._pinned_root = pin
                creds = channel_credentials(
                    pinned_root_pem=self._pinned_root)
                # certificate-less joiners talk to the TLS join port
                host, port = self.addr.rsplit(":", 1)
                target = f"{host}:{int(port) + 2}"
            else:
                creds = channel_credentials(sec)
                target = self.addr
            channel = grpc.aio.secure_channel(
                target, creds, options=secure_channel_options())
        self._channel = channel
        self._mode = want
        self._info = channel.unary_unary(
            f"/{_INFO}/Info", request_serializer=_IDENT,
            response_deserializer=_IDENT)
        self._ctl = channel.unary_unary(
            f"/{_CTL}/Call", request_serializer=_IDENT,
            response_deserializer=_IDENT)
        self.dispatcher = RemoteDispatcher(channel)
        self.ca_server = RemoteCA(channel)
        self.logbroker = RemoteLogBroker(channel)
        self.watch_server = RemoteWatch(channel)
        self.resource_api = RemoteResourceAllocator(channel)

    def start(self) -> None:
        self._refresher = asyncio.get_running_loop().create_task(
            self._refresh_loop())

    async def close(self) -> None:
        self._running = False
        if self._refresher is not None:
            self._refresher.cancel()
            try:
                await self._refresher
            except (asyncio.CancelledError, Exception):
                pass
        if self._channel is not None:
            await self._channel.close()

    async def refresh(self) -> None:
        try:
            await self._connect()
            raw = await asyncio.wait_for(self._info(b""), timeout=2.0)
            self._is_leader, self._leader_addr, self._has_manager = \
                msgpack.unpackb(raw)
            self._last_connect_error = ""
        except Exception as e:
            # A digest-pin refusal is a security event, not connection
            # noise — surface it (once per distinct message, the refresh
            # loop runs every second).
            msg = f"{type(e).__name__}: {e}"
            if msg != self._last_connect_error:
                self._last_connect_error = msg
                level = (log.error if "digest" in str(e).lower()
                         else log.debug)
                level("manager %s unavailable: %s", self.addr, msg)
            self._is_leader, self._has_manager = False, False

    async def _refresh_loop(self) -> None:
        while self._running:
            await self.refresh()
            await asyncio.sleep(self._refresh_interval)

    # Manager duck type (sync; served from the refreshed cache)
    def is_leader(self) -> bool:
        return self._is_leader

    @property
    def leader_addr(self) -> str:
        return self._leader_addr

    @property
    def _running_(self) -> bool:   # parity only
        return self._has_manager

    async def control_call(self, method: str, params: dict):
        """Raw control dispatch (same JSON protocol as the unix socket)."""
        await self._connect()
        try:
            raw = await self._ctl(json.dumps(
                {"method": method, "params": params,
                 "span_id": obs_trace.current_span_id() or ""}).encode())
        except grpc.aio.AioRpcError as e:
            raise _redirectable(e)
        resp = json.loads(raw)
        if "error" in resp:
            from swarmkit_tpu.cmd.ctl import CtlError

            raise CtlError(resp["error"], resp.get("code", "unknown"))
        return resp["result"]

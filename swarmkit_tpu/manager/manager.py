"""Manager: builds the raft node + every API service, and flips the
leader-only control loops on leadership changes.

Reference: manager/manager.go — New (:199) wires raft, store and services;
Run (:427) registers them (:526-548) and starts raft; leadership events
(handleLeadershipEvents :846) drive becomeLeader (:906: orchestrators,
scheduler, allocator, task reaper, constraint enforcer, key manager, role
manager, dispatcher; plus seeding the default cluster + own node objects
:931-983) and becomeFollower (:1088).  The dirty-state check mirrors
manager/dirty.go IsStateDirty.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import (
    Annotations, Cluster, ClusterSpec, MembershipState, Node as ApiNode,
    NodeRole, NodeSpec, Peer, WeightedPeer,
)
from swarmkit_tpu.api.objects import NodeStatus
from swarmkit_tpu.manager.allocator import Allocator
from swarmkit_tpu.manager.controlapi import ControlApi
from swarmkit_tpu.manager.dispatcher import Dispatcher
from swarmkit_tpu.manager.health import HealthServer, HealthStatus
from swarmkit_tpu.manager.keymanager import KeyManager
from swarmkit_tpu.manager.logbroker import LogBroker
from swarmkit_tpu.manager.metrics import Collector
from swarmkit_tpu.manager.orchestrator.constraintenforcer import (
    ConstraintEnforcer,
)
from swarmkit_tpu.manager.orchestrator.global_ import GlobalOrchestrator
from swarmkit_tpu.manager.orchestrator.replicated import (
    ReplicatedOrchestrator,
)
from swarmkit_tpu.manager.orchestrator.taskreaper import TaskReaper
from swarmkit_tpu.manager.resourceapi import ResourceApi
from swarmkit_tpu.manager.role_manager import RoleManager
from swarmkit_tpu.manager.scheduler import Scheduler
from swarmkit_tpu.manager.watchapi import WatchServer
from swarmkit_tpu.ca import CAServer, RootCA, generate_join_token as ca_token
from swarmkit_tpu.raft.node import LeadershipState, Node as RaftNode, NodeOpts
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import Clock, SystemClock
from swarmkit_tpu.watch.queue import watch_with_sweep

log = logging.getLogger("swarmkit_tpu.manager")

DEFAULT_CLUSTER_NAME = "default"   # reference: store.DefaultClusterName


class Manager:
    def __init__(self, node_id: str, addr: str, network, state_dir: str,
                 clock: Optional[Clock] = None, join_addr: str = "",
                 force_new_cluster: bool = False,
                 tick_interval: float = 1.0,
                 election_tick: int = 10, heartbeat_tick: int = 1,
                 seed: int = 0, security=None,
                 encrypter=None, decrypter=None,
                 transport_factory=None, obs=None,
                 coalesce=None, sched_use_kernel: bool = False,
                 sched_commit_debounce: Optional[float] = None) -> None:
        self.node_id = node_id
        self.addr = addr
        self.clock = clock or SystemClock()
        # node-provided TLS identity; its root CA seeds the cluster's CA on
        # bootstrap (reference: manager.go uses SecurityConfig's RootCA)
        self.security = security
        self.ca_server: Optional[CAServer] = None
        from swarmkit_tpu.utils.metrics import Registry
        self.metrics_registry = Registry()
        # typed observability registry: per-manager by default so multi-
        # manager test clusters don't mix counters (pass obs= to share one)
        from swarmkit_tpu.metrics import registry as obs_registry
        self.obs = obs or obs_registry.MetricsRegistry()
        self.raft = RaftNode(NodeOpts(
            metrics_registry=self.metrics_registry,
            obs_registry=self.obs,
            node_id=node_id, addr=addr, network=network,
            state_dir=state_dir, clock=self.clock, join_addr=join_addr,
            force_new_cluster=force_new_cluster,
            tick_interval=tick_interval, election_tick=election_tick,
            heartbeat_tick=heartbeat_tick, seed=seed,
            encrypter=encrypter, decrypter=decrypter,
            transport_factory=transport_factory))
        self.store: MemoryStore = self.raft.store
        # vectorized control plane knobs: batched proposal pipeline
        # (store/pipeline.py CoalesceConfig, or True for defaults) and the
        # jitted [tasks, nodes] scheduler kernel
        if coalesce is not None:
            self.store.set_coalescing(coalesce)
        self._sched_use_kernel = sched_use_kernel
        self._sched_commit_debounce = sched_commit_debounce

        # always-on services (reference: manager.go:526-548)
        self.metrics = Collector(self.store)
        self.control_api = ControlApi(self.store, raft=self.raft,
                                      on_remove_node=self._on_remove_node,
                                      metrics=self.metrics,
                                      metrics_registry=self.metrics_registry)
        from swarmkit_tpu.manager.drivers import DriverProvider
        self.drivers = DriverProvider()
        self.dispatcher = Dispatcher(
            self.store, managers_fn=self._weighted_peers, clock=self.clock,
            peers_queue=self.raft.cluster.broadcast, drivers=self.drivers,
            obs=self.obs)
        self.logbroker = LogBroker(self.store)
        self.watch_server = WatchServer(self.store, proposer=self.raft)
        self.health = HealthServer()
        self.resource_api = ResourceApi(self.store, clock=self.clock)

        # leader-only control loops, built on becomeLeader
        self._leader_components: list = []
        self.role_manager: Optional[RoleManager] = None
        self._leadership_task: Optional[asyncio.Task] = None
        self._members_task: Optional[asyncio.Task] = None
        self._running = False
        self._is_leader = False

    # ------------------------------------------------------------------
    def _weighted_peers(self) -> list[WeightedPeer]:
        return [WeightedPeer(peer=Peer(node_id=m.node_id, addr=m.addr))
                for m in self.raft.cluster.members.values()]

    async def _on_remove_node(self, node_id: str) -> None:
        member = next((m for m in self.raft.cluster.members.values()
                       if m.node_id == node_id), None)
        if member is not None:
            await self.raft.remove_member(member.raft_id)

    def is_leader(self) -> bool:
        return self.raft.is_leader()

    @property
    def leader_addr(self) -> str:
        return self.raft.leader_addr()

    # ------------------------------------------------------------------
    # observability: the /metrics-equivalent scrape surface.  One page
    # merges the typed registry (raft/transport/scheduler/dispatcher/store
    # families), the legacy latency timers, and the store-object gauges
    # (reference: manager.go registers the prometheus handler next to the
    # health service).
    def metrics_text(self) -> str:
        from swarmkit_tpu.metrics import exposition, trace as obs_trace
        return exposition.render_all(
            registry=self.obs,
            legacy_registry=self.metrics_registry,
            collector_gauges=self.metrics.snapshot(),
            tracer=obs_trace.DEFAULT)

    def metrics_snapshot(self) -> dict:
        from swarmkit_tpu.metrics import exposition, trace as obs_trace
        return exposition.snapshot_all(
            registry=self.obs,
            legacy_registry=self.metrics_registry,
            collector_gauges=self.metrics.snapshot(),
            tracer=obs_trace.DEFAULT)

    def is_state_dirty(self) -> bool:
        """reference: manager/dirty.go IsStateDirty — any object beyond the
        cluster + own node means this store has real state."""
        count = sum(len(self.store.find(k))
                    for k in ("service", "task", "network", "secret",
                              "config", "resource", "extension"))
        nodes = self.store.find("node")
        extra_nodes = [n for n in nodes if n.id != self.node_id]
        return count > 0 or len(extra_nodes) > 0

    # ------------------------------------------------------------------
    async def start(self) -> None:
        """reference: manager.Run manager.go:427."""
        self._running = True
        self.raft.pre_join_hook = self._create_joiner_node_record
        # promote our HealthServer onto the wire BEFORE the raft listener
        # starts, so peer probes read real per-service statuses
        # (reference: health service registration manager.go:526-548)
        network = self.raft.opts.network
        if hasattr(network, "set_health"):
            network.set_health(self.addr, lambda: self.health)
        # the metrics scrape service rides the same listener, registered
        # before raft starts for the same reason as health above
        if hasattr(network, "add_service"):
            from swarmkit_tpu.rpc import metrics_handlers
            network.add_service(self.addr, metrics_handlers(self.metrics_text))
        leadership = self.raft.leadership.watch()
        await self.raft.start()
        await self.metrics.start()
        self.health.set_serving_status("Raft", HealthStatus.SERVING)
        self.health.set_serving_status("ControlAPI", HealthStatus.SERVING)
        self.health.set_serving_status("Watch", HealthStatus.SERVING)
        self.health.set_serving_status("ResourceAllocator",
                                       HealthStatus.SERVING)
        self._leadership_task = asyncio.get_running_loop().create_task(
            self._handle_leadership_events(leadership))
        # we may already be the leader (single-node bootstrap elects fast)
        if self.raft.is_leader() and not self._is_leader:
            await self._become_leader()

    async def stop(self) -> None:
        self._running = False
        self.health.shutdown()
        if self._leadership_task is not None:
            self._leadership_task.cancel()
            try:
                await self._leadership_task
            except (asyncio.CancelledError, Exception):
                pass
            self._leadership_task = None
        await self._become_follower()
        await self.store.stop_coalescing()
        await self.metrics.stop()
        await self.raft.stop()

    async def _handle_leadership_events(self, watcher) -> None:
        """reference: handleLeadershipEvents manager.go:846."""
        try:
            async for ev in watcher:
                if not self._running:
                    return
                if not isinstance(ev, LeadershipState):
                    continue
                # one failed flip (e.g. leadership lost mid-seed, raising
                # ErrLostLeadership from a proposal) must not kill the
                # handler — roll back and keep listening
                try:
                    if ev.is_leader and not self._is_leader:
                        await self._become_leader()
                    elif not ev.is_leader and self._is_leader:
                        await self._become_follower()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("leadership flip failed; demoting")
                    try:
                        await self._become_follower()
                    except Exception:
                        log.exception("follower rollback failed")
        except asyncio.CancelledError:
            pass
        except Exception:
            log.exception("leadership handler crashed")

    # ------------------------------------------------------------------
    async def _become_leader(self) -> None:
        """reference: becomeLeader manager.go:906."""
        log.info("manager %s became leader", self.node_id)
        self._is_leader = True
        self.metrics.set_leader(True)
        await self._seed_defaults()

        # the CA signing service, loaded from the replicated cluster object
        # (reference: ca.Server started in becomeLeader manager.go:906)
        cluster = self.store.find("cluster")[0]
        if cluster.root_ca.ca_cert and cluster.root_ca.ca_key:
            self.ca_server = CAServer(
                self.store,
                RootCA(cluster.root_ca.ca_cert, cluster.root_ca.ca_key),
                org=cluster.id, clock=self.clock)
        self.control_api.ca_server = self.ca_server

        sched_kw = {}
        if self._sched_commit_debounce is not None:
            sched_kw["commit_debounce"] = self._sched_commit_debounce
        sched = Scheduler(self.store, clock=self.clock, obs=self.obs,
                          use_kernel=self._sched_use_kernel, **sched_kw)
        replicated = ReplicatedOrchestrator(self.store, clock=self.clock)
        global_ = GlobalOrchestrator(self.store, clock=self.clock)
        reaper = TaskReaper(self.store, clock=self.clock)
        enforcer = ConstraintEnforcer(self.store, clock=self.clock)
        allocator = Allocator(self.store, clock=self.clock)
        keymanager = KeyManager(self.store, clock=self.clock)
        # reconciliation retries scale with the raft tick so fast-tick test
        # clusters retry fast too (production: 1 s ticks → 16 s interval)
        self.role_manager = RoleManager(
            self.store, self.raft, clock=self.clock,
            reconcile_interval=16.0 * self.raft.opts.tick_interval)

        # allocator first so tasks reach PENDING before scheduling
        # (reference ordering in becomeLeader)
        self._leader_components = [allocator, sched, replicated, global_,
                                   reaper, enforcer, keymanager,
                                   self.role_manager]
        if self.ca_server is not None:
            self._leader_components.append(self.ca_server)
        for c in self._leader_components:
            await c.start()
        await self.dispatcher.start(mark_unknown=True)
        # node records for raft members: the reference's CA server creates
        # these when issuing certs to joiners (ca/server.go
        # IssueNodeCertificate); until a node-side CA join flow runs, the
        # leader reconciles them from the member list.  Watch BEFORE the
        # initial reconcile so a join during the first write isn't lost.
        members_watcher = self.raft.cluster.broadcast.watch()
        await self._ensure_member_node_records()
        self._members_task = asyncio.get_running_loop().create_task(
            self._watch_members(members_watcher))

    @staticmethod
    def _manager_node_record(node_id: str) -> ApiNode:
        """The node record the leader materializes for a raft member —
        single source for both the pre-join hook and the sweep."""
        return ApiNode(
            id=node_id,
            spec=NodeSpec(
                annotations=Annotations(name=node_id),
                desired_role=NodeRole.MANAGER,
                membership=MembershipState.ACCEPTED),
            role=NodeRole.MANAGER,
            status=NodeStatus())

    async def _create_joiner_node_record(self, node_id: str,
                                         addr: str) -> None:
        """pre_join_hook: commit the joiner's node record before its member
        can exist, so the role manager never sees a record-less member to
        reap (reference ordering: ca/server.go IssueNodeCertificate runs
        before the manager joins raft)."""
        if self.role_manager is not None \
                and node_id in self.role_manager.pending_removal:
            return  # a record the role manager is deleting must stay gone

        def txn(tx):
            if tx.get("node", node_id) is None:
                tx.create(self._manager_node_record(node_id))
        await self.store.update(txn)

    async def _ensure_member_node_records(self) -> None:
        members = list(self.raft.cluster.members.values())
        # records the role manager is deleting must stay deleted — the
        # sweep otherwise resurrects them faster than the member removal
        # converges
        being_removed = (set(self.role_manager.pending_removal)
                         if self.role_manager is not None else set())

        def txn(tx):
            for m in members:
                if not m.node_id or m.node_id in being_removed \
                        or tx.get("node", m.node_id) is not None:
                    continue
                tx.create(self._manager_node_record(m.node_id))
        await self.store.update(txn)

    async def _watch_members(self, watcher) -> None:
        # Event-driven with a periodic sweep: a membership event arriving
        # during a transient leadership blip must not end reconciliation
        # forever (the blip window is exactly when joins churn), and a
        # failed ensure (proposal timeout on a flip) retries. The txn is
        # create-only, so sweeps are free once records exist.
        try:
            async for _ev in watch_with_sweep(watcher, self.clock, 2.0):
                if not self._running:
                    return
                if self._is_leader:
                    try:
                        await self._ensure_member_node_records()
                    except Exception as e:
                        log.debug("member-record reconcile failed; "
                                  "retrying: %s", e)
        except asyncio.CancelledError:
            pass
        except Exception:
            log.exception("member watch crashed")

    async def _become_follower(self) -> None:
        """reference: becomeFollower manager.go:1088."""
        if self._is_leader:
            log.info("manager %s lost leadership", self.node_id)
        self._is_leader = False
        self.metrics.set_leader(False)
        if self._members_task is not None:
            self._members_task.cancel()
            try:
                await self._members_task
            except (asyncio.CancelledError, Exception):
                pass
            self._members_task = None
        if self.dispatcher._running:
            await self.dispatcher.stop()
        for c in reversed(self._leader_components):
            try:
                await c.stop()
            except Exception:
                log.exception("stopping leader component %r failed", c)
        self._leader_components = []
        self.role_manager = None
        self.ca_server = None
        self.control_api.ca_server = None

    def _bootstrap_root_ca(self) -> Optional[RootCA]:
        if self.security is not None and self.security.root_ca.can_sign:
            return self.security.root_ca
        from swarmkit_tpu.ca.certificates import HAVE_CRYPTOGRAPHY
        if not HAVE_CRYPTOGRAPHY:
            # No x509 stack in this environment: seed the cluster object
            # without CA material (join tokens / TLS identities disabled).
            log.warning("cryptography unavailable; bootstrapping cluster "
                        "without a root CA")
            return None
        return RootCA.create()

    async def _seed_defaults(self) -> None:
        """Seed the default cluster object and our own node record
        (reference: becomeLeader manager.go:931-983)."""
        seed_cluster = not self.store.find("cluster")
        root_ca = self._bootstrap_root_ca() if seed_cluster else None

        # bootstrap cluster id = the certificate org (reference:
        # manager.go uses securityConfig's Organization as the cluster id)
        cluster_id = (self.security.org if self.security is not None
                      else "cluster-" + DEFAULT_CLUSTER_NAME)

        def txn(tx):
            clusters = tx.find("cluster")
            if not clusters and seed_cluster:
                cluster = Cluster(
                    id=cluster_id,
                    spec=ClusterSpec(
                        annotations=Annotations(name=DEFAULT_CLUSTER_NAME)))
                if root_ca is not None:
                    cluster.root_ca.ca_cert = root_ca.cert_pem
                    cluster.root_ca.ca_key = root_ca.key_pem or b""
                    cluster.root_ca.ca_cert_hash = root_ca.digest()
                    cluster.root_ca.join_token_worker = ca_token(root_ca)
                    cluster.root_ca.join_token_manager = ca_token(root_ca)
                tx.create(cluster)
            if tx.get("node", self.node_id) is None:
                tx.create(ApiNode(
                    id=self.node_id,
                    spec=NodeSpec(
                        annotations=Annotations(name=self.node_id),
                        desired_role=NodeRole.MANAGER,
                        membership=MembershipState.ACCEPTED),
                    role=NodeRole.MANAGER,
                    status=NodeStatus()))
        await self.store.update(txn)

"""gRPC-health-protocol-shaped service registry.

Reference: manager/health/health.go (:21) — per-service SERVING /
NOT_SERVING statuses, checked by joiners before trusting a manager
(raft.go:1422 vote-health gating uses this).
"""

from __future__ import annotations

import enum


class HealthStatus(enum.IntEnum):
    UNKNOWN = 0
    SERVING = 1
    NOT_SERVING = 2


class HealthServer:
    def __init__(self) -> None:
        self._statuses: dict[str, HealthStatus] = {}

    def set_serving_status(self, service: str, status: HealthStatus) -> None:
        self._statuses[service] = status

    def check(self, service: str = "") -> HealthStatus:
        return self._statuses.get(service, HealthStatus.UNKNOWN)

    def shutdown(self) -> None:
        for k in self._statuses:
            self._statuses[k] = HealthStatus.NOT_SERVING

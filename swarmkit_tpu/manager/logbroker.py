"""Cluster-wide service logs: subscription fan-out to agents, message relay
back to API clients.

Reference: manager/logbroker/broker.go (LogBroker :38, SubscribeLogs :224,
ListenSubscriptions :306 — the agent side, PublishLogs :380) and
subscription.go (task/node resolution from a LogSelector).  A client's
SubscribeLogs creates a subscription; every agent whose node runs a matching
task hears it via ListenSubscriptions, streams its workloads' output through
PublishLogs, and the broker relays to the client queue.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import AsyncIterator, Optional

from swarmkit_tpu.store.by import ByNode, ByService
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.identity import new_id
from swarmkit_tpu.watch.queue import Queue


class LogStream(enum.IntEnum):
    UNKNOWN = 0
    STDOUT = 1
    STDERR = 2


@dataclass
class LogContext:
    service_id: str = ""
    node_id: str = ""
    task_id: str = ""


@dataclass
class LogMessage:
    context: LogContext = field(default_factory=LogContext)
    timestamp: float = 0.0
    stream: LogStream = LogStream.STDOUT
    data: bytes = b""
    # producer-local monotonic position (TaskLogBuffer ring sequence);
    # lets a follow-mode publisher skip live lines already shipped in the
    # tail snapshot (duplicate suppression) — never crosses the wire as
    # an identity, purely ordering metadata
    seq: int = 0


@dataclass
class LogSelector:
    service_ids: list[str] = field(default_factory=list)
    node_ids: list[str] = field(default_factory=list)
    task_ids: list[str] = field(default_factory=list)


@dataclass
class SubscribeLogsOptions:
    """reference: api/logbroker.proto:24-28 SubscribeLogsOptions."""

    follow: bool = True        # keep streaming after the backlog
    tail: int = -1             # last N buffered messages (-1 = all)
    streams: tuple = ()        # () = both stdout and stderr
    # non-follow safety valve: a matching node that never publishes (down,
    # no agent) must not hang the stream forever — after this many seconds
    # the backlog collected so far is returned (the reference blocks until
    # context cancellation; a CLI deserves a bound)
    max_wait: float = 10.0


@dataclass
class SubscriptionMessage:
    id: str = ""
    selector: LogSelector = field(default_factory=LogSelector)
    close: bool = False
    options: dict = field(default_factory=dict)


class Subscription:
    def __init__(self, selector: LogSelector, store: MemoryStore,
                 options: Optional[SubscribeLogsOptions] = None) -> None:
        self.id = new_id()
        self.selector = selector
        self.options = options or SubscribeLogsOptions()
        self.store = store
        self.queue: Queue = Queue()
        self.closed = False
        # non-follow completion (reference: broker.go publisher tracking):
        # nodes expected to publish a backlog; when every one has sent its
        # close marker and follow is off, the client stream ends
        self.pending_nodes: set[str] = set()

    def node_ids(self) -> set[str]:
        """Nodes whose agents should feed this subscription
        (reference: subscription.go match)."""
        nodes = set(self.selector.node_ids)
        for tid in self.selector.task_ids:
            t = self.store.get("task", tid)
            if t is not None and t.node_id:
                nodes.add(t.node_id)
        for sid in self.selector.service_ids:
            for t in self.store.find("task", ByService(sid)):
                if t.node_id:
                    nodes.add(t.node_id)
        return nodes

    def message(self, close: bool = False) -> SubscriptionMessage:
        return SubscriptionMessage(
            id=self.id, selector=self.selector, close=close,
            options={"follow": self.options.follow,
                     "tail": self.options.tail,
                     "streams": [int(x) for x in self.options.streams]})


class LogBroker:
    def __init__(self, store: MemoryStore) -> None:
        self.store = store
        self.subscriptions: dict[str, Subscription] = {}
        self.subscription_bus: Queue = Queue()  # SubscriptionMessage fan-out

    # -- client side -----------------------------------------------------
    async def subscribe_logs(self, selector: LogSelector,
                             options: Optional[SubscribeLogsOptions] = None
                             ) -> AsyncIterator[LogMessage]:
        """reference: SubscribeLogs broker.go:224.  With follow=False the
        stream ends once every matching node published its backlog."""
        import asyncio

        sub = Subscription(selector, self.store, options)
        self.subscriptions[sub.id] = sub
        if not sub.options.follow:
            sub.pending_nodes = sub.node_ids()
        watcher = sub.queue.watch()
        self.subscription_bus.publish(sub.message())
        # re-announce when the service's tasks land on new nodes, so agents
        # that start matching after the subscribe pick it up
        # (reference: subscription.Run watches task events)
        refresher = asyncio.get_running_loop().create_task(
            self._refresh_subscription(sub))
        timer = None
        try:
            if not sub.options.follow:
                if not sub.pending_nodes:
                    return   # nothing runs anywhere: empty backlog
                # on expiry the stream must FAIL, not end with a clean
                # eof: nodes that never published their backlog mean the
                # tail is incomplete, and the client cannot otherwise
                # tell a complete tail from a truncated one
                timer = asyncio.get_running_loop().call_later(
                    max(sub.options.max_wait, 0.0),
                    lambda: sub.queue.publish(_TIMEOUT))
            async for msg in watcher:
                if msg is _EOF:
                    return
                if msg is _TIMEOUT:
                    if sub.pending_nodes:
                        raise LogsTruncated(
                            f"{len(sub.pending_nodes)} node(s) never "
                            f"published their backlog within "
                            f"{sub.options.max_wait}s: "
                            f"{sorted(sub.pending_nodes)}")
                    return
                yield msg
        finally:
            if timer is not None:
                timer.cancel()
            refresher.cancel()
            watcher.close()
            sub.closed = True
            self.subscriptions.pop(sub.id, None)
            self.subscription_bus.publish(sub.message(close=True))

    async def _refresh_subscription(self, sub: Subscription) -> None:
        import asyncio

        from swarmkit_tpu.store.memory import Event, match

        known = sub.node_ids()
        watcher = self.store.watch(match(kind="task"))
        try:
            async for ev in watcher:
                now = sub.node_ids()
                if now - known:
                    self.subscription_bus.publish(sub.message())
                known = now
        except asyncio.CancelledError:
            pass
        finally:
            watcher.close()

    # -- agent side ------------------------------------------------------
    async def listen_subscriptions(self, node_id: str
                                   ) -> AsyncIterator[SubscriptionMessage]:
        """reference: ListenSubscriptions broker.go:306 — current matching
        subscriptions first, then live updates."""
        watcher = self.subscription_bus.watch()
        try:
            for sub in list(self.subscriptions.values()):
                if node_id in sub.node_ids():
                    yield sub.message()
            async for msg in watcher:
                sub = self.subscriptions.get(msg.id)
                if msg.close:
                    yield msg
                    continue
                if sub is not None and node_id in sub.node_ids():
                    yield msg
        finally:
            watcher.close()

    async def publish_logs(self, subscription_id: str,
                           messages: list[LogMessage],
                           node_id: str = "", close: bool = False) -> None:
        """reference: PublishLogs broker.go:380.  `close` marks this
        node's publisher finished — with follow=False the subscription
        completes once every pending node closed."""
        sub = self.subscriptions.get(subscription_id)
        if sub is None or sub.closed:
            return
        for m in messages:
            sub.queue.publish(m)
        if close and not sub.options.follow:
            sub.pending_nodes.discard(node_id)
            if not sub.pending_nodes:
                sub.queue.publish(_EOF)


class LogsTruncated(Exception):
    """Non-follow subscription timed out with nodes still pending — the
    returned tail is incomplete and the client must treat it as a failure
    (ctl._stream_logs turns this into an error line, never a clean eof)."""


class _Eof:
    """Stream-end sentinel on a subscription queue."""


class _Timeout:
    """max_wait expiry sentinel: eof if nothing is pending, else error."""


_EOF = _Eof()
_TIMEOUT = _Timeout()

"""Gossip/IPSec key rotation loop.

Reference: manager/keymanager/keymanager.go — keeps a ring of 3 keys per
subsystem in the Cluster object, rotates the primary every 12 h
(DefaultKeyRotationInterval), stamping each key with a lamport time so
agents order them (rotateKey :124, Run :173).
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional

from swarmkit_tpu.api.objects import EncryptionKey
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.keymanager")

DEFAULT_KEY_LEN = 16
DEFAULT_KEY_ROTATION_INTERVAL = 12 * 3600.0
SUBSYSTEM_GOSSIP = "networking:gossip"
SUBSYSTEM_IPSEC = "networking:ipsec"
KEYRING_SIZE = 3
AES_128_GCM = 0


class KeyManager:
    def __init__(self, store: MemoryStore, cluster_id: str = "",
                 subsystems: tuple[str, ...] = (SUBSYSTEM_GOSSIP,
                                                SUBSYSTEM_IPSEC),
                 rotation_interval: float = DEFAULT_KEY_ROTATION_INTERVAL,
                 clock: Optional[Clock] = None) -> None:
        self.store = store
        self.cluster_id = cluster_id
        self.subsystems = subsystems
        self.rotation_interval = rotation_interval
        self.clock = clock or SystemClock()
        self._task: Optional[asyncio.Task] = None
        self._running = False

    def _cluster(self):
        if self.cluster_id:
            return self.store.get("cluster", self.cluster_id)
        clusters = self.store.find("cluster")
        return clusters[0] if clusters else None

    def _allocate_key(self, subsystem: str, lamport: int) -> EncryptionKey:
        return EncryptionKey(subsystem=subsystem, algorithm=AES_128_GCM,
                             key=os.urandom(DEFAULT_KEY_LEN),
                             lamport_time=lamport)

    async def start(self) -> None:
        await self.rotate_if_needed(initial=True)
        self._running = True
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _run(self) -> None:
        try:
            while self._running:
                await self.clock.sleep(self.rotation_interval)
                if self._running:
                    await self.rotate_if_needed()
        except asyncio.CancelledError:
            pass
        except Exception:
            log.exception("key manager crashed")

    async def rotate_if_needed(self, initial: bool = False) -> None:
        """reference: rotateKey keymanager.go:124 — push a fresh key per
        subsystem, trim the ring to 3, bump the lamport clock."""
        cluster = self._cluster()
        if cluster is None:
            return
        if initial and cluster.network_bootstrap_keys:
            return  # keys exist; nothing to seed

        def txn(tx):
            cl = tx.get("cluster", cluster.id)
            if cl is None:
                return
            cl = cl.copy()
            cl.encryption_key_lamport_clock += 1
            lamport = cl.encryption_key_lamport_clock
            keep: list[EncryptionKey] = []
            for subsys in self.subsystems:
                ring = [k for k in cl.network_bootstrap_keys
                        if k.subsystem == subsys]
                ring.append(self._allocate_key(subsys, lamport))
                ring.sort(key=lambda k: -k.lamport_time)
                keep.extend(ring[:KEYRING_SIZE])
            cl.network_bootstrap_keys = keep
            tx.update(cl)
        await self.store.update(txn)

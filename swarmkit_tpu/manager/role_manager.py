"""Leader loop reconciling Node.spec.desired_role with the observed role and
the raft membership.

Reference: manager/role_manager.go — roleManager (:26): promotions flip
Node.role immediately; demotions first remove the node from the raft member
list (with a CanRemoveMember quorum safeguard, and a leadership transfer if
the leader demotes itself), then flip the role on a later pass; deleted
nodes' raft members are removed too.  Failed reconciliations retry every
reconciliation interval (16 s).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import NodeRole
from swarmkit_tpu.store.memory import Event, MemoryStore, match
from swarmkit_tpu.utils.clock import Clock, SystemClock
from swarmkit_tpu.watch.queue import watch_with_sweep

log = logging.getLogger("swarmkit_tpu.rolemanager")

RECONCILIATION_INTERVAL = 16.0   # reference: role_manager.go roleReconcileInterval


class RoleManager:
    def __init__(self, store: MemoryStore, raft, clock: Optional[Clock] = None,
                 reconcile_interval: float = RECONCILIATION_INTERVAL) -> None:
        self.store = store
        self.raft = raft
        self.clock = clock or SystemClock()
        self.reconcile_interval = reconcile_interval
        self.pending: dict[str, object] = {}
        self.pending_removal: set[str] = set()
        # node_id -> first time its member was seen without a node record
        self._orphan_since: dict[str, float] = {}
        self._task: Optional[asyncio.Task] = None
        self._running = False

    async def start(self) -> None:
        watcher = self.store.watch(match(kind="node"))
        # initial pass: reconcile every node, and remove raft members whose
        # node object no longer exists (role_manager.go Run)
        node_ids = set()
        for node in self.store.find("node"):
            node_ids.add(node.id)
            if node.spec.desired_role != node.role:
                self.pending[node.id] = node
        for member in list(self.raft.cluster.members.values()):
            if member.node_id and member.node_id not in node_ids:
                self.pending_removal.add(member.node_id)
        self._running = True
        self._task = asyncio.get_running_loop().create_task(self._run(watcher))

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _run(self, watcher) -> None:
        try:
            await self._reconcile_all()
            async for ev in watch_with_sweep(watcher, self.clock,
                                             self.reconcile_interval):
                if not self._running:
                    return
                if isinstance(ev, Event):
                    if ev.action == "remove":
                        # explicit record deletion: no join-in-progress
                        # grace — the member goes as soon as quorum
                        # rules allow
                        self.pending_removal.add(ev.object.id)
                        self._orphan_since[ev.object.id] = float("-inf")
                    elif ev.object.spec.desired_role != ev.object.role:
                        self.pending[ev.object.id] = ev.object
                await self._reconcile_all()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("role manager crashed")

    async def _reconcile_all(self) -> None:
        # Leader-only, re-checked on EVERY pass: after this manager hands
        # leadership away (self-demotion transfer), a stale pass here must
        # not keep injecting TRANSFER_LEADER requests — followers forward
        # those to the new leader, deposing it and bouncing leadership in a
        # loop that can starve the demotion from ever committing.
        if not self._is_leader():
            return
        for node_id in list(self.pending):
            node = self.store.get("node", node_id)
            if node is None:
                self.pending.pop(node_id, None)
                continue
            try:
                await self._reconcile_role(node)
            except Exception as e:
                # one node's failed reconciliation (proposal timeout on a
                # leadership flip, version conflict) must not kill the loop
                log.info("reconcile of %s failed; retrying later: %s",
                         node_id, e)
            if not self._is_leader():
                return
        for node_id in list(self.pending_removal):
            member = self._member_by_node_id(node_id)
            if member is None:
                self.pending_removal.discard(node_id)
                self._orphan_since.pop(node_id, None)
                continue
            # A member without a node record is only an orphan once the
            # record has been missing for a full reconcile interval: in
            # certless clusters the leader CREATES member records AFTER the
            # raft join, so a role manager freshly started by a leadership
            # flip would otherwise kill a member that is mid-join (the
            # reference never hits this because CA issuance creates the
            # record before the manager ever joins raft).
            if self.store.get("node", node_id) is not None:
                self.pending_removal.discard(node_id)
                self._orphan_since.pop(node_id, None)
                continue
            first = self._orphan_since.setdefault(node_id, self.clock.now())
            if self.clock.now() - first < self.reconcile_interval:
                continue
            try:
                await self._remove_member(member)
            except Exception as e:
                log.info("member removal of %s failed; retrying later: %s",
                         node_id, e)
            if not self._is_leader():
                return

    def _is_leader(self) -> bool:
        return self.raft.is_leader()

    def _member_by_node_id(self, node_id: str):
        for m in self.raft.cluster.members.values():
            if m.node_id == node_id:
                return m
        return None

    async def _remove_member(self, member) -> None:
        """reference: removeMember role_manager.go:200 — quorum safeguard +
        self-demotion leadership transfer."""
        if not self.raft.can_remove_member(member.raft_id):
            log.debug("removing %s would break quorum; retrying later",
                      member.node_id)
            return
        if member.raft_id == self.raft.raft_id:
            if not self._is_leader():
                return  # stale pass after the transfer already happened
            log.info("demoted; transferring leadership")
            try:
                await self.raft.transfer_leadership()
                return
            except Exception as e:
                log.info("failed to transfer leadership: %s", e)
        try:
            await self.raft.remove_member(member.raft_id)
        except Exception as e:
            log.debug("cannot remove member %s yet: %s", member.node_id, e)

    async def _reconcile_role(self, node) -> None:
        """reference: reconcileRole role_manager.go:231."""
        if node.spec.desired_role == node.role:
            self.pending.pop(node.id, None)
            return
        if node.spec.desired_role == NodeRole.MANAGER \
                and node.role == NodeRole.WORKER:
            await self._set_role(node, NodeRole.MANAGER)
            self.pending.pop(node.id, None)
        elif node.spec.desired_role == NodeRole.WORKER \
                and node.role == NodeRole.MANAGER:
            member = self._member_by_node_id(node.id)
            if member is not None:
                # remove from raft first; flip the role on a later pass
                await self._remove_member(member)
                return
            await self._set_role(node, NodeRole.WORKER)
            self.pending.pop(node.id, None)

    async def _set_role(self, node, role: NodeRole) -> None:
        def txn(tx):
            cur = tx.get("node", node.id)
            if cur is None or cur.spec.desired_role != node.spec.desired_role \
                    or cur.role != node.role:
                return
            cur = cur.copy()
            cur.role = role
            tx.update(cur)
        await self.store.update(txn)

"""Placement constraint language: parse + node matching.

Reference: manager/constraint/constraint.go (Parse, NodeMatches) — the
`node.id==abc`, `node.labels.foo!=bar`, `engine.labels.x==y` expressions from
service placement specs.  Values match exact or glob (*) like the reference's
use of filepath.Match-style patterns.
"""

from __future__ import annotations

import fnmatch
import re
from dataclasses import dataclass

EQ = "=="
NEQ = "!="

# reference: constraint.go alphaNumeric / valuePattern
_KEY_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9\-_.]+$")
_VALUE_RE = re.compile(r"^(?i:[a-z0-9:\-_\s.*()?+\[\]\\^$|/]+)$")


class InvalidConstraint(ValueError):
    pass


@dataclass
class Constraint:
    key: str
    operator: str  # "==" | "!="
    value: str

    def match(self, *whats: str) -> bool:
        """True if any candidate matches per the operator
        (reference: constraint.go Match)."""
        hit = any(w == self.value or fnmatch.fnmatchcase(w, self.value)
                  for w in whats)
        return hit if self.operator == EQ else not hit


def parse(expressions: list[str]) -> list[Constraint]:
    """reference: constraint.go Parse."""
    out = []
    for expr in expressions:
        if NEQ in expr:
            parts, op = expr.split(NEQ, 1), NEQ
        elif EQ in expr:
            parts, op = expr.split(EQ, 1), EQ
        else:
            raise InvalidConstraint(
                f"invalid constraint {expr!r}: expected == or !=")
        key, value = parts[0].strip(), parts[1].strip()
        if not key or not value:
            raise InvalidConstraint(f"invalid constraint {expr!r}")
        if not _KEY_RE.match(key):
            raise InvalidConstraint(f"invalid constraint key {key!r}")
        if not _VALUE_RE.match(value):
            raise InvalidConstraint(f"invalid constraint value {value!r}")
        out.append(Constraint(key=key, operator=op, value=value))
    return out


def node_matches(constraints: list[Constraint], node) -> bool:
    """reference: constraint.go NodeMatches."""
    for c in constraints:
        key = c.key.lower()
        if key == "node.id":
            if not c.match(node.id):
                return False
        elif key == "node.hostname":
            hostname = node.description.hostname if node.description else ""
            if not c.match(hostname):
                return False
        elif key == "node.ip":
            if not c.match(node.status.addr or ""):
                return False
        elif key == "node.role":
            from swarmkit_tpu.api import NodeRole
            role = "manager" if node.role == NodeRole.MANAGER else "worker"
            if not c.match(role):
                return False
        elif key == "node.platform.os":
            plat = node.description.platform if node.description else None
            if not c.match(plat.os if plat else ""):
                return False
        elif key == "node.platform.arch":
            plat = node.description.platform if node.description else None
            if not c.match(plat.architecture if plat else ""):
                return False
        elif key.startswith("node.labels."):
            label = c.key[len("node.labels."):]
            val = node.spec.annotations.labels.get(label, "")
            if not c.match(val):
                return False
        elif key.startswith("engine.labels."):
            label = c.key[len("engine.labels."):]
            engine = node.description.engine if node.description else None
            val = (engine.labels if engine else {}).get(label, "")
            if not c.match(val):
                return False
        else:
            # unknown key: only != can pass (reference behavior)
            if c.operator != NEQ:
                return False
    return True

"""Manager control plane: API services and leader-only control loops.

Reference: /root/reference/manager/ — re-expressed as asyncio event-loop
components over the watchable MemoryStore (no goroutines/channels).
"""

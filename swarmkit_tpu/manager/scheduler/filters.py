"""Scheduler filter pipeline.

Reference: manager/scheduler/filter.go (Ready/Resource/Plugin/Constraint/
Platform/HostPort/MaxReplicas filters) and pipeline.go (Pipeline.Process:
SetTask once per task, then Check per node, collecting failure explanations).
"""

from __future__ import annotations

from typing import Optional

from swarmkit_tpu.api import NodeAvailability, NodeState
from swarmkit_tpu.manager import constraint as constraint_mod
from swarmkit_tpu.manager.scheduler.nodeinfo import NodeInfo, task_reserved


class Filter:
    name = "filter"

    def set_task(self, task) -> bool:
        """Return False if this filter is a no-op for the task."""
        raise NotImplementedError

    def check(self, info: NodeInfo) -> bool:
        raise NotImplementedError


class ReadyFilter(Filter):
    """Node must be READY and ACTIVE (filter.go:31)."""

    name = "ready"

    def set_task(self, task) -> bool:
        return True

    def check(self, info: NodeInfo) -> bool:
        return (info.node.status.state == NodeState.READY
                and info.node.spec.availability == NodeAvailability.ACTIVE)


class ResourceFilter(Filter):
    """Reservations must fit remaining resources (filter.go:58)."""

    name = "resource"

    def __init__(self) -> None:
        self._cpus = 0
        self._mem = 0
        self._generic: dict[str, int] = {}

    def set_task(self, task) -> bool:
        self._cpus, self._mem, self._generic = task_reserved(task)
        return bool(self._cpus or self._mem or self._generic)

    def check(self, info: NodeInfo) -> bool:
        if self._cpus > info.available_cpus:
            return False
        if self._mem > info.available_memory:
            return False
        for k, v in self._generic.items():
            # a named id set satisfies a count reservation when enough ids
            # remain free (reference: filter.go:107-150 generic resources)
            if k in info.available_named:
                if v > len(info.available_named[k]):
                    return False
            elif v > info.available_generic.get(k, 0):
                return False
        return True


class ConstraintFilter(Filter):
    """Placement constraint expressions (filter.go:153)."""

    name = "constraint"

    def __init__(self) -> None:
        self._constraints: list = []

    def set_task(self, task) -> bool:
        p = task.spec.placement
        if p is None or not p.constraints:
            self._constraints = []
            return False
        try:
            self._constraints = constraint_mod.parse(p.constraints)
        except constraint_mod.InvalidConstraint:
            # a stored task with an unparseable constraint (pre-validation
            # data, WAL replay) must not crash the scheduler loop — stay
            # active and reject every node so the task parks with an
            # explanation instead
            self._constraints = None
        return True

    def check(self, info: NodeInfo) -> bool:
        if self._constraints is None:
            return False
        return constraint_mod.node_matches(self._constraints, info.node)


class PlatformFilter(Filter):
    """Image/spec platform must match node platform (filter.go:250)."""

    name = "platform"

    def __init__(self) -> None:
        self._platforms: list[str] = []

    def set_task(self, task) -> bool:
        p = task.spec.placement
        self._platforms = list(p.platforms) if p is not None else []
        return bool(self._platforms)

    def check(self, info: NodeInfo) -> bool:
        desc = info.node.description
        plat = desc.platform if desc is not None else None
        if plat is None:
            return False
        node_plat = f"{plat.os}/{plat.architecture}"
        for want in self._platforms:
            if "/" not in want:
                want = f"{want}/{plat.architecture}"
            w_os, w_arch = want.split("/", 1)
            if (not w_os or w_os == plat.os) \
                    and (not w_arch or w_arch == plat.architecture):
                return True
        return False


class HostPortFilter(Filter):
    """Host-mode published ports must be free on the node (filter.go:300)."""

    name = "hostport"

    def __init__(self) -> None:
        self._ports: list[tuple[str, int]] = []

    @staticmethod
    def _host_ports(task) -> list[tuple[str, int]]:
        ep = task.endpoint
        if ep is None:
            return []
        return [(p.protocol, p.published_port) for p in ep.ports
                if p.publish_mode == "host" and p.published_port]

    def set_task(self, task) -> bool:
        self._ports = self._host_ports(task)
        return bool(self._ports)

    def check(self, info: NodeInfo) -> bool:
        used = set()
        for t in info.tasks.values():
            if info.counts_toward_load(t):
                used.update(self._host_ports(t))
        return not any(p in used for p in self._ports)


class MaxReplicasFilter(Filter):
    """placement.max_replicas per node (filter.go:356)."""

    name = "maxreplicas"

    def __init__(self) -> None:
        self._max = 0
        self._service = ""

    def set_task(self, task) -> bool:
        p = task.spec.placement
        self._max = p.max_replicas if p is not None else 0
        self._service = task.service_id
        return self._max > 0

    def check(self, info: NodeInfo) -> bool:
        return info.count_for_service(self._service) < self._max


class PluginFilter(Filter):
    """Node must carry the network/log driver plugins the task references
    (filter.go:104-201).  Plugin entries on EngineDescription.plugins are
    'Type/name' strings ('Network/overlay', 'Log/json-file').  Mirrors the
    reference's leniencies: no engine description -> pass; a named log
    driver only filters when the node reports ANY Log/ plugins (older
    engines didn't report them)."""

    name = "plugin"

    def __init__(self) -> None:
        self._log_driver = ""
        self._net_drivers: list[str] = []

    def set_task(self, task) -> bool:
        # the RESOLVED driver (task.log_driver, populated by new_task from
        # the spec or the cluster's TaskDefaults) — not the raw spec field
        ld = task.log_driver if task.log_driver is not None \
            else getattr(task.spec, "log_driver", None)
        self._log_driver = ld.name if ld is not None \
            and ld.name not in ("", "none") else ""
        self._net_drivers = [a.driver for a in task.networks if a.driver]
        return bool(self._log_driver or self._net_drivers)

    def check(self, info: NodeInfo) -> bool:
        desc = info.node.description
        if desc is None:
            return True   # not running an engine: plugins unsupported
        plugins = set(desc.engine.plugins)
        for d in self._net_drivers:
            if f"Network/{d}" not in plugins:
                return False
        if self._log_driver:
            reports_log = any(p.startswith("Log/") for p in plugins)
            if reports_log and f"Log/{self._log_driver}" not in plugins:
                return False
        return True


DEFAULT_FILTERS = (ReadyFilter, PluginFilter, ResourceFilter,
                   ConstraintFilter, PlatformFilter, HostPortFilter,
                   MaxReplicasFilter)


class Pipeline:
    """reference: pipeline.go:37."""

    def __init__(self, filters=None) -> None:
        self._all = [f() for f in (filters or DEFAULT_FILTERS)]
        self._active: list[Filter] = []

    def set_task(self, task) -> None:
        self._active = [f for f in self._all if f.set_task(task)]

    def process(self, info: NodeInfo) -> bool:
        return all(f.check(info) for f in self._active)

    def explain(self, info: NodeInfo) -> str:
        failed = [f.name for f in self._active if not f.check(info)]
        return "no suitable node (%s)" % ", ".join(failed) if failed else ""

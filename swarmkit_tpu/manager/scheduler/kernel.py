"""Jitted [tasks, nodes] scheduler kernel.

The host path (scheduler.py ``_schedule_group``) re-runs the filter
Pipeline and rebuilds the spread DecisionTree once PER TASK — O(T · N)
Python with an O(N log N) sort inside.  This module expresses the same
group fan-out as one jitted device program: encoded feasibility columns,
a ``lax.fori_loop`` greedy pass, and masked lexicographic argmins — the
same array-native shape the raft tick already has.

**Bit-identity contract.**  Every task in a group shares one spec
(``_common_spec_key``), so per-(group, node) the filters split into

- *static* checks — Ready, Plugin, Constraint, Platform, plus the
  initial HostPort occupancy and the zero-reservation sign checks of
  Resource — evaluated ONCE on the host using the real filter classes
  (no re-implementation to drift), and
- *dynamic* checks — Resource cpu/mem/discrete-generic depletion,
  MaxReplicas, and same-group HostPort self-conflicts — which under an
  identical-spec group reduce to an integer per-node CAPACITY
  ``cap[n]`` = how many tasks of this spec the node can take.  The only
  device-side state is ``a[n]``, tasks assigned so far; feasibility at
  every step is ``static[n] & (a[n] < cap[n])``, exactly complementing
  the filters' ``>`` comparisons (host capacities are computed with
  exact Python integers, so no 64-bit device arithmetic is needed).

Selection replicates ``find_best_nodes(1, ...)``: a stable-sorted
lexicographic minimum over (taint, count_for_service,
active_task_count, insertion index), nested inside a (branch load,
branch first-seen index) minimum when one spread preference level is
present — the DecisionTree's stable branch ranking and its dict
insertion order tie-break, re-derived per task from the CURRENT
feasible set just as the host rebuilds the tree per task.

``encode_group`` returns None — host Pipeline fallback — for the cases
the encoding does not cover: named generic resources (claim side
effects) and >1 spread preference levels.  The host Pipeline stays the
oracle; tests/test_scheduler_kernel.py pins decisions bit-identical on
randomized task/node sets.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from functools import partial
from typing import Optional

from swarmkit_tpu.manager.scheduler.filters import (
    ConstraintFilter, HostPortFilter, Pipeline, PlatformFilter, PluginFilter,
    ReadyFilter,
)
from swarmkit_tpu.manager.scheduler.nodeinfo import NodeInfo, task_reserved
from swarmkit_tpu.manager.scheduler.nodeset import spread_keys

log = logging.getLogger("swarmkit_tpu.sched_kernel")

# Locked two-way to the catalog by metrics_lint check #12.
METRIC_NAMES: dict[str, tuple[str, ...]] = {
    "swarm_sched_kernel_groups_total": ("path",),
    "swarm_sched_kernel_tasks_total": (),
    "swarm_sched_kernel_seconds": (),
}
SAMPLE_LABELS: dict[str, str] = {"path": "kernel"}

_STATIC_FILTERS = (ReadyFilter, PluginFilter, ConstraintFilter,
                   PlatformFilter)


def _pow2(n: int, floor: int = 8) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


@dataclass
class GroupEncoding:
    """Host-encoded columns for one task group (all lists length N, the
    scheduler's node insertion order)."""

    node_list: list          # NodeInfo, insertion order
    static_ok: list          # bool
    cap: list                # int, 0..T+1
    count0: list             # count_for_service at group start
    active0: list            # active_task_count at group start
    taint: list              # bool
    branch: list             # spread branch id (all 0 when no spread)
    n_branches: int          # 0 = no spread level
    has_service: bool
    gen: dict                # discrete generic reservation (for decode)


def encode_group(sample, prefs: list[str], node_list: list[NodeInfo],
                 fkey: tuple, now: float) -> Optional[GroupEncoding]:
    """Encode one group's scheduling state; None → host fallback."""
    t_cap = 1 << 30  # "unbounded" sentinel before clamping

    cpus, mem, gen = task_reserved(sample)
    res_active = bool(cpus or mem or gen)
    if gen and any(k in info.available_named
                   for info in node_list for k in gen):
        return None   # named generic resources: claim side effects
    spreads = [p for p in prefs
               if (p.split("=", 1)[0].strip().lower() if "=" in p
                   else "spread") == "spread"]
    if len(spreads) > 1:
        return None   # multi-level spread tree

    statics = Pipeline(filters=_STATIC_FILTERS)
    statics.set_task(sample)
    hostport = HostPortFilter()
    hostport_active = hostport.set_task(sample)

    p = sample.spec.placement
    max_replicas = p.max_replicas if p is not None else 0
    service_id = sample.service_id

    static_ok, cap, count0, active0, taintv = [], [], [], [], []
    branch, branch_ids = [], {}
    for info in node_list:
        ok = statics.process(info)
        c = t_cap
        if res_active:
            # exact complements of ResourceFilter.check under repeated
            # identical reservations, computed with Python bigints:
            # after a assignments, available = initial - a*need, and
            # "need > available" fails ⇔ a >= floor(initial/need)
            for need, avail in ((cpus, info.available_cpus),
                                (mem, info.available_memory)):
                if need > 0:
                    c = min(c, avail // need if avail >= 0 else 0)
                elif avail < 0:
                    ok = False     # "0 > avail" fails the host check
            for k, v in gen.items():
                avail = info.available_generic.get(k, 0)
                if v > 0:
                    c = min(c, avail // v if avail >= 0 else 0)
                elif avail < 0:
                    ok = False
        if max_replicas > 0 and service_id:
            # serviceless tasks never bump count_for_service, so the host
            # check stays 0 < max forever — no capacity bound
            c = min(c, max_replicas - info.count_for_service(service_id))
        if hostport_active:
            if not hostport.check(info):
                ok = False
            # same-group tasks publish the same host ports: one per node
            c = min(c, 1)
        static_ok.append(bool(ok))
        cap.append(max(0, min(c, t_cap)))
        count0.append(info.count_for_service(service_id))
        active0.append(info.active_task_count())
        # idempotent: the host comparator calls taint() repeatedly with
        # the same `now`; one call returns the same value and leaves
        # recent_failures in the same pruned state
        taintv.append(bool(info.taint(fkey, now)))
        if spreads:
            key = spread_keys(spreads, info)[0]
            branch.append(branch_ids.setdefault(key, len(branch_ids)))
        else:
            branch.append(0)
    return GroupEncoding(node_list=node_list, static_ok=static_ok, cap=cap,
                         count0=count0, active0=active0, taint=taintv,
                         branch=branch, n_branches=len(branch_ids),
                         has_service=bool(service_id), gen=gen)


# --------------------------------------------------------------------------
# device kernel

def _build_place():
    import jax
    import jax.numpy as jnp

    BIG = jnp.int32(1 << 30)

    def _refine(m, vals):
        """Narrow mask m to the entries minimizing vals (lexicographic
        stage; an all-false mask stays all-false)."""
        best = jnp.where(m, vals, BIG).min()
        return m & (vals == best)

    @partial(jax.jit, static_argnames=("t_pad", "b_pad", "spread"))
    def place(static_ok, cap, count0, active0, taint, branch,
              has_service, t_count, *, t_pad, b_pad, spread):
        n = static_ok.shape[0]
        idx = jnp.arange(n, dtype=jnp.int32)

        def body(i, state):
            a, choices = state
            count = count0 + a * has_service
            active = active0 + a
            feas = static_ok & (a < cap)
            found = feas.any() & (i < t_count)
            if spread:
                load_b = jnp.zeros(b_pad, jnp.int32).at[branch].add(
                    jnp.where(feas, count, 0))
                any_b = jnp.zeros(b_pad, jnp.bool_).at[branch].max(feas)
                first_b = jnp.full(b_pad, BIG, jnp.int32).at[branch].min(
                    jnp.where(feas, idx, BIG))
                bm = _refine(any_b, load_b)
                bm = _refine(bm, first_b)
                bidx = jnp.argmax(bm).astype(jnp.int32)
                feas = feas & (branch == bidx)
            m = _refine(feas, taint.astype(jnp.int32))
            m = _refine(m, count)
            m = _refine(m, active)
            pick = jnp.where(m, idx, BIG).min().astype(jnp.int32)
            choice = jnp.where(found, pick, jnp.int32(-1))
            a = a.at[choice].add(jnp.where(found, 1, 0).astype(jnp.int32))
            return a, choices.at[i].set(choice)

        a0 = jnp.zeros(n, jnp.int32)
        out0 = jnp.full(t_pad, -1, jnp.int32)
        _, choices = jax.lax.fori_loop(0, t_pad, body, (a0, out0))
        return choices

    return place


_PLACE = None


def place_group(enc: GroupEncoding, n_tasks: int) -> list[int]:
    """Run the jitted kernel; returns per-task node indices (-1 = no
    fit), FIFO over the group."""
    global _PLACE
    import numpy as np

    if _PLACE is None:
        _PLACE = _build_place()
    n = len(enc.node_list)
    n_pad = _pow2(n)
    t_pad = _pow2(n_tasks)
    b_pad = _pow2(max(1, enc.n_branches), floor=1)
    t_clamp = min(1 << 20, t_pad) + 1

    def col(vals, fill, dtype):
        arr = np.full(n_pad, fill, dtype=dtype)
        arr[:n] = vals
        return arr

    choices = _PLACE(
        col([bool(v) for v in enc.static_ok], False, np.bool_),
        col([min(v, t_clamp) for v in enc.cap], 0, np.int32),
        col(enc.count0, 0, np.int32),
        col(enc.active0, 0, np.int32),
        col([bool(v) for v in enc.taint], False, np.bool_),
        col(enc.branch, 0, np.int32),
        np.int32(1 if enc.has_service else 0),
        np.int32(n_tasks),
        t_pad=t_pad, b_pad=b_pad,
        spread=enc.n_branches > 0)
    return [int(c) for c in np.asarray(choices)[:n_tasks]]

"""Event-driven task scheduler.

Reference: manager/scheduler/scheduler.go — watches the store, keeps an
in-memory mirror of nodes + tasks, debounces commits (50 ms, max latency 1 s,
scheduler.go:123-128), groups unassigned tasks by common spec key
(commonSpecKey, :376), runs the filter pipeline once per group, and picks
least-loaded nodes with spread preferences (scheduleTaskGroup :533).
Decisions are applied in a store batch with retry when the task changed
underneath (applySchedulingDecisions :432).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import TaskState
from swarmkit_tpu.metrics import catalog as obs_catalog
from swarmkit_tpu.metrics import registry as obs_registry
from swarmkit_tpu.manager.scheduler.filters import Pipeline
from swarmkit_tpu.manager.scheduler.nodeinfo import NodeInfo, task_reserved
from swarmkit_tpu.manager.scheduler.nodeset import NodeSet
from swarmkit_tpu.store.by import ByTaskState
from swarmkit_tpu.store.memory import Event, EventCommit, MemoryStore, match, match_commit
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.scheduler")

COMMIT_DEBOUNCE = 0.050   # reference: scheduler.go:126
MAX_LATENCY = 1.0         # reference: scheduler.go:124


class Scheduler:
    def __init__(self, store: MemoryStore, clock: Optional[Clock] = None,
                 obs: Optional[obs_registry.MetricsRegistry] = None,
                 commit_debounce: float = COMMIT_DEBOUNCE,
                 max_latency: float = MAX_LATENCY,
                 use_kernel: bool = False) -> None:
        self.store = store
        self.clock = clock or SystemClock()
        self.obs = obs or obs_registry.DEFAULT
        # debounce knobs ride the injected Clock, so tests and the load
        # harness can run debounce-accurate without wall-clock sleeps
        self.commit_debounce = commit_debounce
        self.max_latency = max_latency
        # jitted [tasks, nodes] group-placement kernel (kernel.py); the
        # host Pipeline below stays the oracle and the fallback
        self.use_kernel = use_kernel
        self._m_kernel_groups = obs_catalog.get(
            self.obs, "swarm_sched_kernel_groups_total")
        self._m_kernel_tasks = obs_catalog.get(
            self.obs, "swarm_sched_kernel_tasks_total")
        self._m_kernel_seconds = obs_catalog.get(
            self.obs, "swarm_sched_kernel_seconds")
        self._m_latency = obs_catalog.get(
            self.obs, "swarm_scheduler_latency_seconds")
        self._m_decisions = obs_catalog.get(
            self.obs, "swarm_scheduler_decisions_total")
        obs_catalog.get(self.obs, "swarm_scheduler_pending_tasks") \
            .set_function(lambda: float(len(self.unassigned)
                                        + len(self.preassigned)))
        self.node_set = NodeSet()
        self.unassigned: dict[str, object] = {}  # taskid -> task
        # PENDING tasks that arrived with a node already chosen (global
        # services pin one task per node): the scheduler still validates
        # the fit and flips them to ASSIGNED (reference:
        # pendingPreassignedTasks + processPreassignedTasks scheduler.go)
        self.preassigned: dict[str, object] = {}
        self.all_tasks: dict[str, object] = {}
        self.pipeline = Pipeline()
        self._task: Optional[asyncio.Task] = None
        self._running = False
        self._changed_since_tick = True

    # ------------------------------------------------------------------
    async def start(self) -> None:
        # initial state (reference: Run :105 buildNodeSet under view)
        watcher = self.store.watch(match(kind="task"), match(kind="node"),
                                   match_commit)
        for t in self.store.find("task"):
            if t.status.state == TaskState.PENDING:
                if t.node_id:
                    self.preassigned[t.id] = t
                else:
                    self.unassigned[t.id] = t
            self.all_tasks[t.id] = t
        for n in self.store.find("node"):
            self.node_set.add_or_update(self._node_info(n))
        self._running = True
        self._task = asyncio.get_running_loop().create_task(
            self._run(watcher))

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    def _node_info(self, node) -> NodeInfo:
        tasks = {t.id: t for t in self.all_tasks.values()
                 if t.node_id == node.id}
        return NodeInfo(node, tasks)

    # ------------------------------------------------------------------
    async def _run(self, watcher) -> None:
        try:
            while self._running:
                ev = await watcher.get()
                dirty = self._handle(ev)
                # debounce: wait for a quiet 50 ms window (or 1 s max)
                start = self.clock.now()
                while self._running:
                    try:
                        nxt = watcher.try_get()
                        if nxt is None:
                            await self.clock.sleep(self.commit_debounce)
                            nxt = watcher.try_get()
                            if nxt is None:
                                break
                        dirty = self._handle(nxt) or dirty
                    except Exception:
                        raise
                    if self.clock.now() - start > self.max_latency:
                        break
                if dirty and self._running:
                    await self.tick()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("scheduler loop crashed")

    def _handle(self, ev) -> bool:
        """Update mirrors; return True when a tick might make progress."""
        if isinstance(ev, EventCommit):
            # only retry unassigned work when something actually changed
            # since the last tick — a commit alone can't make progress
            fire = self._changed_since_tick \
                and bool(self.unassigned or self.preassigned)
            return fire
        if not isinstance(ev, Event):
            return False
        if ev.kind == "node":
            self._changed_since_tick = True
            if ev.action == "remove":
                self.node_set.remove(ev.object.id)
            else:
                # rebuild NodeInfo so available_* reflect a changed
                # description (resources can grow/shrink on re-register) —
                # but carry the failure history forward: node status churn
                # (READY/DOWN flaps) must not reset the taint backoff
                old = self.node_set.get(ev.object.id)
                info = self._node_info(ev.object)
                if old is not None:
                    info.recent_failures = old.recent_failures
                self.node_set.add_or_update(info)
            return True
        if ev.kind == "task":
            self._changed_since_tick = True
            t = ev.object
            if ev.action == "remove":
                self.all_tasks.pop(t.id, None)
                self.unassigned.pop(t.id, None)
                self.preassigned.pop(t.id, None)
                if t.node_id:
                    info = self.node_set.get(t.node_id)
                    if info is not None:
                        info.remove_task(t)
                return False
            prev = self.all_tasks.get(t.id)
            self.all_tasks[t.id] = t
            if prev is not None and prev.node_id:
                info = self.node_set.get(prev.node_id)
                if info is not None:
                    info.remove_task(prev)
            if t.node_id:
                info = self.node_set.get(t.node_id)
                if info is not None:
                    info.add_task(t)
            # remember nodes that keep failing tasks so placement backs off
            # (reference: scheduler.go recording task failures per node)
            if ev.action == "update" and t.node_id \
                    and t.status.state in (TaskState.FAILED,
                                           TaskState.REJECTED) \
                    and (prev is None
                         or prev.status.state != t.status.state):
                info = self.node_set.get(t.node_id)
                if info is not None:
                    info.record_failure(t, self.clock.now())
            if t.status.state == TaskState.PENDING \
                    and t.desired_state <= TaskState.RUNNING:
                if t.node_id:
                    self.preassigned[t.id] = t
                    self.unassigned.pop(t.id, None)
                else:
                    self.unassigned[t.id] = t
                    self.preassigned.pop(t.id, None)
                return True
            self.unassigned.pop(t.id, None)
            self.preassigned.pop(t.id, None)
            return False
        return False

    # ------------------------------------------------------------------
    @staticmethod
    def _common_spec_key(task) -> tuple:
        """Group tasks that can share one scheduling decision pipeline run
        (reference: commonSpecKey scheduler.go:376)."""
        return (task.service_id,
                task.spec.encode() if hasattr(task.spec, "encode")
                else repr(task.spec))

    async def tick(self) -> None:
        """Schedule everything currently unassigned."""
        with self._m_latency.time():
            self._changed_since_tick = False
            if self.preassigned:
                await self._process_preassigned()
            groups: dict[tuple, list] = {}
            for t in list(self.unassigned.values()):
                groups.setdefault(self._common_spec_key(t), []).append(t)

            decisions = []  # (task, node_id, mirrored copy)
            for group in groups.values():
                decisions.extend(self._schedule_group(group))
            placed = {t.id for t, _, _ in decisions}
            if decisions:
                await self._apply(decisions)
            # annotate tasks no filter would place so operators can see why
            # (reference: noSuitableNode scheduler.go — sets task status
            # message; taskFitNode does the same for preassigned misfits)
            unplaced = [t for t in self.unassigned.values()
                        if t.id not in placed] \
                + list(self.preassigned.values())
            if unplaced:
                self._m_decisions.labels(result="unassigned") \
                    .inc(len(unplaced))
            await self._explain_unplaced(unplaced)

    async def _process_preassigned(self) -> None:
        """Validate PENDING tasks whose node is already chosen and flip
        them to ASSIGNED (reference: processPreassignedTasks + taskFitNode
        scheduler.go:34-38).  A task whose pinned node fails the pipeline
        stays pending and is retried when the node changes."""
        from swarmkit_tpu.store.errors import ErrSequenceConflict

        fits = []
        for t in list(self.preassigned.values()):
            info = self.node_set.get(t.node_id)
            if info is None:
                continue
            # the event mirror already booked this task's reservation on
            # its pinned node — take it out so the task does not compete
            # with ITSELF (reference: processPreassignedTasks removes the
            # task from nodeInfo before taskFitNode)
            had = info.remove_task(t)
            self.pipeline.set_task(t)
            if self.pipeline.process(info):
                fits.append((t, info))
            elif had:
                info.add_task(t)
        if not fits:
            return
        batch = self.store.batch()
        applied: dict[str, bool] = {}
        for t, info in fits:
            def txn(tx, t=t):
                current = tx.get("task", t.id)
                if current is None \
                        or current.status.state != TaskState.PENDING \
                        or current.node_id != t.node_id \
                        or current.desired_state > TaskState.RUNNING:
                    return False
                current.status.state = TaskState.ASSIGNED
                current.status.message = "scheduler confirmed node fit"
                current.status.timestamp = self.clock.now()
                tx.update(current)
                return True

            try:
                applied[t.id] = await batch.update(txn)
            except ErrSequenceConflict:
                applied[t.id] = False
        await batch.commit()
        for t, info in fits:
            if applied.get(t.id):
                self.preassigned.pop(t.id, None)
                self._m_decisions.labels(result="preassigned").inc()
            # re-book the reservation either way (the fit check removed it)
            info.add_task(t)

    async def _explain_unplaced(self, tasks: list) -> None:
        updates = []
        for t in tasks:
            self.pipeline.set_task(t)
            if t.node_id:
                # pinned (preassigned): explain the fit against ITS node
                info = self.node_set.get(t.node_id)
                reasons = {self.pipeline.explain(info)} if info is not None \
                    else {f"node {t.node_id} not in scheduler view"}
            else:
                reasons = {self.pipeline.explain(i)
                           for i in self.node_set.nodes.values()} \
                    or {"no nodes"}
            msg = "; ".join(sorted(r for r in reasons if r)) or \
                "no suitable node"
            if msg != t.status.message:
                updates.append((t.id, msg))
        if not updates:
            return

        def txn(tx):
            for tid, msg in updates:
                cur = tx.get("task", tid)
                if cur is not None and cur.status.message != msg:
                    cur.status.message = msg
                    tx.update(cur)
        await self.store.update(txn)

    def _schedule_group(self, tasks: list
                        ) -> list[tuple[object, str, object]]:
        """Returns (task, node_id, mirrored-assigned-copy) triples
        (reference: scheduleTaskGroup :533)."""
        sample = tasks[0]
        self.pipeline.set_task(sample)
        prefs = []
        if sample.spec.placement is not None:
            prefs = list(sample.spec.placement.preferences)
        service_id = sample.service_id

        def better(a: NodeInfo, b: NodeInfo) -> bool:
            ca, cb = a.count_for_service(service_id), b.count_for_service(service_id)
            if ca != cb:
                return ca < cb
            return a.active_task_count() < b.active_task_count()

        now = self.clock.now()
        fkey = NodeInfo.failure_key(sample)   # once per group, not per cmp

        def best(a: NodeInfo, b: NodeInfo) -> bool:
            # nodes that keep failing this service's tasks lose ties
            # (reference: nodeLess + countRecentFailures backoff)
            ta = a.taint(fkey, now)
            tb = b.taint(fkey, now)
            if ta != tb:
                return tb
            return better(a, b)

        if self.use_kernel:
            out = self._schedule_group_kernel(tasks, sample, prefs, fkey, now)
            if out is not None:
                return out
            self._m_kernel_groups.labels(path="host").inc()

        out = []
        for task in tasks:
            candidates = self.node_set.find_best_nodes(
                1, self.pipeline.process, prefs, best,
                load=lambda i: i.count_for_service(service_id))
            if not candidates:
                continue
            info = candidates[0]
            # mirror the assignment so the next pick sees updated load
            assigned = task.copy()
            assigned.node_id = info.id
            # claim concrete named-resource ids now so parallel decisions
            # in this pass cannot hand the same id to two tasks
            _, _, gen = task_reserved(task)
            if gen:
                assigned.assigned_generic = info.claim_named(gen)
            info.add_task(assigned)
            out.append((task, info.id, assigned))
        return out

    def _schedule_group_kernel(self, tasks, sample, prefs, fkey, now
                               ) -> Optional[list]:
        """Jitted group fan-out (kernel.py); None → host fallback for the
        cases the encoding does not cover."""
        from swarmkit_tpu.manager.scheduler import kernel as sched_kernel

        node_list = list(self.node_set.nodes.values())
        if not node_list:
            return []
        with self._m_kernel_seconds.time():
            enc = sched_kernel.encode_group(sample, prefs, node_list,
                                            fkey, now)
            if enc is None:
                return None
            choices = sched_kernel.place_group(enc, len(tasks))
        self._m_kernel_groups.labels(path="kernel").inc()
        out = []
        _, _, gen = task_reserved(sample)
        for task, c in zip(tasks, choices):
            if c < 0:
                continue
            info = node_list[c]
            assigned = task.copy()
            assigned.node_id = info.id
            if gen:
                assigned.assigned_generic = info.claim_named(gen)
            info.add_task(assigned)
            out.append((task, info.id, assigned))
            self._m_kernel_tasks.inc()
        return out

    async def _apply(self, decisions: list[tuple[object, str, object]]) -> None:
        """reference: applySchedulingDecisions :432."""
        from swarmkit_tpu.store.errors import ErrSequenceConflict

        batch = self.store.batch()
        applied: dict[str, bool] = {}
        for task, node_id, _assigned in decisions:
            def txn(tx, task=task, node_id=node_id, _assigned=_assigned):
                current = tx.get("task", task.id)
                if current is None:
                    return False
                if current.status.state != TaskState.PENDING \
                        or current.node_id \
                        or current.desired_state > TaskState.RUNNING:
                    return False  # changed underneath; event flow will retry
                current.status.state = TaskState.ASSIGNED
                current.status.message = "scheduler assigned task"
                current.status.timestamp = self.clock.now()
                current.node_id = node_id
                current.assigned_generic = dict(_assigned.assigned_generic)
                tx.update(current)
                return True

            try:
                applied[task.id] = await batch.update(txn)
            except ErrSequenceConflict:
                applied[task.id] = False
        await batch.commit()
        for task, node_id, assigned in decisions:
            self.unassigned.pop(task.id, None)
            if applied.get(task.id):
                self._m_decisions.labels(result="assigned").inc()
            else:
                # roll the phantom copy back out of the node mirror
                # (reference: applySchedulingDecisions failure path)
                info = self.node_set.get(node_id)
                if info is not None:
                    info.remove_task(assigned)

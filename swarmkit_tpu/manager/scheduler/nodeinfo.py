"""Scheduler's in-memory view of one node.

Reference: manager/scheduler/nodeinfo.go — NodeInfo wraps the store Node with
its task set, per-service active counts, and remaining resources, maintained
incrementally as tasks come and go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from swarmkit_tpu.api import TaskState
from swarmkit_tpu.api.types import TERMINAL_STATES


# reference nodeinfo.go: monitorFailures = 5*time.Minute, maxFailures = 5
FAILURE_WINDOW = 300.0
FAILURE_LIMIT = 5


def task_reserved(task) -> tuple[int, int, dict]:
    res = task.spec.resources
    if res is None or res.reservations is None:
        return 0, 0, {}
    r = res.reservations
    return r.nano_cpus, r.memory_bytes, dict(r.generic)


class NodeInfo:
    def __init__(self, node, tasks: Optional[dict] = None) -> None:
        self.node = node
        self.tasks: dict[str, object] = {}
        # ACTIVE (non-terminal desired) tasks per service
        self.active_tasks_per_service: dict[str, int] = {}
        self.available_cpus = 0
        self.available_memory = 0
        self.available_generic: dict[str, int] = {}
        # named string-set resources: kind -> ids still free on this node
        # (reference: api/genericresource string sets + nodeinfo claims)
        self.available_named: dict[str, set[str]] = {}
        self._advertised_named: dict[str, frozenset] = {}
        desc = node.description
        if desc is not None and desc.resources is not None:
            self.available_cpus = desc.resources.nano_cpus
            self.available_memory = desc.resources.memory_bytes
            self.available_generic = dict(desc.resources.generic)
            self.available_named = {
                k: set(v)
                for k, v in desc.resources.generic_named.items()}
            # releases are clamped to what the node CURRENTLY advertises —
            # a re-register that drops dead chips must not let a finishing
            # task resurrect them
            self._advertised_named = {
                k: frozenset(v)
                for k, v in desc.resources.generic_named.items()}
        # (service id, spec fingerprint) -> timestamps of recent task
        # failures on this node.  Keying by spec too means a service
        # update escapes the taint (reference versionedService,
        # nodeinfo.go:153) — failures of the broken old spec must not
        # penalize the fixed new one.
        self.recent_failures: dict[tuple, list[float]] = {}
        for t in (tasks or {}).values():
            self.add_task(t)

    @property
    def id(self) -> str:
        return self.node.id

    def counts_toward_load(self, task) -> bool:
        return task.desired_state <= TaskState.RUNNING \
            and task.status.state <= TaskState.RUNNING

    def add_task(self, task) -> bool:
        """reference: nodeinfo.go addTask."""
        if task.id in self.tasks:
            return False
        self.tasks[task.id] = task
        if self.counts_toward_load(task):
            cpus, mem, gen = task_reserved(task)
            self.available_cpus -= cpus
            self.available_memory -= mem
            for k, v in gen.items():
                # named kinds deduct their claimed ids below; a task with a
                # named-kind reservation but no recorded claim (scheduled
                # before the kind became named) falls back to the discrete
                # counter so the pool is not overcommitted
                if k in self.available_named and task.assigned_generic.get(k):
                    continue
                self.available_generic[k] = self.available_generic.get(k, 0) - v
            for k, ids in task.assigned_generic.items():
                self.available_named.setdefault(k, set()).difference_update(
                    ids)
            if task.service_id:
                self.active_tasks_per_service[task.service_id] = \
                    self.active_tasks_per_service.get(task.service_id, 0) + 1
        return True

    def remove_task(self, task) -> bool:
        old = self.tasks.pop(task.id, None)
        if old is None:
            return False
        if self.counts_toward_load(old):
            cpus, mem, gen = task_reserved(old)
            self.available_cpus += cpus
            self.available_memory += mem
            for k, v in gen.items():
                if k in self.available_named and old.assigned_generic.get(k):
                    continue
                self.available_generic[k] = self.available_generic.get(k, 0) + v
            for k, ids in old.assigned_generic.items():
                allowed = self._advertised_named.get(k, frozenset())
                self.available_named.setdefault(k, set()).update(
                    set(ids) & allowed)
            if old.service_id:
                n = self.active_tasks_per_service.get(old.service_id, 1) - 1
                if n <= 0:
                    self.active_tasks_per_service.pop(old.service_id, None)
                else:
                    self.active_tasks_per_service[old.service_id] = n
        return True

    def claim_named(self, requirements: dict) -> dict[str, list[str]]:
        """Pick the specific named ids satisfying a reservation on this
        node (reference: genericresource.Claim). Deterministic: sorted ids,
        lowest first. Caller records them on the task so add_task deducts
        exactly these."""
        claimed: dict[str, list[str]] = {}
        for k, v in requirements.items():
            pool = self.available_named.get(k)
            if pool is None:
                continue  # discrete kind
            ids = sorted(pool)[:v]
            if len(ids) < v:
                return {}
            claimed[k] = ids
        return claimed

    def active_task_count(self) -> int:
        return sum(1 for t in self.tasks.values()
                   if self.counts_toward_load(t))

    def count_for_service(self, service_id: str) -> int:
        return self.active_tasks_per_service.get(service_id, 0)

    @staticmethod
    def failure_key(task) -> tuple:
        """reference versionedService: service id + spec fingerprint.
        Fingerprinting serializes the spec — compute once per failure /
        per scheduling group, never inside a comparator."""
        return (task.service_id, task.spec.fingerprint())

    def record_failure(self, task, now: float,
                       window: float = FAILURE_WINDOW) -> None:
        """reference: nodeinfo.go taskFailed — failures keyed by the
        versioned service (service id + spec).  Also sweeps keys whose
        newest failure left the window (superseded spec revisions would
        otherwise accumulate forever — the old key is never queried
        again once a service is updated; reference lastCleanup sweep,
        nodeinfo.go:181)."""
        dead = [k for k, ts in self.recent_failures.items()
                if not ts or now - ts[-1] >= window]
        for k in dead:
            del self.recent_failures[k]
        self.recent_failures.setdefault(self.failure_key(task),
                                        []).append(now)

    def taint(self, key: tuple, now: float, window: float = FAILURE_WINDOW,
              limit: int = FAILURE_LIMIT) -> bool:
        """True when this node has failed tasks of THIS service spec
        (key = failure_key(task), precomputed by the caller) too often
        lately (reference: countRecentFailures + backoff)."""
        hist = [t for t in self.recent_failures.get(key, ())
                if now - t < window]
        if hist:
            self.recent_failures[key] = hist
        else:
            self.recent_failures.pop(key, None)
        return len(hist) >= limit

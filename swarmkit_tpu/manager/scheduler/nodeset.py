"""Node set with spread-preference decision tree and least-loaded selection.

Reference: manager/scheduler/nodeset.go (nodeSet, findBestNodes),
decision_tree.go (preference tree), nodeheap.go (max-heap of the best K by
fewest active tasks for the relevant service).
"""

from __future__ import annotations

import heapq
from typing import Callable, Optional

from swarmkit_tpu.manager.scheduler.nodeinfo import NodeInfo


class DecisionTree:
    """reference: decision_tree.go — buckets nodes by each spread preference
    level, then picks from buckets round-robin so replicas spread evenly."""

    def __init__(self) -> None:
        self.next_level: Optional[dict[str, "DecisionTree"]] = None
        self.nodes: list[NodeInfo] = []

    def insert(self, keys: list[str], info: NodeInfo) -> None:
        self.nodes.append(info)
        if not keys:
            return
        if self.next_level is None:
            self.next_level = {}
        child = self.next_level.setdefault(keys[0], DecisionTree())
        child.insert(keys[1:], info)

    def order_best(self, n: int, better: Callable[[NodeInfo, NodeInfo], bool],
                   load: Callable[[NodeInfo], int]) -> list[NodeInfo]:
        """Pick up to n nodes, preferring the least-loaded branch first
        (reference: decision_tree.go orderedNodes weighs subtrees by their
        task count for the service, so replicas spread across branches)."""
        if not self.next_level:
            return _best_k(self.nodes, n, better)
        ranked = sorted(
            ((sum(load(i) for i in child.nodes),
              child.order_best(n, better, load))
             for child in self.next_level.values()),
            key=lambda pair: pair[0])
        branches = [b for _, b in ranked]
        out: list[NodeInfo] = []
        # round-robin across branches, least-loaded first
        idx = 0
        while len(out) < n:
            progressed = False
            for b in branches:
                if idx < len(b):
                    out.append(b[idx])
                    progressed = True
                    if len(out) >= n:
                        break
            if not progressed:
                break
            idx += 1
        return out


def _best_k(nodes: list[NodeInfo], k: int,
            better: Callable[[NodeInfo, NodeInfo], bool]) -> list[NodeInfo]:
    """Top-k by the comparison function (reference: nodeheap.go)."""
    import functools

    def cmp(a: NodeInfo, b: NodeInfo) -> int:
        if better(a, b):
            return -1
        if better(b, a):
            return 1
        return 0

    return sorted(nodes, key=functools.cmp_to_key(cmp))[:k]


def spread_keys(preferences: list[str], info: NodeInfo) -> list[str]:
    """Bucket keys for each `spread=node.labels.X` preference
    (reference: nodeset.go tree)."""
    keys = []
    for pref in preferences:
        if "=" in pref:
            strategy, descriptor = pref.split("=", 1)
        else:
            strategy, descriptor = "spread", pref
        if strategy.strip().lower() != "spread":
            continue
        descriptor = descriptor.strip()
        if descriptor.startswith("node.labels."):
            label = descriptor[len("node.labels."):]
            keys.append(info.node.spec.annotations.labels.get(label, ""))
        elif descriptor == "node.id":
            keys.append(info.node.id)
        else:
            keys.append("")
    return keys


class NodeSet:
    """reference: nodeSet nodeset.go:50."""

    def __init__(self) -> None:
        self.nodes: dict[str, NodeInfo] = {}

    def add_or_update(self, info: NodeInfo) -> None:
        self.nodes[info.id] = info

    def remove(self, node_id: str) -> None:
        self.nodes.pop(node_id, None)

    def get(self, node_id: str) -> Optional[NodeInfo]:
        return self.nodes.get(node_id)

    def find_best_nodes(self, n: int, meets: Callable[[NodeInfo], bool],
                        preferences: list[str],
                        better: Callable[[NodeInfo, NodeInfo], bool],
                        load: Optional[Callable[[NodeInfo], int]] = None
                        ) -> list[NodeInfo]:
        """reference: findBestNodes nodeset.go."""
        tree = DecisionTree()
        for info in self.nodes.values():
            if meets(info):
                tree.insert(spread_keys(preferences, info), info)
        return tree.order_best(n, better,
                               load or (lambda i: i.active_task_count()))

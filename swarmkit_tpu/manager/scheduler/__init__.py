from swarmkit_tpu.manager.scheduler.scheduler import Scheduler
from swarmkit_tpu.manager.scheduler.nodeinfo import NodeInfo
from swarmkit_tpu.manager.scheduler.filters import (
    Filter, Pipeline, ReadyFilter, ResourceFilter, ConstraintFilter,
    PlatformFilter, HostPortFilter, MaxReplicasFilter,
)

__all__ = ["Scheduler", "NodeInfo", "Filter", "Pipeline", "ReadyFilter",
           "ResourceFilter", "ConstraintFilter", "PlatformFilter",
           "HostPortFilter", "MaxReplicasFilter"]

"""Control API: validated CRUD over every cluster object.

Reference: manager/controlapi/ — server.go (Server :18), service.go (932 LoC
of CreateService/UpdateService validation), node.go (update/remove incl.
role-change safety), cluster.go (UpdateCluster + join-token rotation),
network.go, secret.go, config.go.  gRPC status codes become exception
types; the store is written through ``store.update`` so every mutation
rides raft when a proposer is attached.

The reference wraps this server in generated raft proxies
(RaftProxyControlServer) so followers forward to the leader; here the
manager exposes the same behavior via a ``leader_conn`` seam on the Manager
(leader proxying lives there, not in this class).
"""

from __future__ import annotations

import re
from typing import Optional

from swarmkit_tpu.api import (
    Annotations, Cluster, Config, Extension, Mode, Network, Node,
    NodeAvailability, NodeRole, NodeState, Resource, Secret, Service, Task,
    TaskState,
)
from swarmkit_tpu.store import by as by_mod
from swarmkit_tpu.store.errors import ErrNameConflict, ErrSequenceConflict
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.identity import new_id

# reference: secret.go MaxSecretSize 500KB
MAX_SECRET_SIZE = 500 * 1024
MAX_CONFIG_SIZE = 500 * 1024

_NAME_RE = re.compile(r"^[a-zA-Z0-9]([a-zA-Z0-9\-_.]*[a-zA-Z0-9])?$")


class ControlError(Exception):
    code = "unknown"


class InvalidArgument(ControlError):
    code = "invalid_argument"


class NotFound(ControlError):
    code = "not_found"


class AlreadyExists(ControlError):
    code = "already_exists"


class FailedPrecondition(ControlError):
    code = "failed_precondition"


class PermissionDenied(ControlError):
    code = "permission_denied"


def validate_annotations(annotations: Optional[Annotations]) -> None:
    """reference: controlapi/common.go validateAnnotations."""
    if annotations is None or not annotations.name:
        raise InvalidArgument("meta: name must be provided")
    if not _NAME_RE.match(annotations.name):
        raise InvalidArgument(
            f"name must conform to {_NAME_RE.pattern}: {annotations.name!r}")


def _validate_task_spec(task_spec) -> None:
    """reference: controlapi/service.go validateTask."""
    if task_spec.container is None:
        raise InvalidArgument("spec: container spec must be provided")
    if not task_spec.container.image:
        raise InvalidArgument("spec: image reference must be provided")
    if task_spec.restart is not None and task_spec.restart.delay < 0:
        raise InvalidArgument("spec: restart delay must be non-negative")
    if task_spec.placement is not None and task_spec.placement.constraints:
        from swarmkit_tpu.manager import constraint as constraint_mod
        try:
            constraint_mod.parse(task_spec.placement.constraints)
        except constraint_mod.InvalidConstraint as e:
            raise InvalidArgument(f"spec: invalid constraint: {e}")
    # resource quantities must be non-negative: a negative reservation
    # would inflate scheduler availability accounting instead of
    # constraining it (reference validateResources)
    res = task_spec.resources
    for group in ((res.reservations, res.limits) if res is not None
                  else ()):
        if group is None:
            continue
        if group.nano_cpus < 0 or group.memory_bytes < 0 \
                or any(v < 0 for v in group.generic.values()):
            raise InvalidArgument(
                "spec: resource quantities must be non-negative")
    # reference service.go validateMounts: every mount needs a target,
    # bind mounts need a source, and targets must not collide
    targets = set()
    for m in task_spec.container.mounts:
        if m.type not in ("bind", "volume", "tmpfs", "npipe"):
            raise InvalidArgument(f"spec: invalid mount type {m.type!r}")
        if not m.target:
            raise InvalidArgument("spec: mount target must be provided")
        if m.target in targets:
            raise InvalidArgument(
                f"spec: duplicate mount target {m.target!r}")
        targets.add(m.target)
        if m.type == "bind" and not m.source:
            raise InvalidArgument("spec: bind mount requires a source")
        if m.type == "tmpfs" and m.source:
            raise InvalidArgument("spec: tmpfs mount cannot have a source")


def _validate_endpoint_spec(ep_spec) -> None:
    """reference: service.go validateEndpointSpec — no duplicate
    (protocol, published_port) within one spec."""
    if ep_spec is None:
        return
    seen = set()
    for p in ep_spec.ports:
        if not (0 <= p.target_port <= 65535) \
                or not (0 <= p.published_port <= 65535):
            raise InvalidArgument("endpoint: port out of range")
        if p.published_port:
            key = (p.protocol, p.published_port)
            if key in seen:
                raise InvalidArgument(
                    f"endpoint: duplicate published port "
                    f"{p.protocol}/{p.published_port}")
            seen.add(key)


def _validate_update_config(update) -> None:
    if update is None:
        return
    if not (0.0 <= update.max_failure_ratio <= 1.0):
        raise InvalidArgument(
            "update: max_failure_ratio must be within [0, 1]")
    if update.delay < 0 or update.monitor < 0:
        raise InvalidArgument("update: delays must be non-negative")


def _validate_service_spec(spec) -> None:
    validate_annotations(spec.annotations)
    _validate_task_spec(spec.task)
    _validate_endpoint_spec(spec.endpoint)
    _validate_update_config(spec.update)
    _validate_update_config(spec.rollback)
    if spec.mode == Mode.REPLICATED:
        if spec.replicated is None or spec.replicated.replicas < 0:
            raise InvalidArgument("spec: replicas must be non-negative")
    elif spec.mode == Mode.GLOBAL:
        if spec.global_ is None:
            raise InvalidArgument("spec: global mode config missing")
    else:
        raise InvalidArgument("spec: unrecognized service mode")


def _mint_manager_kek():
    """A fresh manager autolock key record (reference: generateUnlockKey)."""
    import secrets as _secrets

    from swarmkit_tpu.api.objects import EncryptionKey
    return EncryptionKey(
        subsystem="manager",
        key=("SWMKEY-1-" + _secrets.token_hex(32)).encode())


class ControlApi:
    def __init__(self, store: MemoryStore, raft=None,
                 on_remove_node=None, metrics=None,
                 metrics_registry=None) -> None:
        self.store = store
        self.raft = raft   # for memberlist in node listings / demote checks
        # hook the manager uses to deregister raft members on node removal
        self.on_remove_node = on_remove_node
        self.metrics = metrics  # gauge collector for cluster.metrics
        self.metrics_registry = metrics_registry  # per-node latency timers

    # -- helpers ---------------------------------------------------------
    def _get(self, kind: str, obj_id: str):
        obj = self.store.get(kind, obj_id)
        if obj is None:
            raise NotFound(f"{kind} {obj_id} not found")
        return obj

    def _check_version(self, current, requested_version) -> None:
        if requested_version is not None \
                and current.meta.version.index != requested_version:
            raise FailedPrecondition(
                f"update out of sequence: stored version "
                f"{current.meta.version.index} != {requested_version}")

    @staticmethod
    def _check_secret_config_refs(tx, spec) -> None:
        """reference: service.go checkSecretExistence/checkConfigExistence —
        runs INSIDE the write transaction so a concurrent remove_secret
        cannot slip between the check and the commit."""
        c = spec.task.container
        if c is None:
            return
        missing = [r.secret_id for r in c.secrets
                   if tx.get("secret", r.secret_id) is None]
        missing += [r.config_id for r in c.configs
                    if tx.get("config", r.config_id) is None]
        if missing:
            raise InvalidArgument(
                "spec: unknown secret/config references: "
                + ", ".join(missing))

    # -- service ---------------------------------------------------------
    async def create_service(self, spec) -> Service:
        """reference: CreateService service.go."""
        _validate_service_spec(spec)
        service = Service(id=new_id(), spec=spec.copy())

        def txn(tx):
            self._check_secret_config_refs(tx, spec)
            tx.create(service)
        try:
            await self.store.update(txn)
        except ErrNameConflict:
            raise AlreadyExists(
                f"service name {spec.annotations.name!r} is in use")
        return service

    async def update_service(self, service_id: str, spec,
                             version: Optional[int] = None) -> Service:
        """reference: UpdateService service.go — mode is immutable; the
        prior spec is kept for rollback."""
        _validate_service_spec(spec)

        def txn(tx):
            self._check_secret_config_refs(tx, spec)
            svc = tx.get("service", service_id)
            if svc is None:
                raise NotFound(f"service {service_id} not found")
            self._check_version(svc, version)
            if svc.spec.mode != spec.mode:
                raise InvalidArgument("service mode cannot be changed")
            if svc.spec.annotations.name != spec.annotations.name:
                raise InvalidArgument("renaming services is not supported")
            svc = svc.copy()
            svc.previous_spec = svc.spec
            svc.spec = spec.copy()
            svc.update_status = None
            tx.update(svc)
            return svc
        try:
            return await self.store.update(txn)
        except ErrSequenceConflict:
            raise FailedPrecondition("update out of sequence")

    async def rollback_service(self, service_id: str,
                               version: Optional[int] = None) -> Service:
        """Manual rollback (reference: UpdateServiceRequest.Rollback,
        service.go — restore previous_spec; the update supervisor sees
        ROLLBACK_STARTED and re-runs reconciliation under the rollback
        config, updater.go:587)."""
        from swarmkit_tpu.api.objects import UpdateStatus

        def txn(tx):
            svc = tx.get("service", service_id)
            if svc is None:
                raise NotFound(f"service {service_id} not found")
            self._check_version(svc, version)
            if svc.previous_spec is None:
                raise FailedPrecondition(
                    "service has no previous spec to roll back to")
            svc = svc.copy()
            svc.spec = svc.previous_spec
            svc.previous_spec = None
            svc.update_status = UpdateStatus(
                state="rollback_started",
                message="manually requested rollback")
            tx.update(svc)
            return svc
        try:
            return await self.store.update(txn)
        except ErrSequenceConflict:
            raise FailedPrecondition("rollback out of sequence")

    async def remove_service(self, service_id: str) -> None:
        def txn(tx):
            if tx.get("service", service_id) is None:
                raise NotFound(f"service {service_id} not found")
            tx.delete("service", service_id)
        await self.store.update(txn)

    def get_service(self, service_id: str) -> Service:
        return self._get("service", service_id)

    def list_services(self, names=None, name_prefixes=None, id_prefixes=None,
                      labels=None) -> list[Service]:
        return self._list("service", names, name_prefixes, id_prefixes,
                          labels)

    # -- task ------------------------------------------------------------
    def get_task(self, task_id: str) -> Task:
        return self._get("task", task_id)

    async def remove_task(self, task_id: str) -> None:
        def txn(tx):
            if tx.get("task", task_id) is None:
                raise NotFound(f"task {task_id} not found")
            tx.delete("task", task_id)
        await self.store.update(txn)

    def list_tasks(self, service_ids=None, node_ids=None,
                   desired_states=None, names=None, id_prefixes=None,
                   labels=None) -> list[Task]:
        tasks = self.store.find("task")
        if service_ids:
            tasks = [t for t in tasks if t.service_id in service_ids]
        if node_ids:
            tasks = [t for t in tasks if t.node_id in node_ids]
        if desired_states:
            tasks = [t for t in tasks if t.desired_state in desired_states]
        if id_prefixes:
            tasks = [t for t in tasks
                     if any(t.id.startswith(p) for p in id_prefixes)]
        if names:
            tasks = [t for t in tasks
                     if t.service_annotations.name in names
                     or t.annotations.name in names]
        if labels:
            tasks = [t for t in tasks
                     if all(t.annotations.labels.get(k) == v if v
                            else k in t.annotations.labels
                            for k, v in labels.items())]
        return tasks

    # -- node ------------------------------------------------------------
    def get_node(self, node_id: str) -> Node:
        return self._get("node", node_id)

    def list_nodes(self, roles=None, memberships=None, names=None,
                   id_prefixes=None, labels=None) -> list[Node]:
        nodes = self._list("node", names, None, id_prefixes, labels)
        if roles:
            nodes = [n for n in nodes if n.role in roles]
        if memberships:
            nodes = [n for n in nodes if n.spec.membership in memberships]
        return nodes

    async def update_node(self, node_id: str, spec,
                          version: Optional[int] = None) -> Node:
        """reference: UpdateNode node.go — demotion safety lives with the
        role manager; here we gate demoting the last manager."""
        def txn(tx):
            node = tx.get("node", node_id)
            if node is None:
                raise NotFound(f"node {node_id} not found")
            self._check_version(node, version)
            if spec.desired_role == NodeRole.WORKER:
                # inside the transaction so two concurrent demotions of the
                # last two managers cannot both pass (reference: node.go
                # performs this check within store.Update)
                self._check_can_demote(tx, node_id)
            node = node.copy()
            node.spec = spec.copy()
            tx.update(node)
            return node
        try:
            return await self.store.update(txn)
        except ErrSequenceConflict:
            raise FailedPrecondition("update out of sequence")

    @staticmethod
    def _check_can_demote(tx, node_id: str) -> None:
        target = tx.get("node", node_id)
        if target is None or target.role != NodeRole.MANAGER:
            return
        others = [n for n in tx.find("node")
                  if n.id != node_id and n.role == NodeRole.MANAGER
                  and n.spec.desired_role == NodeRole.MANAGER]
        if not others:
            raise FailedPrecondition(
                "attempting to demote the last manager of the swarm")

    async def remove_node(self, node_id: str, force: bool = False) -> None:
        """reference: RemoveNode node.go — only down workers (or with
        force) can be removed; managers must be demoted first."""
        def txn(tx):
            node = tx.get("node", node_id)
            if node is None:
                raise NotFound(f"node {node_id} not found")
            if node.role == NodeRole.MANAGER:
                raise FailedPrecondition(
                    "node is a cluster manager and is a member of the raft "
                    "cluster; it must be demoted before removal")
            if not force and node.status.state == NodeState.READY:
                raise FailedPrecondition(
                    "node is not down and can't be removed; use force")
            tx.delete("node", node_id)
        await self.store.update(txn)
        if self.on_remove_node is not None:
            await self.on_remove_node(node_id)

    # -- network ---------------------------------------------------------
    async def create_network(self, spec) -> Network:
        validate_annotations(spec.annotations)
        net = Network(id=new_id(), spec=spec.copy())
        try:
            await self.store.update(lambda tx: tx.create(net))
        except ErrNameConflict:
            raise AlreadyExists(
                f"network name {spec.annotations.name!r} is in use")
        return net

    def get_network(self, network_id: str) -> Network:
        return self._get("network", network_id)

    def list_networks(self, names=None, name_prefixes=None, id_prefixes=None,
                      labels=None) -> list[Network]:
        return self._list("network", names, name_prefixes, id_prefixes,
                          labels)

    async def remove_network(self, network_id: str) -> None:
        """reference: RemoveNetwork network.go — refuse while in use."""
        def txn(tx):
            net = tx.get("network", network_id)
            if net is None:
                raise NotFound(f"network {network_id} not found")
            for svc in tx.find("service"):
                nets = list(svc.spec.networks) + list(svc.spec.task.networks)
                if network_id in nets:
                    raise FailedPrecondition(
                        f"network {network_id} is in use by service "
                        f"{svc.id}")
            for t in tx.find("task"):
                if any(a.network_id == network_id for a in t.networks):
                    raise FailedPrecondition(
                        f"network {network_id} is in use by task {t.id}")
            tx.delete("network", network_id)
        await self.store.update(txn)

    # -- cluster ---------------------------------------------------------
    @staticmethod
    def _redact_cluster(cl: Cluster) -> Cluster:
        """Strip private material before returning cluster objects
        (reference: controlapi/cluster.go redactClusters — CA keys and
        unlock keys never leave the manager)."""
        cl = cl.copy()
        cl.root_ca.ca_key = b""
        if cl.root_ca.root_rotation is not None:
            cl.root_ca.root_rotation.ca_key = b""
        cl.unlock_keys = []
        return cl

    async def rotate_root_ca(self) -> dict:
        """Begin a root-CA rotation on the leader (reference: controlapi
        UpdateCluster with a new root + ca/server.go rotation path; the
        integration bar is TestSuccessfulRootRotation)."""
        ca = getattr(self, "ca_server", None)
        if ca is None:
            raise FailedPrecondition("no CA server on this manager (not "
                                     "the leader, or external-CA-only)")
        await ca.start_root_rotation()
        cl = self.get_cluster()
        rot = cl.root_ca.root_rotation
        new_cert = rot.ca_cert if rot else cl.root_ca.ca_cert
        from swarmkit_tpu.ca import RootCA
        return {"rotation_active": rot is not None,
                "new_ca_digest": RootCA(new_cert).digest()}

    async def rotate_unlock_key(self) -> dict:
        """Mint a fresh manager autolock key (reference: swarmctl/swarm
        unlock-key --rotate); manager nodes re-encrypt their keys via the
        autolock watch."""
        minted = _mint_manager_kek()

        def txn(tx):
            clusters = tx.find("cluster")
            if not clusters:
                raise NotFound("cluster object not created yet")
            cl = clusters[0].copy()
            if not cl.spec.encryption_config.auto_lock_managers:
                raise FailedPrecondition(
                    "autolock is not enabled on this cluster")
            cl.unlock_keys = [k for k in cl.unlock_keys
                              if k.subsystem != "manager"] + [minted]
            tx.update(cl)
        await self.store.update(txn)
        # return the key THIS call minted — a re-read could race a
        # concurrent autolock-off or second rotation
        return {"unlock_key": minted.key.decode(), "autolock": True}

    def get_unlock_key(self) -> dict:
        """The manager autolock key (reference: GetUnlockKey ca/server.go —
        deliberately excluded from redacted cluster objects; this is the
        one endpoint that returns it)."""
        clusters = self.store.find("cluster")
        if not clusters:
            raise NotFound("cluster object not created yet")
        cl = clusters[0]
        key = next((k.key for k in cl.unlock_keys
                    if k.subsystem == "manager"), b"")
        return {"unlock_key": key.decode() if key else "",
                "autolock": bool(
                    cl.spec.encryption_config.auto_lock_managers)}

    def get_cluster(self, cluster_id: str = "") -> Cluster:
        if cluster_id:
            return self._redact_cluster(self._get("cluster", cluster_id))
        clusters = self.store.find("cluster")
        if not clusters:
            raise NotFound("cluster not found")
        return self._redact_cluster(clusters[0])

    def list_clusters(self, **kw) -> list[Cluster]:
        return [self._redact_cluster(c)
                for c in self.store.find("cluster")]

    async def update_cluster(self, cluster_id: str, spec,
                             version: Optional[int] = None,
                             rotate_worker_token: bool = False,
                             rotate_manager_token: bool = False) -> Cluster:
        """reference: UpdateCluster cluster.go — spec update + join-token
        rotation flags."""
        validate_annotations(spec.annotations)

        def txn(tx):
            cl = tx.get("cluster", cluster_id)
            if cl is None:
                raise NotFound(f"cluster {cluster_id} not found")
            self._check_version(cl, version)
            cl = cl.copy()
            cl.spec = spec.copy()
            if (rotate_worker_token or rotate_manager_token) \
                    and not cl.root_ca.ca_cert:
                # a token without the CA digest could never be accepted by
                # the CA server — refuse loudly instead of minting it
                raise FailedPrecondition(
                    "cluster has no root CA; cannot rotate join tokens")
            if rotate_worker_token:
                cl.root_ca.join_token_worker = generate_join_token(
                    ca_cert=cl.root_ca.ca_cert)
            if rotate_manager_token:
                cl.root_ca.join_token_manager = generate_join_token(
                    ca_cert=cl.root_ca.ca_cert)
            # Manager autolock (reference: cluster.go UpdateCluster unlock
            # key management + keyreadwriter RotateKEK): toggling it on
            # mints the manager KEK; off clears it.  Every manager node
            # applies the replicated key to its KeyReadWriter (node.py
            # autolock watch).
            want_lock = bool(spec.encryption_config.auto_lock_managers)
            have = [k for k in cl.unlock_keys if k.subsystem == "manager"]
            if want_lock and not have:
                cl.unlock_keys = list(cl.unlock_keys) + [_mint_manager_kek()]
            elif not want_lock and have:
                cl.unlock_keys = [k for k in cl.unlock_keys
                                  if k.subsystem != "manager"]
            tx.update(cl)
            return cl
        try:
            return await self.store.update(txn)
        except ErrSequenceConflict:
            raise FailedPrecondition("update out of sequence")

    # -- secret / config -------------------------------------------------
    async def create_secret(self, spec) -> Secret:
        validate_annotations(spec.annotations)
        if len(spec.data) > MAX_SECRET_SIZE:
            raise InvalidArgument(
                f"secret data must be less than {MAX_SECRET_SIZE} bytes")
        if not spec.data:
            raise InvalidArgument("secret data must be provided")
        secret = Secret(id=new_id(), spec=spec.copy())
        try:
            await self.store.update(lambda tx: tx.create(secret))
        except ErrNameConflict:
            raise AlreadyExists(
                f"secret name {spec.annotations.name!r} is in use")
        return secret

    def get_secret(self, secret_id: str) -> Secret:
        """Returns the secret WITHOUT data (reference: GetSecret redacts)."""
        s = self._get("secret", secret_id).copy()
        s.spec.data = b""
        return s

    def list_secrets(self, names=None, name_prefixes=None, id_prefixes=None,
                     labels=None) -> list[Secret]:
        out = []
        for s in self._list("secret", names, name_prefixes, id_prefixes,
                            labels):
            s = s.copy()
            s.spec.data = b""  # never return secret payloads in lists
            out.append(s)
        return out

    async def update_secret(self, secret_id: str, spec,
                            version: Optional[int] = None) -> Secret:
        """reference: UpdateSecret secret.go — only labels may change."""
        def txn(tx):
            s = tx.get("secret", secret_id)
            if s is None:
                raise NotFound(f"secret {secret_id} not found")
            self._check_version(s, version)
            if spec.data and spec.data != s.spec.data:
                raise InvalidArgument(
                    "only updates to Labels are allowed")
            if spec.annotations.name != s.spec.annotations.name:
                raise InvalidArgument("renaming secrets is not supported")
            s = s.copy()
            s.spec.annotations.labels = dict(spec.annotations.labels)
            tx.update(s)
            return s
        return await self.store.update(txn)

    async def remove_secret(self, secret_id: str) -> None:
        """Refuse to remove a secret in use (reference: RemoveSecret)."""
        def txn(tx):
            if tx.get("secret", secret_id) is None:
                raise NotFound(f"secret {secret_id} not found")
            users = tx.find("service")
            names = [s.spec.annotations.name for s in users
                     if s.spec.task.container is not None
                     and any(r.secret_id == secret_id
                             for r in s.spec.task.container.secrets)]
            if names:
                raise FailedPrecondition(
                    f"secret is in use by services: {', '.join(names)}")
            tx.delete("secret", secret_id)
        await self.store.update(txn)

    async def create_config(self, spec) -> Config:
        validate_annotations(spec.annotations)
        if len(spec.data) > MAX_CONFIG_SIZE:
            raise InvalidArgument(
                f"config data must be less than {MAX_CONFIG_SIZE} bytes")
        if not spec.data:
            raise InvalidArgument("config data must be provided")
        config = Config(id=new_id(), spec=spec.copy())
        try:
            await self.store.update(lambda tx: tx.create(config))
        except ErrNameConflict:
            raise AlreadyExists(
                f"config name {spec.annotations.name!r} is in use")
        return config

    def get_config(self, config_id: str) -> Config:
        return self._get("config", config_id)

    def list_configs(self, names=None, name_prefixes=None, id_prefixes=None,
                     labels=None) -> list[Config]:
        return self._list("config", names, name_prefixes, id_prefixes,
                          labels)

    async def update_config(self, config_id: str, spec,
                            version: Optional[int] = None) -> Config:
        def txn(tx):
            c = tx.get("config", config_id)
            if c is None:
                raise NotFound(f"config {config_id} not found")
            self._check_version(c, version)
            if spec.data and spec.data != c.spec.data:
                raise InvalidArgument("only updates to Labels are allowed")
            if spec.annotations.name != c.spec.annotations.name:
                raise InvalidArgument("renaming configs is not supported")
            c = c.copy()
            c.spec.annotations.labels = dict(spec.annotations.labels)
            tx.update(c)
            return c
        return await self.store.update(txn)

    async def remove_config(self, config_id: str) -> None:
        def txn(tx):
            if tx.get("config", config_id) is None:
                raise NotFound(f"config {config_id} not found")
            users = tx.find("service")
            names = [s.spec.annotations.name for s in users
                     if s.spec.task.container is not None
                     and any(r.config_id == config_id
                             for r in s.spec.task.container.configs)]
            if names:
                raise FailedPrecondition(
                    f"config is in use by services: {', '.join(names)}")
            tx.delete("config", config_id)
        await self.store.update(txn)

    # -- extension / resource -------------------------------------------
    async def create_extension(self, annotations: Annotations,
                               description: str = "") -> Extension:
        validate_annotations(annotations)
        ext = Extension(id=new_id(), annotations=annotations.copy(),
                        description=description)
        try:
            await self.store.update(lambda tx: tx.create(ext))
        except ErrNameConflict:
            raise AlreadyExists(
                f"extension name {annotations.name!r} is in use")
        return ext

    async def remove_extension(self, extension_id: str) -> None:
        def txn(tx):
            ext = tx.get("extension", extension_id)
            if ext is None:
                raise NotFound(f"extension {extension_id} not found")
            for r in tx.find("resource"):
                if r.kind == ext.annotations.name:
                    raise FailedPrecondition(
                        f"extension {extension_id} is in use")
            tx.delete("extension", extension_id)
        await self.store.update(txn)

    async def create_resource(self, annotations: Annotations, kind: str,
                              payload: bytes = b"") -> Resource:
        validate_annotations(annotations)
        exts = [e for e in self.store.find("extension")
                if e.annotations.name == kind]
        if not exts:
            raise InvalidArgument(f"unrecognized resource kind {kind!r}")
        res = Resource(id=new_id(), annotations=annotations.copy(),
                       kind=kind, payload=payload)
        try:
            await self.store.update(lambda tx: tx.create(res))
        except ErrNameConflict:
            raise AlreadyExists(
                f"resource name {annotations.name!r} is in use")
        return res

    async def remove_resource(self, resource_id: str) -> None:
        def txn(tx):
            if tx.get("resource", resource_id) is None:
                raise NotFound(f"resource {resource_id} not found")
            tx.delete("resource", resource_id)
        await self.store.update(txn)

    # -- shared listing --------------------------------------------------
    def _list(self, kind: str, names, name_prefixes, id_prefixes, labels
              ) -> list:
        objs = self.store.find(kind)
        if names:
            objs = [o for o in objs if o.annotations.name in names]
        if name_prefixes:
            objs = [o for o in objs
                    if any(o.annotations.name.startswith(p)
                           for p in name_prefixes)]
        if id_prefixes:
            objs = [o for o in objs
                    if any(o.id.startswith(p) for p in id_prefixes)]
        if labels:
            def has_labels(o):
                have = o.annotations.labels
                return all(have.get(k) == v if v else k in have
                           for k, v in labels.items())
            objs = [o for o in objs if has_labels(o)]
        return objs


def generate_join_token(secret: Optional[str] = None,
                        ca_cert: bytes = b"") -> str:
    """``SWMTKN-1-<ca digest>-<secret>`` (reference: ca/config.go
    GenerateJoinToken).  A CA certificate is required — a digest-less
    token would be unjoinable."""
    if not ca_cert:
        raise ValueError("cannot generate a join token without a root CA")
    from swarmkit_tpu.ca import RootCA
    from swarmkit_tpu.ca import generate_join_token as ca_generate

    return ca_generate(RootCA(ca_cert), secret)

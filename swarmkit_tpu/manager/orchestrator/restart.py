"""Restart supervisor: applies restart policies when tasks fail.

Reference: manager/orchestrator/restart/restart.go — Restart (:103) shuts
down the failed task and, when shouldRestart (:195) allows (condition,
max-attempts within window), creates a replacement in the same slot with
desired_state READY, then DelayStart (:395) flips it to RUNNING after the
policy delay.  Restart history is tracked per slot (restartedInstances ring).
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from dataclasses import dataclass
from typing import Optional

from swarmkit_tpu.api import RestartCondition, TaskState
from swarmkit_tpu.manager.orchestrator import common
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.orchestrator.restart")


@dataclass
class _Instance:
    timestamp: float


class RestartSupervisor:
    def __init__(self, store: MemoryStore, clock: Optional[Clock] = None
                 ) -> None:
        self.store = store
        self.clock = clock or SystemClock()
        # slot tuple -> deque of restart timestamps (restart.go history)
        self._history: dict[tuple, deque] = {}
        self._delays: dict[str, asyncio.Task] = {}  # new task id -> timer

    async def stop(self) -> None:
        for t in self._delays.values():
            t.cancel()
        for t in list(self._delays.values()):
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._delays = {}

    # ------------------------------------------------------------------
    def should_restart(self, task, service) -> bool:
        """reference: shouldRestart restart.go:195."""
        cond = common.restart_condition(task)
        if cond == RestartCondition.NONE:
            return False
        if cond == RestartCondition.ON_FAILURE \
                and task.status.state == TaskState.COMPLETE:
            return False
        policy = common.restart_policy(task)
        if policy.max_attempts == 0:
            return True
        slot = common.slot_tuple(task)
        history = self._history.get(slot, deque())
        now = self.clock.now()
        if policy.window > 0:
            recent = sum(1 for inst in history
                         if now - inst.timestamp <= policy.window)
        else:
            recent = len(history)
        return recent < policy.max_attempts

    def restart(self, tx, cluster, service, task) -> None:
        """Shut down `task`; maybe create its replacement.  Runs inside a
        store transaction (synchronous — only the delayed-start timer is
        async; reference: Restart restart.go:103)."""
        t = tx.get("task", task.id)
        if t is None:
            return
        if t.desired_state > TaskState.RUNNING:
            return  # already being shut down
        t.desired_state = int(TaskState.SHUTDOWN)
        tx.update(t)

        if not self.should_restart(task, service):
            return

        policy = common.restart_policy(task)
        new = common.new_task(cluster, service, slot=task.slot,
                              node_id="" if task.slot else task.node_id)
        # replacement waits in READY until the restart delay elapses
        new.desired_state = int(TaskState.READY)
        tx.create(new)

        slot = common.slot_tuple(task)
        self._history.setdefault(slot, deque(maxlen=256)).append(
            _Instance(timestamp=self.clock.now()))
        self.delay_start(new.id, policy.delay)

    # ------------------------------------------------------------------
    def delay_start(self, task_id: str, delay: float) -> None:
        """reference: DelayStart restart.go:395."""
        if task_id in self._delays:
            return

        async def _timer():
            try:
                if delay > 0:
                    await self.clock.sleep(delay)
                await self.store.update(lambda tx: self._promote(tx, task_id))
            except asyncio.CancelledError:
                pass
            except Exception:
                log.exception("delayed start of %s failed", task_id)
            finally:
                self._delays.pop(task_id, None)

        self._delays[task_id] = asyncio.get_running_loop().create_task(_timer())

    @staticmethod
    def _promote(tx, task_id: str) -> None:
        t = tx.get("task", task_id)
        if t is None or t.desired_state != TaskState.READY:
            return
        t.desired_state = int(TaskState.RUNNING)
        tx.update(t)

    def cancel_delay(self, task_id: str) -> None:
        timer = self._delays.pop(task_id, None)
        if timer is not None:
            timer.cancel()

    def pending_delays(self) -> int:
        return len(self._delays)

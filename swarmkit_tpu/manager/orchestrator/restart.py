"""Restart supervisor: applies restart policies when tasks fail.

Reference: manager/orchestrator/restart/restart.go — Restart (:103) shuts
down the failed task and, when shouldRestart (:195) allows (condition,
max-attempts within window), creates a replacement in the same slot with
desired_state READY, then DelayStart (:395) flips it to RUNNING after the
policy delay.  Restart history is tracked per slot (restartedInstances
ring) and RESETS when the task spec changes (:223 specVersion check), so a
slot that exhausted max_attempts under a broken spec restarts again after
a service update.  Before promoting, DelayStart also waits for the old
task to actually stop (or its node to go down / disappear, or a 1-minute
timeout) so a slot never runs two tasks concurrently; the restart delay is
skipped for tasks leaving a drained node (:156).
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from swarmkit_tpu.api import RestartCondition, TaskState
from swarmkit_tpu.api.types import NodeAvailability, NodeState
from swarmkit_tpu.manager.orchestrator import common
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.orchestrator.restart")

# reference defaultOldTaskTimeout (restart.go:20): the longest the
# replacement waits for the old task to stop before starting anyway
OLD_TASK_TIMEOUT = 60.0


@dataclass
class _Instance:
    timestamp: float


@dataclass
class _History:
    """Per-slot restart record (reference restartedInstanceInfo)."""
    spec_key: int
    total: int = 0
    instances: deque = field(default_factory=lambda: deque(maxlen=256))


def _spec_key(task) -> int:
    """Stable fingerprint of the spec a task runs; plays the role of the
    reference's Task.SpecVersion (restart history resets across updates)."""
    return task.spec.fingerprint()


class RestartSupervisor:
    def __init__(self, store: MemoryStore, clock: Optional[Clock] = None
                 ) -> None:
        self.store = store
        self.clock = clock or SystemClock()
        self.old_task_timeout = OLD_TASK_TIMEOUT
        # slot tuple -> _History (restart.go historyByService)
        self._history: dict[tuple, _History] = {}
        self._delays: dict[str, asyncio.Task] = {}  # new task id -> timer

    async def stop(self) -> None:
        for t in self._delays.values():
            t.cancel()
        for t in list(self._delays.values()):
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._delays = {}

    # ------------------------------------------------------------------
    def should_restart(self, task, service) -> bool:
        """reference: shouldRestart restart.go:195."""
        cond = common.restart_condition(task)
        if cond == RestartCondition.NONE:
            return False
        if cond == RestartCondition.ON_FAILURE \
                and task.status.state == TaskState.COMPLETE:
            return False
        policy = common.restart_policy(task)
        if policy.max_attempts == 0:
            return True
        h = self._history.get(common.slot_tuple(task))
        if h is None or h.spec_key != _spec_key(task):
            # no history under THIS spec: a service update wipes the
            # slot's strike count (restart.go:223)
            return True
        if policy.window <= 0:
            return h.total < policy.max_attempts
        now = self.clock.now()
        recent = sum(1 for inst in h.instances
                     if now - inst.timestamp <= policy.window)
        return recent < policy.max_attempts

    def restart(self, tx, cluster, service, task) -> None:
        """Shut down `task`; maybe create its replacement.  Runs inside a
        store transaction (synchronous — only the delayed-start timer is
        async; reference: Restart restart.go:103)."""
        t = tx.get("task", task.id)
        if t is None:
            return
        if t.desired_state > TaskState.RUNNING:
            return  # already being shut down
        t.desired_state = int(TaskState.SHUTDOWN)
        tx.update(t)

        if not self.should_restart(task, service):
            return

        policy = common.restart_policy(task)
        new = common.new_task(cluster, service, slot=task.slot,
                              node_id="" if task.slot else task.node_id)
        # replacement waits in READY until the restart delay elapses
        new.desired_state = int(TaskState.READY)
        tx.create(new)

        slot = common.slot_tuple(task)
        # record the strike under the REPLACEMENT's spec key: new_task
        # builds from the service's current spec, which may differ from the
        # failed task's.  Keying by the old spec would let the next failure
        # (of the replacement) read the history as stale and wipe the
        # slot's strike count, so max_attempts would never trip across a
        # service update (reference keys by the restarted task's
        # SpecVersion, restart.go:223).
        key = _spec_key(new)
        h = self._history.get(slot)
        if h is None or h.spec_key != key:
            h = self._history[slot] = _History(spec_key=key)
        h.total += 1
        h.instances.append(_Instance(timestamp=self.clock.now()))

        node = tx.get("node", task.node_id) if task.node_id else None
        # restart delay is not applied to drained nodes (restart.go:156):
        # evacuation replacements start immediately
        drained = (node is not None and node.spec is not None
                   and node.spec.availability == NodeAvailability.DRAIN)
        delay = 0.0 if drained else policy.delay
        # wait for the old task to stop before starting the replacement,
        # unless it is already dead or its node is down (restart.go:169)
        node_down = (node is not None and node.status is not None
                     and node.status.state == NodeState.DOWN)
        wait_stop = not (node_down or task.status.state > TaskState.RUNNING)
        self.delay_start(new.id, delay,
                         old_task=task if wait_stop else None)

    # ------------------------------------------------------------------
    def delay_start(self, task_id: str, delay: float,
                    old_task=None, old_tasks=None) -> None:
        """reference: DelayStart restart.go:395 — sleep the restart delay,
        then (when old task(s) are given) hold the replacement in READY
        until EVERY one of them stops running, its node goes down or
        disappears, or `old_task_timeout` elapses, so the slot never runs
        two tasks."""
        if task_id in self._delays:
            return
        olds = list(old_tasks or ([] if old_task is None else [old_task]))

        async def _timer():
            try:
                if delay > 0:
                    await self.clock.sleep(delay)
                if olds:
                    # ONE deadline across all old tasks: N stuck nodes must
                    # not compound the bound to N x old_task_timeout
                    deadline = self.clock.now() + self.old_task_timeout
                    for old in olds:
                        await self._wait_old_task_stopped(old, deadline)
                await self.store.update(lambda tx: self._promote(tx, task_id))
            except asyncio.CancelledError:
                pass
            except Exception:
                log.exception("delayed start of %s failed", task_id)
            finally:
                self._delays.pop(task_id, None)

        self._delays[task_id] = asyncio.get_running_loop().create_task(_timer())

    def _old_task_gone(self, old_task) -> bool:
        t = self.store.get("task", old_task.id)
        if t is None or t.status.state > TaskState.RUNNING:
            return True
        if old_task.node_id:
            n = self.store.get("node", old_task.node_id)
            if n is None or (n.status is not None
                             and n.status.state == NodeState.DOWN):
                return True
        return False

    async def _wait_old_task_stopped(self, old_task,
                                     deadline: Optional[float] = None
                                     ) -> None:
        """Event-driven wait (reference DelayStart's watch on the old
        task/node, restart.go:420): wake on updates to the old task or its
        node rather than polling, bounded by `deadline` (default: one
        old_task_timeout from now)."""
        def relevant(ev):
            from swarmkit_tpu.store.memory import Event

            if not isinstance(ev, Event):
                return False
            return ((ev.kind == "task" and ev.object.id == old_task.id)
                    or (old_task.node_id and ev.kind == "node"
                        and ev.object.id == old_task.node_id))

        watcher = self.store.watch(relevant)
        try:
            # subscribe-then-check: an event between the check and the
            # subscription cannot be missed this way
            if self._old_task_gone(old_task):
                return
            if deadline is None:
                deadline = self.clock.now() + self.old_task_timeout
            timeout = asyncio.ensure_future(
                self.clock.sleep(max(0.0, deadline - self.clock.now())))
            try:
                while not self._old_task_gone(old_task):
                    ev = asyncio.ensure_future(watcher.get())
                    done, _ = await asyncio.wait(
                        {ev, timeout}, return_when=asyncio.FIRST_COMPLETED)
                    if ev not in done:
                        ev.cancel()
                    elif ev.exception() is not None:
                        # watcher torn down under us (WatcherClosed on
                        # store shutdown): no further events can arrive,
                        # so treat it as terminal and start the
                        # replacement instead of re-arming a get() that
                        # fails instantly until the deadline
                        return
                    if timeout in done:
                        return   # waited long enough; start anyway
            finally:
                timeout.cancel()
        finally:
            watcher.close()

    @staticmethod
    def _promote(tx, task_id: str) -> None:
        """reference: StartNow restart.go:487 — any task still desired
        below RUNNING is started; already-started or re-purposed tasks
        are left alone."""
        t = tx.get("task", task_id)
        if t is None or t.desired_state >= TaskState.RUNNING:
            return
        t.desired_state = int(TaskState.RUNNING)
        tx.update(t)

    def cancel_delay(self, task_id: str) -> None:
        timer = self._delays.pop(task_id, None)
        if timer is not None:
            timer.cancel()

    def clear_service_history(self, service_id: str) -> None:
        """reference: ClearServiceHistory restart.go:525 — forget strike
        counts when a service is removed."""
        for slot in [s for s in self._history if s[1] == service_id]:
            del self._history[slot]

    def pending_delays(self) -> int:
        return len(self._delays)

"""Task reaper: garbage-collects dead and REMOVE-desired tasks.

Reference: manager/orchestrator/taskreaper/task_reaper.go — keeps at most
TaskHistoryRetentionLimit dead tasks per slot (tick :234), deletes tasks with
desired_state REMOVE once they reach a terminal state OR while still
unassigned (task_reaper.go:109-111,181: state < ASSIGNED never reaches an
agent, so nothing will ever shut it down — the design/tla/Tasks.tla reaper
exceptions <<new, null>> / <<pending, null>>), and cleans up tasks orphaned
for too long.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import TaskState
from swarmkit_tpu.manager.orchestrator import common
from swarmkit_tpu.store.by import BySlot
from swarmkit_tpu.store.memory import Event, EventCommit, MemoryStore, match, match_commit
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.orchestrator.taskreaper")

DEFAULT_RETENTION = 5  # reference: defaults.Service TaskHistoryRetentionLimit


def _removable(t) -> bool:
    """Reapable outright: desired REMOVE and either already dead or never
    assigned (reference task_reaper.go:181: state < ASSIGNED or
    >= COMPLETE), or a SERVICELESS orphaned task (network-attachment
    tasks have no service to reconcile them; task_reaper.go:174-175)."""
    if t.status.state >= TaskState.ORPHANED and not t.service_id:
        return True
    return t.desired_state == TaskState.REMOVE \
        and (t.status.state < TaskState.ASSIGNED
             or common.in_terminal_state(t))


class TaskReaper:
    def __init__(self, store: MemoryStore, clock: Optional[Clock] = None
                 ) -> None:
        self.store = store
        self.clock = clock or SystemClock()
        self._dirty_slots: set[tuple] = set()
        self._cleanup: set[str] = set()
        self._task: Optional[asyncio.Task] = None
        self._running = False

    def _retention(self) -> int:
        clusters = self.store.find("cluster")
        if clusters:
            orch = clusters[0].spec.orchestration
            if orch is not None:
                # the configured value verbatim: 0 keeps NO history and
                # negative disables cleanup (reference reads the cluster
                # field directly; the dataclass default supplies 5)
                return orch.task_history_retention_limit
        return DEFAULT_RETENTION

    async def start(self) -> None:
        watcher = self.store.watch(match(kind="task"), match_commit)
        # startup scan (reference: taskReaper.Run initial pass)
        for t in self.store.find("task"):
            if _removable(t):
                self._cleanup.add(t.id)
            elif common.in_terminal_state(t) \
                    or t.desired_state > TaskState.RUNNING:
                self._dirty_slots.add(common.slot_tuple(t))
        self._running = True
        self._task = asyncio.get_running_loop().create_task(self._run(watcher))

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _run(self, watcher) -> None:
        try:
            if self._cleanup or self._dirty_slots:
                await self.tick()
            while self._running:
                ev = await watcher.get()
                if isinstance(ev, Event):
                    t = ev.object
                    if ev.action == "remove":
                        continue
                    if ev.action == "create" and t.service_id:
                        # a new task in a slot is when its history can
                        # exceed retention (reference EventCreateTask
                        # dirtying, task_reaper.go:166)
                        self._dirty_slots.add(common.slot_tuple(t))
                    if _removable(t):
                        self._cleanup.add(t.id)
                    elif common.in_terminal_state(t) \
                            or t.desired_state > TaskState.RUNNING:
                        self._dirty_slots.add(common.slot_tuple(t))
                elif isinstance(ev, EventCommit) \
                        and (self._cleanup or self._dirty_slots):
                    await self.tick()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("task reaper crashed")

    async def tick(self) -> None:
        """reference: tick task_reaper.go:234."""
        cleanup, self._cleanup = self._cleanup, set()
        dirty, self._dirty_slots = self._dirty_slots, set()
        retention = self._retention()

        to_delete = set(cleanup)
        for slot in dirty:
            kind, service_id, key = slot
            service = self.store.get("service", service_id)
            if service is None:
                continue   # orchestrator deletes the tasks wholesale
            hist = retention
            rp = service.spec.task.restart
            if rp is not None and rp.max_attempts > 0:
                # keep one more than max_attempts so restart history can
                # be reconstructed after a leader change — this OVERRIDES
                # the cluster retention limit (task_reaper.go:295)
                hist = rp.max_attempts + 1
            if hist < 0:
                # negative retention = never clean history
                # (task_reaper.go:298)
                continue
            if kind == "slot":
                tasks = self.store.find("task", BySlot(service_id, key))
            else:
                from swarmkit_tpu.store.by import ByService
                tasks = [t for t in self.store.find(
                    "task", ByService(service_id)) if t.node_id == key
                    and not t.slot]
            # cleanable history: reached a terminal state (and already
            # processed by the restart path: desired > RUNNING), or will
            # NEVER run — desired terminal while still unassigned, so no
            # agent will ever move it (taskInTerminalState ||
            # taskWillNeverRun, task_reaper.go:344-347)
            dead = sorted(
                (t for t in tasks
                 if (common.in_terminal_state(t)
                     and t.desired_state > TaskState.RUNNING)
                 or (t.status.state < TaskState.ASSIGNED
                     and t.desired_state > TaskState.RUNNING)),
                key=lambda t: t.status.timestamp)
            excess = len(dead) - hist
            for t in dead[:max(0, excess)]:
                to_delete.add(t.id)

        if not to_delete:
            return

        batch = self.store.batch()
        for tid in to_delete:
            def txn(tx, tid=tid):
                if tx.get("task", tid) is not None:
                    tx.delete("task", tid)
            await batch.update(txn)
        await batch.commit()

"""Shared orchestrator task helpers.

Reference: manager/orchestrator/task.go (NewTask, IsTaskDirty,
RestartCondition) and slot.go.
"""

from __future__ import annotations

from typing import Optional

from swarmkit_tpu.api import (
    Annotations, RestartCondition, RestartPolicy, Task, TaskState, TaskStatus,
)
from swarmkit_tpu.utils.identity import new_id


def new_task(cluster, service, slot: int = 0, node_id: str = "") -> Task:
    """reference: orchestrator/task.go NewTask."""
    log_driver = service.spec.task.log_driver
    if log_driver is None and cluster is not None:
        # cluster-wide default (reference: newTask task.go reads
        # cluster.Spec.TaskDefaults.LogDriver)
        log_driver = cluster.spec.task_defaults.log_driver
    t = Task(
        id=new_id(),
        service_id=service.id,
        slot=slot,
        node_id=node_id,
        spec=service.spec.task.copy(),
        service_annotations=service.spec.annotations.copy(),
        status=TaskStatus(state=TaskState.NEW, message="created"),
        desired_state=int(TaskState.RUNNING),
        log_driver=log_driver,
    )
    t.annotations = Annotations(name=f"{service.spec.annotations.name}.{slot or node_id}.{t.id}")
    if service.spec.endpoint is not None:
        from swarmkit_tpu.api.types import Endpoint
        t.endpoint = Endpoint(spec=service.spec.endpoint.copy())
    return t


def is_task_dirty(service, task) -> bool:
    """Spec divergence check (reference: task.go IsTaskDirty)."""
    return task.spec.to_dict() != service.spec.task.to_dict() \
        or (task.endpoint is not None and service.spec.endpoint is not None
            and task.endpoint.spec is not None
            and task.endpoint.spec.to_dict()
            != service.spec.endpoint.to_dict())


def restart_condition(task) -> RestartCondition:
    """reference: task.go RestartCondition (default ANY)."""
    if task.spec.restart is None:
        return RestartCondition.ANY
    return task.spec.restart.condition


def restart_policy(task) -> RestartPolicy:
    return task.spec.restart if task.spec.restart is not None \
        else RestartPolicy()


def slot_tuple(task) -> tuple:
    """Identity of the slot a task occupies (reference: slot.go)."""
    if task.service_id and task.slot:
        return ("slot", task.service_id, task.slot)
    return ("node", task.service_id, task.node_id)


def is_replicated(service) -> bool:
    from swarmkit_tpu.api import Mode
    return service.spec.mode == Mode.REPLICATED


def is_global(service) -> bool:
    from swarmkit_tpu.api import Mode
    return service.spec.mode == Mode.GLOBAL


def in_terminal_state(task) -> bool:
    from swarmkit_tpu.api.types import TERMINAL_STATES
    return task.status.state in TERMINAL_STATES


def runnable(task) -> bool:
    """Task still wants to run (desired <= RUNNING and not failed out)."""
    return task.desired_state <= TaskState.RUNNING \
        and not in_terminal_state(task)


def invalid_node(node) -> bool:
    """Node cannot host running tasks: gone, down, or drained
    (reference: orchestrator.InvalidNode task.go:141-145)."""
    from swarmkit_tpu.api.types import NodeAvailability, NodeState
    return (node is None
            or node.status.state == NodeState.DOWN
            or node.spec.availability == NodeAvailability.DRAIN)

"""Orchestrators: reconcile service specs into tasks.

Reference: manager/orchestrator/ — replicated + global orchestrators, the
restart and update supervisors, task reaper, constraint enforcer, and the
shared task helpers (task.go).
"""

from swarmkit_tpu.manager.orchestrator.common import (
    new_task, is_task_dirty, restart_condition, slot_tuple,
)

__all__ = ["new_task", "is_task_dirty", "restart_condition", "slot_tuple"]

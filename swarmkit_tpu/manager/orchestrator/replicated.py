"""Replicated-service orchestrator.

Reference: manager/orchestrator/replicated/ — watches service/task/node
events, reconciles on commit (replicated.go:47-93): scale up by creating
tasks in free slots, scale down by removing the least-valuable slots
(services.go), restart failed tasks via the restart supervisor (tasks.go),
and hand dirty (spec-changed) slots to the update supervisor.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import Mode, TaskState
from swarmkit_tpu.manager.orchestrator import common
from swarmkit_tpu.manager.orchestrator.restart import RestartSupervisor
from swarmkit_tpu.manager.orchestrator.taskinit import check_tasks
from swarmkit_tpu.manager.orchestrator.update import UpdateSupervisor
from swarmkit_tpu.store.by import ByNode, ByService
from swarmkit_tpu.store.memory import Event, EventCommit, MemoryStore, match, match_commit
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.orchestrator.replicated")


class ReplicatedOrchestrator:
    def __init__(self, store: MemoryStore, clock: Optional[Clock] = None,
                 restart: Optional[RestartSupervisor] = None,
                 updater: Optional[UpdateSupervisor] = None) -> None:
        self.store = store
        self.clock = clock or SystemClock()
        self.restart = restart or RestartSupervisor(store, clock=self.clock)
        self.updater = updater or UpdateSupervisor(store, self.restart,
                                                   clock=self.clock)
        self._dirty_services: set[str] = set()
        self._deleted_services: dict[str, object] = {}
        self._restart_queue: list[tuple] = []
        self._task: Optional[asyncio.Task] = None
        self._running = False

    async def start(self) -> None:
        watcher = self.store.watch(match(kind="service"), match(kind="task"),
                                   match(kind="node"), match_commit)
        # initial reconciliation of everything (reference: init via taskinit)
        for s in self.store.find("service"):
            if s.spec.mode == Mode.REPLICATED:
                self._dirty_services.add(s.id)
        # fix stale tasks from before this orchestrator existed: re-arm
        # parked restart delays, restart tasks that died unwatched
        # (reference: taskinit.CheckTasks via replicated.go Run)
        await check_tasks(self.store, self.restart, Mode.REPLICATED)
        self._running = True
        self._task = asyncio.get_running_loop().create_task(self._run(watcher))

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        await self.updater.stop()
        await self.restart.stop()

    # ------------------------------------------------------------------
    async def _run(self, watcher) -> None:
        try:
            if self._dirty_services:
                await self.tick()
            while self._running:
                ev = await watcher.get()
                self._handle(ev)
                if isinstance(ev, EventCommit) and (
                        self._dirty_services or self._restart_queue
                        or self._deleted_services):
                    await self.tick()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("replicated orchestrator crashed")

    def _handle(self, ev) -> None:
        if not isinstance(ev, Event):
            return
        if ev.kind == "service":
            s = ev.object
            if s.spec.mode != Mode.REPLICATED:
                return
            if ev.action == "remove":
                self._deleted_services[s.id] = s
            else:
                self._dirty_services.add(s.id)
        elif ev.kind == "task":
            t = ev.object
            if not t.service_id:
                return
            if ev.action == "remove":
                self._dirty_services.add(t.service_id)
                return
            # a task reaching a terminal state — or sitting on a node
            # that can no longer host it — may need a restart
            # (reference: handleTaskChange tasks.go:118-146)
            if ev.action == "update" and t.desired_state <= TaskState.RUNNING \
                    and (common.in_terminal_state(t)
                         or (t.node_id and common.invalid_node(
                             self.store.get("node", t.node_id)))):
                self._restart_queue.append(t)
        elif ev.kind == "node":
            # a node going down/drained (or deleted) restarts its tasks
            # elsewhere (reference: handleNodeChange + restartTasksByNodeID
            # tasks.go:85-115; InvalidNode task.go:141)
            n = ev.object
            if ev.action == "remove" or common.invalid_node(n):
                self._queue_node_restarts(n.id)

    def _queue_node_restarts(self, node_id: str) -> None:
        """reference: restartTasksByNodeID tasks.go:85 — every runnable
        replicated task on the node goes through the restart supervisor,
        which shuts it down AND creates its replacement in one txn."""
        for t in self.store.find("task", ByNode(node_id)):
            if t.desired_state <= TaskState.RUNNING and t.service_id:
                self._restart_queue.append(t)

    # ------------------------------------------------------------------
    async def tick(self) -> None:
        deleted, self._deleted_services = self._deleted_services, {}
        for service in deleted.values():
            await self._delete_service_tasks(service)

        restarts, self._restart_queue = self._restart_queue, []
        for task in restarts:
            await self._restart_task(task)

        dirty, self._dirty_services = self._dirty_services, set()
        for sid in dirty:
            service = self.store.get("service", sid)
            if service is not None and service.spec.mode == Mode.REPLICATED:
                await self._reconcile(service)

    async def _delete_service_tasks(self, service) -> None:
        """reference: replicated.go deleteServiceTasks."""
        tasks = self.store.find("task", ByService(service.id))

        def txn(tx):
            for t in tasks:
                cur = tx.get("task", t.id)
                if cur is not None:
                    tx.delete("task", t.id)
        if tasks:
            await self.store.update(txn)
        # forget restart strike counts (reference ClearServiceHistory)
        self.restart.clear_service_history(service.id)

    async def _restart_task(self, task) -> None:
        service = self.store.get("service", task.service_id)
        if service is None or service.spec.mode != Mode.REPLICATED:
            return
        cluster = self._cluster()
        await self.store.update(
            lambda tx: self.restart.restart(tx, cluster, service, task))

    def _cluster(self):
        clusters = self.store.find("cluster")
        return clusters[0] if clusters else None

    async def _reconcile(self, service) -> None:
        """reference: services.go reconcile."""
        tasks = self.store.find("task", ByService(service.id))
        # group live tasks by slot
        slots: dict[int, list] = {}
        for t in tasks:
            if common.runnable(t):
                slots.setdefault(t.slot, []).append(t)
        want = service.spec.replica_count()
        have = len(slots)

        if have < want:
            cluster = self._cluster()
            used = set(slots)
            free = [i for i in range(1, want + len(used) + 1)
                    if i not in used]
            new_tasks = []
            for i in range(want - have):
                new_tasks.append(common.new_task(cluster, service,
                                                 slot=free[i]))

            def txn(tx):
                for t in new_tasks:
                    tx.create(t)
            await self.store.update(txn)
        elif have > want:
            # remove surplus slots, preferring those not yet running
            # (reference: services.go scale-down preferences)
            def sort_key(item):
                slot_num, slot_tasks = item
                running = any(t.status.state == TaskState.RUNNING
                              for t in slot_tasks)
                return (running, slot_num)
            surplus = sorted(slots.items(), key=sort_key)[:have - want]

            def txn(tx):
                for _, slot_tasks in surplus:
                    for t in slot_tasks:
                        cur = tx.get("task", t.id)
                        if cur is None:
                            continue
                        cur.desired_state = int(TaskState.REMOVE)
                        tx.update(cur)
            await self.store.update(txn)

        # dirty slots go to the rolling updater
        live_slots = [s for s in slots.values() if s]
        if any(common.is_task_dirty(service, t)
               for s in live_slots for t in s):
            self.updater.update(self._cluster(), service, live_slots)

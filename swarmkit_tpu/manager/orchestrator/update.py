"""Rolling-update supervisor.

Reference: manager/orchestrator/update/updater.go — one Updater per service
update (Supervisor.Update :50 dedups by service id), with parallelism, delay,
order (stop-first/start-first), monitor window, max_failure_ratio and
failure_action pause/continue/rollback (rollbackUpdate :587).  Progress and
outcome land in service.update_status.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import (
    TaskState, UpdateConfig, UpdateFailureAction, UpdateOrder,
)
from swarmkit_tpu.api.objects import UpdateStatus
from swarmkit_tpu.manager.orchestrator import common
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.orchestrator.update")

# update_status.state values (reference: api UpdateStatus_UpdateState)
UPDATING = "updating"
PAUSED = "paused"
COMPLETED = "completed"
ROLLBACK_STARTED = "rollback_started"
ROLLBACK_PAUSED = "rollback_paused"
ROLLBACK_COMPLETED = "rollback_completed"


class UpdateSupervisor:
    """reference: update.Supervisor updater.go:27."""

    def __init__(self, store: MemoryStore, restart, clock: Optional[Clock] = None
                 ) -> None:
        self.store = store
        self.restart = restart
        self.clock = clock or SystemClock()
        self._updates: dict[str, asyncio.Task] = {}
        self._update_specs: dict[str, object] = {}

    def update(self, cluster, service, slots: list[list]) -> None:
        """Start the updater for a service; a second call with an UNCHANGED
        spec while one is running is a no-op — only a newer spec replaces the
        in-flight updater (reference: Supervisor.Update :50)."""
        # A paused update stays paused until the OPERATOR acts: a new
        # service-update resets update_status (controlapi), which is the
        # only resume path (reference: Updater.Run updater.go:130).
        if service.update_status is not None \
                and service.update_status.state in (PAUSED, ROLLBACK_PAUSED):
            return
        digest = service.spec.to_dict()
        old = self._updates.get(service.id)
        if old is not None and not old.done():
            if self._update_specs.get(service.id) == digest:
                return
            old.cancel()
        dirty = [s for s in slots if any(common.is_task_dirty(service, t)
                                         for t in s)]
        if not dirty:
            return
        self._update_specs[service.id] = digest
        # a spec restored by _rollback arrives flagged ROLLBACK_STARTED: run
        # the pass under the rollback config (reference: updater.go:125)
        rollback = (service.update_status is not None
                    and service.update_status.state == ROLLBACK_STARTED)
        self._updates[service.id] = asyncio.get_running_loop().create_task(
            self._run(cluster, service, slots, rollback=rollback))

    async def stop(self) -> None:
        for t in self._updates.values():
            t.cancel()
        for t in list(self._updates.values()):
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._updates = {}

    # ------------------------------------------------------------------
    async def _run(self, cluster, service, slots: list[list],
                   rollback: bool = False) -> None:
        try:
            await self._run_update(cluster, service, slots, rollback=rollback)
        except asyncio.CancelledError:
            pass
        except Exception:
            log.exception("update of service %s crashed", service.id)
        finally:
            # Only clear our own registration: a cancelled updater must not
            # pop the successor that replaced it (Supervisor.Update :50
            # replaces the map entry before the old goroutine winds down).
            if self._updates.get(service.id) is asyncio.current_task():
                self._updates.pop(service.id, None)
                self._update_specs.pop(service.id, None)

    def _config(self, service, rollback: bool) -> UpdateConfig:
        cfg = service.spec.rollback if rollback else service.spec.update
        return cfg if cfg is not None else UpdateConfig()

    async def _run_update(self, cluster, service, slots: list[list],
                          rollback: bool) -> None:
        cfg = self._config(service, rollback)
        parallelism = cfg.parallelism or len(slots) or 1
        dirty = [s for s in slots
                 if any(common.is_task_dirty(service, t) for t in s)]
        await self._set_status(
            service.id, ROLLBACK_STARTED if rollback else UPDATING,
            "update in progress")

        failures = 0
        total = len(dirty) or 1
        for i in range(0, len(dirty), parallelism):
            batch = dirty[i:i + parallelism]
            results = await asyncio.gather(
                *(self._update_slot(cluster, service, slot, cfg)
                  for slot in batch))
            failures += sum(1 for ok in results if not ok)
            if failures and failures / total > cfg.max_failure_ratio:
                action = cfg.failure_action
                if action == UpdateFailureAction.PAUSE:
                    await self._set_status(
                        service.id, ROLLBACK_PAUSED if rollback else PAUSED,
                        f"update paused after {failures} failures")
                    return
                if action == UpdateFailureAction.ROLLBACK and not rollback:
                    await self._rollback(cluster, service)
                    return
                # CONTINUE: fall through
            if cfg.delay > 0 and i + parallelism < len(dirty):
                await self.clock.sleep(cfg.delay)

        await self._set_status(
            service.id, ROLLBACK_COMPLETED if rollback else COMPLETED,
            "update completed")

    async def _update_slot(self, cluster, service, slot: list,
                           cfg: UpdateConfig) -> bool:
        """Replace one slot's task; True on success
        (reference: updateTask updater.go:411)."""
        # A half-updated slot may already hold a task matching the new
        # spec (an earlier updater died between create and cleanup):
        # finish the slot by removing the others instead of churning the
        # healthy new task (reference worker/useExistingTask
        # updater.go:313-485).
        clean = [t for t in slot if not common.is_task_dirty(service, t)]
        existing = next(
            (t for t in clean if t.desired_state == TaskState.RUNNING),
            None) or next(
            (t for t in clean if t.desired_state < TaskState.RUNNING), None)
        if existing is not None:
            draining: list = []
            reused = False

            def finish(tx):
                nonlocal reused
                draining.clear()
                # the slot snapshot is stale by the time this batch runs:
                # re-validate the candidate INSIDE the transaction — a
                # clean task that died meanwhile must not absorb the slot
                cur_ex = tx.get("task", existing.id)
                if cur_ex is None \
                        or cur_ex.desired_state > TaskState.RUNNING \
                        or common.in_terminal_state(cur_ex):
                    return
                reused = True
                for old in slot:
                    if old.id == existing.id:
                        continue
                    cur = tx.get("task", old.id)
                    if cur is not None \
                            and cur.desired_state <= TaskState.RUNNING:
                        cur.desired_state = int(TaskState.SHUTDOWN)
                        tx.update(cur)
                        if cur.status.state <= TaskState.RUNNING:
                            draining.append(cur)
            await self.store.update(finish)
            if reused:
                if existing.desired_state >= TaskState.RUNNING:
                    return True
                # parked below RUNNING: start it once ALL old tasks drain
                self.restart.delay_start(existing.id, 0.0,
                                         old_tasks=draining)
                return await self._wait_running(existing.id, cfg.monitor)
            # candidate died under us: fall through and create a fresh task

        slot_num = slot[0].slot if slot else 0
        node_id = slot[0].node_id if slot and not slot_num else ""
        new = common.new_task(cluster, service, slot=slot_num,
                              node_id=node_id)

        if cfg.order == UpdateOrder.START_FIRST:
            new.desired_state = int(TaskState.RUNNING)

            def txn(tx):
                tx.create(new)
            await self.store.update(txn)
            started = await self._wait_running(new.id, cfg.monitor)
            if not started:
                # keep the healthy old task: start-first exists precisely so
                # a failed replacement never takes the slot down
                return False

            def stop_old(tx):
                for old in slot:
                    cur = tx.get("task", old.id)
                    if cur is not None \
                            and cur.desired_state <= TaskState.RUNNING:
                        cur.desired_state = int(TaskState.SHUTDOWN)
                        tx.update(cur)
            await self.store.update(stop_old)
            return True
        else:  # STOP_FIRST
            new.desired_state = int(TaskState.READY)

            def txn(tx):
                for old in slot:
                    cur = tx.get("task", old.id)
                    if cur is not None \
                            and cur.desired_state <= TaskState.RUNNING:
                        cur.desired_state = int(TaskState.SHUTDOWN)
                        tx.update(cur)
                tx.create(new)
            await self.store.update(txn)
            await self._wait_shutdown(slot, cfg.monitor)

            def promote(tx):
                cur = tx.get("task", new.id)
                if cur is not None and cur.desired_state == TaskState.READY:
                    cur.desired_state = int(TaskState.RUNNING)
                    tx.update(cur)
            await self.store.update(promote)
            return await self._wait_running(new.id, cfg.monitor)

    async def _wait_running(self, task_id: str, monitor: float) -> bool:
        """Watch the task reach RUNNING (or fail) within the monitor window."""
        deadline = self.clock.now() + (monitor or 5.0)
        while self.clock.now() < deadline:
            t = self.store.get("task", task_id)
            if t is None:
                return False
            if t.status.state == TaskState.RUNNING:
                return True
            if common.in_terminal_state(t):
                return False
            await self.clock.sleep(0.05)
        # window elapsed without failure => treat as success if still moving
        t = self.store.get("task", task_id)
        return t is not None and not common.in_terminal_state(t)

    async def _wait_shutdown(self, slot: list, monitor: float) -> None:
        deadline = self.clock.now() + (monitor or 5.0)
        while self.clock.now() < deadline:
            tasks = [self.store.get("task", t.id) for t in slot]
            if all(t is None or common.in_terminal_state(t) for t in tasks):
                return
            await self.clock.sleep(0.05)

    async def _rollback(self, cluster, service) -> None:
        """reference: rollbackUpdate updater.go:587 — flip the spec back to
        previous_spec and let reconciliation re-run."""
        def txn(tx):
            s = tx.get("service", service.id)
            if s is None or s.previous_spec is None:
                return
            s.spec = s.previous_spec
            s.previous_spec = None
            s.update_status = UpdateStatus(
                state=ROLLBACK_STARTED, started_at=self.clock.now(),
                message="rolling back after update failure")
            tx.update(s)
        await self.store.update(txn)

    async def _set_status(self, service_id: str, state: str, message: str
                          ) -> None:
        def txn(tx):
            s = tx.get("service", service_id)
            if s is None:
                return
            if s.update_status is None:
                s.update_status = UpdateStatus(started_at=self.clock.now())
            s.update_status.state = state
            s.update_status.message = message
            if state in (COMPLETED, ROLLBACK_COMPLETED):
                s.update_status.completed_at = self.clock.now()
            tx.update(s)
        try:
            await self.store.update(txn)
        except Exception:
            log.exception("could not update service %s status", service_id)

"""Global-service orchestrator: one task per eligible node.

Reference: manager/orchestrator/global/global.go — reconcileServices (:253)
creates a task on every READY, non-drained node matching the service's
constraints and shuts down tasks on nodes that stopped qualifying; node
add/remove events trigger reconciliation of every global service.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import Mode, NodeAvailability, NodeState, TaskState
from swarmkit_tpu.manager import constraint as constraint_mod
from swarmkit_tpu.manager.orchestrator import common
from swarmkit_tpu.manager.orchestrator.restart import RestartSupervisor
from swarmkit_tpu.manager.orchestrator.taskinit import check_tasks
from swarmkit_tpu.manager.orchestrator.update import UpdateSupervisor
from swarmkit_tpu.store.by import ByService
from swarmkit_tpu.store.memory import Event, EventCommit, MemoryStore, match, match_commit
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.orchestrator.global")


def _node_eligible(service, node) -> bool:
    if node.status.state != NodeState.READY:
        return False
    if node.spec.availability in (NodeAvailability.DRAIN,):
        return False
    p = service.spec.task.placement
    if p is not None and p.constraints:
        try:
            cons = constraint_mod.parse(p.constraints)
        except constraint_mod.InvalidConstraint:
            return False
        if not constraint_mod.node_matches(cons, node):
            return False
    return True


class GlobalOrchestrator:
    def __init__(self, store: MemoryStore, clock: Optional[Clock] = None,
                 restart: Optional[RestartSupervisor] = None,
                 updater: Optional[UpdateSupervisor] = None) -> None:
        self.store = store
        self.clock = clock or SystemClock()
        self.restart = restart or RestartSupervisor(store, clock=self.clock)
        self.updater = updater or UpdateSupervisor(store, self.restart,
                                                   clock=self.clock)
        self._dirty: set[str] = set()
        self._deleted: dict[str, object] = {}
        self._restart_queue: list = []
        self._nodes_changed = False
        self._task: Optional[asyncio.Task] = None
        self._running = False

    async def start(self) -> None:
        watcher = self.store.watch(match(kind="service"), match(kind="task"),
                                   match(kind="node"), match_commit)
        for s in self.store.find("service"):
            if s.spec.mode == Mode.GLOBAL:
                self._dirty.add(s.id)
        # fix stale tasks from before this orchestrator existed
        # (reference: taskinit.CheckTasks via global.go Run)
        await check_tasks(self.store, self.restart, Mode.GLOBAL)
        self._running = True
        self._task = asyncio.get_running_loop().create_task(self._run(watcher))

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        await self.updater.stop()
        await self.restart.stop()

    async def _run(self, watcher) -> None:
        try:
            if self._dirty:
                await self.tick()
            while self._running:
                ev = await watcher.get()
                self._handle(ev)
                if isinstance(ev, EventCommit) and (
                        self._dirty or self._deleted or self._restart_queue):
                    await self.tick()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("global orchestrator crashed")

    def _handle(self, ev) -> None:
        if not isinstance(ev, Event):
            return
        if ev.kind == "service":
            if ev.object.spec.mode != Mode.GLOBAL:
                return
            if ev.action == "remove":
                self._deleted[ev.object.id] = ev.object
            else:
                self._dirty.add(ev.object.id)
        elif ev.kind == "node":
            # any node change can affect every global service
            for s in self.store.find("service"):
                if s.spec.mode == Mode.GLOBAL:
                    self._dirty.add(s.id)
        elif ev.kind == "task":
            t = ev.object
            if not t.service_id:
                return
            if ev.action == "remove":
                self._dirty.add(t.service_id)
            elif ev.action == "update" and common.in_terminal_state(t) \
                    and t.desired_state <= TaskState.RUNNING:
                self._restart_queue.append(t)

    async def tick(self) -> None:
        deleted, self._deleted = self._deleted, {}
        for service in deleted.values():
            tasks = self.store.find("task", ByService(service.id))
            if tasks:
                def txn(tx, tasks=tasks):
                    for t in tasks:
                        if tx.get("task", t.id) is not None:
                            tx.delete("task", t.id)
                await self.store.update(txn)
            self.restart.clear_service_history(service.id)

        restarts, self._restart_queue = self._restart_queue, []
        for task in restarts:
            service = self.store.get("service", task.service_id)
            if service is None or service.spec.mode != Mode.GLOBAL:
                continue
            cluster = self._cluster()
            await self.store.update(
                lambda tx, s=service, t=task:
                self.restart.restart(tx, cluster, s, t))

        dirty, self._dirty = self._dirty, set()
        for sid in dirty:
            service = self.store.get("service", sid)
            if service is not None and service.spec.mode == Mode.GLOBAL:
                await self._reconcile(service)

    def _cluster(self):
        clusters = self.store.find("cluster")
        return clusters[0] if clusters else None

    async def _reconcile(self, service) -> None:
        """reference: reconcileServices global.go:253."""
        nodes = self.store.find("node")
        eligible = {n.id for n in nodes if _node_eligible(service, n)}
        tasks = self.store.find("task", ByService(service.id))
        by_node: dict[str, list] = {}
        for t in tasks:
            if common.runnable(t):
                by_node.setdefault(t.node_id, []).append(t)

        cluster = self._cluster()
        to_create = [nid for nid in eligible if nid not in by_node]
        to_shutdown = [t for nid, ts in by_node.items()
                       if nid not in eligible for t in ts]
        if to_create or to_shutdown:
            def txn(tx):
                for nid in to_create:
                    tx.create(common.new_task(cluster, service, slot=0,
                                              node_id=nid))
                for t in to_shutdown:
                    cur = tx.get("task", t.id)
                    if cur is not None \
                            and cur.desired_state <= TaskState.RUNNING:
                        cur.desired_state = int(TaskState.SHUTDOWN)
                        tx.update(cur)
            await self.store.update(txn)

        # spec changes roll out via the update supervisor, one "slot" per
        # node (reference: global.go reconcileServices → g.updater.Update)
        node_slots = [ts for nid, ts in by_node.items() if nid in eligible]
        if any(common.is_task_dirty(service, t)
               for ts in node_slots for t in ts):
            self.updater.update(cluster, service, node_slots)

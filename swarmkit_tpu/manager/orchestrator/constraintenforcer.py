"""Constraint enforcer: evicts tasks from nodes that stop satisfying their
placement constraints or resource reservations.

Reference: manager/orchestrator/constraintenforcer/constraint_enforcer.go —
watches node updates, rejectNoncompliantTasks (:65) shuts down running tasks
whose constraints no longer match the changed node.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from swarmkit_tpu.api import NodeAvailability, TaskState
from swarmkit_tpu.manager import constraint as constraint_mod
from swarmkit_tpu.manager.orchestrator import common
from swarmkit_tpu.store.by import ByNode
from swarmkit_tpu.store.memory import Event, MemoryStore, match
from swarmkit_tpu.utils.clock import Clock, SystemClock

log = logging.getLogger("swarmkit_tpu.orchestrator.constraintenforcer")


class ConstraintEnforcer:
    def __init__(self, store: MemoryStore, clock: Optional[Clock] = None
                 ) -> None:
        self.store = store
        self.clock = clock or SystemClock()
        self._task: Optional[asyncio.Task] = None
        self._running = False

    async def start(self) -> None:
        watcher = self.store.watch(match(kind="node", action="update"))
        self._running = True
        self._task = asyncio.get_running_loop().create_task(self._run(watcher))

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def _run(self, watcher) -> None:
        try:
            while self._running:
                ev = await watcher.get()
                if isinstance(ev, Event):
                    await self.reject_noncompliant(ev.object)
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("constraint enforcer crashed")

    async def reject_noncompliant(self, node) -> None:
        """reference: rejectNoncompliantTasks constraint_enforcer.go:65."""
        # Drain is the ORCHESTRATOR's job (its restart supervisor shuts
        # down AND replaces each task atomically); pause means leave the
        # tasks alone.  The enforcer only polices ACTIVE nodes
        # (reference: constraint_enforcer.go:66-72).
        if node.spec.availability != NodeAvailability.ACTIVE:
            return
        tasks = self.store.find("task", ByNode(node.id))
        to_shutdown = []
        # remaining capacity for the resource-fit pass (the reference
        # recomputes available resources and evicts tasks whose
        # reservations no longer fit a shrunk node)
        cpus = mem = 0
        generic: dict[str, int] = {}
        if node.description is not None \
                and node.description.resources is not None:
            cpus = node.description.resources.nano_cpus
            mem = node.description.resources.memory_bytes
            generic = dict(node.description.resources.generic)
        for t in sorted(tasks, key=lambda t: t.id):
            if t.desired_state > TaskState.RUNNING \
                    or common.in_terminal_state(t):
                continue
            p = t.spec.placement
            if p is not None and p.constraints:
                try:
                    cons = constraint_mod.parse(p.constraints)
                except constraint_mod.InvalidConstraint:
                    continue
                if not constraint_mod.node_matches(cons, node):
                    to_shutdown.append(t)
                    continue
            res = t.spec.resources
            reserved = res.reservations if res is not None else None
            if reserved is not None:
                over_generic = any(generic.get(k, 0) < v
                                   for k, v in reserved.generic.items())
                if reserved.nano_cpus > cpus or reserved.memory_bytes > mem \
                        or over_generic:
                    to_shutdown.append(t)
                    continue
                cpus -= reserved.nano_cpus
                mem -= reserved.memory_bytes
                for k, v in reserved.generic.items():
                    generic[k] = generic.get(k, 0) - v
        if not to_shutdown:
            return

        def txn(tx):
            for t in to_shutdown:
                cur = tx.get("task", t.id)
                if cur is not None \
                        and cur.desired_state <= TaskState.RUNNING:
                    cur.desired_state = int(TaskState.SHUTDOWN)
                    cur.status.message = \
                        "node no longer satisfies task constraints"
                    tx.update(cur)
        await self.store.update(txn)

"""Startup task fixing shared by orchestrators.

Reference: manager/orchestrator/taskinit/init.go CheckTasks — after a leader
change, re-arm delayed restarts for tasks parked in READY and restart tasks
that died while no orchestrator was watching.
"""

from __future__ import annotations

from swarmkit_tpu.api import Mode, TaskState
from swarmkit_tpu.manager.orchestrator import common


async def check_tasks(store, restart_supervisor, mode: Mode) -> None:
    dead: list = []
    parked: list = []
    by_slot: dict[tuple, list] = {}
    for t in store.find("task"):
        if not t.service_id:
            continue
        service = store.get("service", t.service_id)
        if service is None or service.spec.mode != mode:
            continue
        by_slot.setdefault(common.slot_tuple(t), []).append(t)
        if common.in_terminal_state(t) \
                and t.desired_state <= TaskState.RUNNING:
            dead.append((service, t))
        elif t.desired_state == TaskState.READY \
                and t.status.state < TaskState.RUNNING:
            parked.append(t)

    clusters = store.find("cluster")
    cluster = clusters[0] if clusters else None
    for service, task in dead:
        await store.update(
            lambda tx, s=service, t=task:
            restart_supervisor.restart(tx, cluster, s, t))
    for t in parked:
        policy = common.restart_policy(t)
        # credit time already waited before the failover: the delay runs
        # from the predecessor's failure timestamp, not from re-arm
        # (reference init.go:74-87 restartTime arithmetic)
        delay = policy.delay
        if delay > 0 and t.status.timestamp:
            elapsed = restart_supervisor.clock.now() - t.status.timestamp
            delay = max(0.0, delay - elapsed)
        # unlike the reference (init.go:94 passes a nil oldTask), keep the
        # old-task wait across failovers: the slot's predecessor — still
        # draining toward SHUTDOWN — is recoverable from the slot itself
        old = next((o for o in by_slot.get(common.slot_tuple(t), [])
                    if o.id != t.id
                    and o.desired_state > TaskState.RUNNING
                    and o.status.state <= TaskState.RUNNING), None)
        restart_supervisor.delay_start(t.id, delay, old_task=old)

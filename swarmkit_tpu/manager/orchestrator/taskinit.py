"""Startup task fixing shared by orchestrators.

Reference: manager/orchestrator/taskinit/init.go CheckTasks — after a leader
change, re-arm delayed restarts for tasks parked in READY and restart tasks
that died while no orchestrator was watching.
"""

from __future__ import annotations

from swarmkit_tpu.api import Mode, TaskState
from swarmkit_tpu.manager.orchestrator import common


async def check_tasks(store, restart_supervisor, mode: Mode) -> None:
    dead: list = []
    parked: list = []
    for t in store.find("task"):
        if not t.service_id:
            continue
        service = store.get("service", t.service_id)
        if service is None or service.spec.mode != mode:
            continue
        if common.in_terminal_state(t) \
                and t.desired_state <= TaskState.RUNNING:
            dead.append((service, t))
        elif t.desired_state == TaskState.READY \
                and t.status.state < TaskState.RUNNING:
            parked.append(t)

    clusters = store.find("cluster")
    cluster = clusters[0] if clusters else None
    for service, task in dead:
        await store.update(
            lambda tx, s=service, t=task:
            restart_supervisor.restart(tx, cluster, s, t))
    for t in parked:
        policy = common.restart_policy(t)
        restart_supervisor.delay_start(t.id, policy.delay)

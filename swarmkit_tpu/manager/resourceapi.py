"""Agent-facing network-attachment task API.

Reference: manager/resourceapi/allocator.go (:124) — AttachNetwork creates
an attachment task bound to a node+network (used by the engine for
`docker run --network <swarm net>`), DetachNetwork removes it.
"""

from __future__ import annotations

from swarmkit_tpu.api import Task, TaskState, TaskStatus
from swarmkit_tpu.api.specs import TaskSpec
from swarmkit_tpu.store.memory import MemoryStore
from swarmkit_tpu.utils.identity import new_id


class ResourceError(Exception):
    pass


class ResourceApi:
    def __init__(self, store: MemoryStore, clock=None) -> None:
        self.store = store
        self.clock = clock

    async def attach_network(self, node_id: str, network_id: str,
                             container_id: str = "") -> str:
        task = Task(
            id=new_id(), node_id=node_id,
            spec=TaskSpec(networks=[network_id]),
            status=TaskStatus(state=TaskState.NEW,
                              message="network attachment requested"),
            desired_state=int(TaskState.RUNNING))
        task.annotations.labels["attachment-container"] = container_id

        def txn(tx):
            # existence checks inside the txn so a concurrent
            # remove_network/remove_node cannot slip between check+commit
            if tx.get("network", network_id) is None:
                raise ResourceError(f"network {network_id} not found")
            if tx.get("node", node_id) is None:
                raise ResourceError(f"node {node_id} not found")
            tx.create(task)
        await self.store.update(txn)
        return task.id

    async def detach_network(self, attachment_id: str) -> None:
        def txn(tx):
            t = tx.get("task", attachment_id)
            if t is None:
                raise ResourceError(f"attachment {attachment_id} not found")
            tx.delete("task", attachment_id)
        await self.store.update(txn)
